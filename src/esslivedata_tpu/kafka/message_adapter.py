"""Wire -> domain adapters with routing combinators.

Parity with reference ``kafka/message_adapter.py``: one adapter per wire
schema (KafkaToEv44Adapter:196, KafkaToDa00Adapter:238, KafkaToF144Adapter:
255, KafkaToAd00Adapter:457, monitor fast path:360, run-control:325,
commands:484), combinators (ChainedAdapter:503, RouteBySchemaAdapter:516,
RouteByTopicAdapter:539) and ``AdaptingMessageSource`` (:562) with
*per-message* error containment — one hostile payload must never kill the
service (exercised by the hostile-wire tests, SURVEY.md section 4.3).

Message timestamps follow the reference convention: ev44 uses
``reference_time[-1]``; f144/da00/ad00 use their payload timestamp.
"""

from __future__ import annotations

import json
import logging
import os
import time
from collections.abc import Mapping, Sequence
from dataclasses import dataclass
from typing import Any, Protocol, runtime_checkable

import numpy as np

from ..config.workflow_spec import WorkflowConfig
from ..core.message import Message, RunStart, RunStop, StreamId, StreamKind
from ..core.timestamp import Timestamp
from ..telemetry.e2e import observe_stage
from ..telemetry.instruments import (
    DECODE_BATCH_SIZE,
    DECODE_BYTES,
    DECODE_ERRORS,
)
from ..preprocessors.event_data import (
    DetectorEvents,
    EventChunkRef,
    MonitorEvents,
)
from ..preprocessors.to_nxlog import LogData
from . import wire
from .da00_compat import da00_to_dataarray
from .source import KafkaMessage
from .stream_mapping import (
    MERGED_DETECTOR_STREAM,
    InputStreamKey,
    StreamMapping,
)

#: Stream kinds whose message timestamp is a production time, making
#: wall-clock-minus-timestamp a meaningful producer lag.
_LAG_TRACKED_KINDS = frozenset(
    {
        StreamKind.DETECTOR_EVENTS,
        StreamKind.MONITOR_EVENTS,
        StreamKind.MONITOR_COUNTS,
        StreamKind.AREA_DETECTOR,
        StreamKind.LOG,
        StreamKind.DEVICE,
    }
)

__all__ = [
    "AdaptFailure",
    "AdaptingMessageSource",
    "ChainedAdapter",
    "CommandsAdapter",
    "KafkaToAd00Adapter",
    "KafkaToDa00Adapter",
    "KafkaToDetectorEventsAdapter",
    "KafkaToF144Adapter",
    "KafkaToMonitorEventsAdapter",
    "KafkaToRunControlAdapter",
    "MessageAdapter",
    "NullAdapter",
    "RouteBySchemaAdapter",
    "RouteByTopicAdapter",
]

logger = logging.getLogger(__name__)


@runtime_checkable
class MessageAdapter(Protocol):
    def adapt(self, message: KafkaMessage) -> Message | list[Message] | None: ...


class UnroutedError(KeyError):
    """No route/stream mapping for a message."""


@dataclass(slots=True)
class AdaptFailure:
    """Batch-adapt contract's per-message failure slot (ADR 0125).

    ``adapt_batch(raws)`` returns a list aligned 1:1 with its input
    where each entry is ``Message | list[Message] | None`` (the
    ``adapt`` result forms) or an ``AdaptFailure`` wrapping the
    exception that message raised — quarantine without poisoning the
    poll. ``AdaptingMessageSource`` folds failures into the same
    containment accounting as the per-message path (``UnroutedError``
    inside counts as unrouted, anything else as an adapt error).
    ``schema`` is the wire schema when known, for the
    ``livedata_decode_errors_total{schema}`` label.
    """

    error: Exception
    schema: str = ""


def _env_batch_decode() -> bool:
    """The LIVEDATA_BATCH_DECODE rollout gate (ADR 0125), resolved at
    adapter construction — same env-as-plumbing convention as
    LIVEDATA_PIPELINE. Default off: the per-message path stays the
    reference until the flag opts a service in."""
    return os.environ.get("LIVEDATA_BATCH_DECODE", "").strip().lower() in (
        "1",
        "true",
        "yes",
        "on",
    )


def _schema_of(raw) -> str:
    """Best-effort schema label of a raw message (error accounting)."""
    try:
        return wire.get_schema(raw.value())
    except Exception:
        return ""


def _adapt_one(adapter, raw):
    """One message through ``adapt`` with failures reified in-band —
    the per-adapter fallback the batch-adapt combinators build on."""
    try:
        return adapter.adapt(raw)
    except Exception as err:
        return AdaptFailure(error=err, schema=_schema_of(raw))


class NullAdapter:
    """Deliberate drop: the schema is known, expected on the topic, and
    carries nothing we consume (reference: kafka/message_adapter.py:130).

    Returning None (instead of raising UnroutedError) keeps expected
    traffic — e.g. EPICS alarm/connection chatter interleaved with f144
    on forwarder log topics — out of the unrouted-anomaly counter.
    """

    def adapt(self, message: KafkaMessage) -> None:
        return None


def _resolve(
    lut: Mapping[InputStreamKey, str], topic: str, source: str
) -> str | None:
    return lut.get(InputStreamKey(topic=topic, source_name=source))


class KafkaToDetectorEventsAdapter:
    """ev44 -> Message[DetectorEvents] with (topic, source) -> stream name.

    Under the batch decode gate (``batch_wire`` / LIVEDATA_BATCH_DECODE,
    ADR 0125) the payload becomes an :class:`EventChunkRef` over a
    single header walk — no payload ndarrays are decoded here; the
    accumulator lands them straight into a decode arena. Timestamps and
    routing come from the same header fields either way, so window
    membership (MessageBatcher) is byte-identical across modes.
    """

    def __init__(
        self,
        mapping: StreamMapping,
        *,
        merge_detectors: bool = False,
        batch_wire: bool | None = None,
    ):
        self._mapping = mapping
        self._merge = merge_detectors
        self._batch = (
            _env_batch_decode() if batch_wire is None else bool(batch_wire)
        )
        #: StreamId interning: one frozen StreamId per stream name
        #: instead of a fresh dataclass per message (the detector topic
        #: set is small and fixed; this is a per-message allocation on
        #: the consume hot path either decode mode pays).
        self._sids: dict[str, StreamId] = {}

    def _stream(self, name: str) -> StreamId:
        sid = self._sids.get(name)
        if sid is None:
            sid = self._sids[name] = StreamId(
                kind=StreamKind.DETECTOR_EVENTS, name=name
            )
        return sid

    def adapt(self, message: KafkaMessage) -> Message | None:
        if self._batch:
            v = wire.walk_ev44(message.value())
            name = _resolve(
                self._mapping.detectors, message.topic(), v.source_name
            )
            if name is None:
                return None
            if self._merge:
                name = MERGED_DETECTOR_STREAM
            ts = (
                Timestamp.from_ns(v.reference_time_ns)
                if v.reference_time_ns is not None
                else Timestamp.now()
            )
            return Message(
                timestamp=ts,
                stream=self._stream(name),
                value=EventChunkRef(view=v),
            )
        ev = wire.decode_ev44(message.value())
        name = _resolve(self._mapping.detectors, message.topic(), ev.source_name)
        if name is None:
            return None
        if self._merge:
            # All banks onto one logical stream (bifrost pattern).
            name = MERGED_DETECTOR_STREAM
        ts = (
            Timestamp.from_ns(int(ev.reference_time[-1]))
            if ev.reference_time.size
            else Timestamp.now()
        )
        return Message(
            timestamp=ts,
            stream=self._stream(name),
            value=DetectorEvents(
                pixel_id=ev.pixel_id,
                time_of_arrival=ev.time_of_flight.astype(np.float32),
            ),
        )

    def adapt_batch(self, raws: Sequence[KafkaMessage]) -> list:
        """Whole-poll form (see :class:`AdaptFailure`): one header walk
        per message, malformed wire quarantined in-band."""
        out = []
        for raw in raws:
            try:
                out.append(self.adapt(raw))
            except wire.WireError as err:
                out.append(AdaptFailure(error=err, schema="ev44"))
            except Exception as err:
                out.append(AdaptFailure(error=err, schema=_schema_of(raw)))
        return out


class KafkaToMonitorEventsAdapter:
    """ev44 fast path for monitors: skips the pixel_id field entirely
    (reference message_adapter.py:360) — EXCEPT for monitors registered
    as pixellated (reference instrument.py:401), whose per-pixel event
    ids are meaningful and ride through as a DetectorEvents payload so a
    2-D monitor view can consume them. The stream kind stays
    MONITOR_EVENTS either way (routing and job dispatch are by kind +
    name; the payload type carries the pixel ids)."""

    def __init__(
        self, mapping: StreamMapping, *, batch_wire: bool | None = None
    ):
        self._mapping = mapping
        self._batch = (
            _env_batch_decode() if batch_wire is None else bool(batch_wire)
        )
        self._sids: dict[str, StreamId] = {}  # see detector adapter

    def _stream(self, name: str) -> StreamId:
        sid = self._sids.get(name)
        if sid is None:
            sid = self._sids[name] = StreamId(
                kind=StreamKind.MONITOR_EVENTS, name=name
            )
        return sid

    def adapt(self, message: KafkaMessage) -> Message | None:
        if self._batch:
            v = wire.walk_ev44(message.value())
            name = _resolve(
                self._mapping.monitors, message.topic(), v.source_name
            )
            if name is None:
                return None
            ts = (
                Timestamp.from_ns(v.reference_time_ns)
                if v.reference_time_ns is not None
                else Timestamp.now()
            )
            # Same routing decision as the eager branch below, off the
            # header counts alone: pixellated + consistent ids ride as a
            # detector-style chunk; everything else (incl. mismatched or
            # absent ids) takes the pixel-less monitor semantics.
            pixellated = (
                name in self._mapping.pixellated_monitors
                and v.n_pid == v.n_tof
                and v.n_pid > 0
            )
            return Message(
                timestamp=ts,
                stream=self._stream(name),
                value=EventChunkRef(view=v, monitor=not pixellated),
            )
        ev = wire.decode_ev44(message.value())
        name = _resolve(self._mapping.monitors, message.topic(), ev.source_name)
        if name is None:
            return None
        ts = (
            Timestamp.from_ns(int(ev.reference_time[-1]))
            if ev.reference_time.size
            else Timestamp.now()
        )
        if (
            name in self._mapping.pixellated_monitors
            and ev.pixel_id.size == ev.time_of_flight.size
            and ev.pixel_id.size > 0
        ):
            value = DetectorEvents(
                pixel_id=ev.pixel_id,
                time_of_arrival=ev.time_of_flight.astype(np.float32),
            )
        else:
            # Plain monitors — and pixellated ones whose producer omitted
            # ids (standard monitor ev44 carries an empty pixel_id
            # vector): the id-skipping fast path. An empty-id message
            # must NOT become DetectorEvents, or staging would size the
            # append by len(pixel_id)=0 and silently drop every event.
            value = MonitorEvents(
                time_of_arrival=ev.time_of_flight.astype(np.float32)
            )
        return Message(
            timestamp=ts,
            stream=self._stream(name),
            value=value,
        )

    def adapt_batch(self, raws: Sequence[KafkaMessage]) -> list:
        """Whole-poll form (see :class:`AdaptFailure`)."""
        out = []
        for raw in raws:
            try:
                out.append(self.adapt(raw))
            except wire.WireError as err:
                out.append(AdaptFailure(error=err, schema="ev44"))
            except Exception as err:
                out.append(AdaptFailure(error=err, schema=_schema_of(raw)))
        return out


class KafkaToDa00Adapter:
    """da00 -> Message[DataArray]; also used for histogram-mode monitors."""

    def __init__(
        self,
        mapping: StreamMapping,
        *,
        lut: str = "monitors",
        kind: StreamKind = StreamKind.MONITOR_COUNTS,
    ):
        self._mapping = mapping
        self._lut_name = lut
        self._kind = kind

    def adapt(self, message: KafkaMessage) -> Message | None:
        da00 = wire.decode_da00(message.value())
        lut = getattr(self._mapping, self._lut_name)
        name = _resolve(lut, message.topic(), da00.source_name)
        if name is None:
            return None
        da = da00_to_dataarray(da00.variables, name=da00.source_name)
        return Message(
            timestamp=Timestamp.from_ns(da00.timestamp_ns),
            stream=StreamId(kind=self._kind, name=name),
            value=da,
        )


class KafkaToF144Adapter:
    """f144 -> Message[LogData]."""

    def __init__(self, mapping: StreamMapping):
        self._mapping = mapping

    def adapt(self, message: KafkaMessage) -> Message | None:
        f = wire.decode_f144(message.value())
        name = _resolve(self._mapping.logs, message.topic(), f.source_name)
        if name is None:
            name = f.source_name  # logs default to source name (open set)
        value = f.value if f.value.size != 1 else f.value[0]
        return Message(
            timestamp=Timestamp.from_ns(f.timestamp_ns),
            stream=StreamId(kind=StreamKind.LOG, name=name),
            value=LogData(time=f.timestamp_ns, value=value),
        )


class KafkaToAd00Adapter:
    """ad00 -> Message[DataArray] (2-D camera frame)."""

    def __init__(self, mapping: StreamMapping):
        self._mapping = mapping

    def adapt(self, message: KafkaMessage) -> Message | None:
        img = wire.decode_ad00(message.value())
        name = _resolve(
            self._mapping.area_detectors, message.topic(), img.source_name
        )
        if name is None:
            return None
        from ..utils.labeled import DataArray, Variable

        if img.data.ndim != 2:
            raise wire.WireError(f"ad00 image must be 2-D, got {img.data.shape}")
        da = DataArray(
            Variable(img.data, ("y", "x"), "counts"), name=img.source_name
        )
        return Message(
            timestamp=Timestamp.from_ns(img.timestamp_ns),
            stream=StreamId(kind=StreamKind.AREA_DETECTOR, name=name),
            value=da,
        )


class KafkaToRunControlAdapter:
    """pl72/6s4t -> Message[RunStart|RunStop]."""

    def adapt(self, message: KafkaMessage) -> Message | None:
        buf = message.value()
        schema = wire.get_schema(buf)
        if schema == "pl72":
            start = wire.decode_pl72(buf)
            return Message(
                timestamp=Timestamp.from_ns(start.start_time_ns),
                stream=StreamId(kind=StreamKind.RUN_CONTROL, name=""),
                value=RunStart(
                    run_name=start.run_name,
                    start_time=Timestamp.from_ns(start.start_time_ns),
                    stop_time=(
                        Timestamp.from_ns(start.stop_time_ns)
                        if start.stop_time_ns
                        else None
                    ),
                ),
            )
        if schema == "6s4t":
            stop = wire.decode_6s4t(buf)
            return Message(
                timestamp=Timestamp.from_ns(stop.stop_time_ns),
                stream=StreamId(kind=StreamKind.RUN_CONTROL, name=""),
                value=RunStop(
                    run_name=stop.run_name,
                    stop_time=Timestamp.from_ns(stop.stop_time_ns),
                ),
            )
        raise wire.WireError(f"Unexpected run-control schema {schema!r}")


class CommandsAdapter:
    """JSON commands topic -> Message[WorkflowConfig | dict].

    Payload: {"kind": "start_job", "config": {...WorkflowConfig...}} or
    {"kind": "job_command", "command": "stop"|"remove"|"reset", "job_id":
    {...}} (the job-command model lives in core/job_manager)."""

    def adapt(self, message: KafkaMessage) -> Message | None:
        payload = json.loads(message.value().decode("utf-8"))
        kind = payload.get("kind")
        if kind == "start_job":
            value: Any = WorkflowConfig.model_validate(payload["config"])
        elif kind in ("job_command", "roi_update"):
            value = payload
        else:
            raise ValueError(f"Unknown command kind {kind!r}")
        return Message(
            timestamp=Timestamp.now(),
            stream=StreamId(kind=StreamKind.LIVEDATA_COMMANDS, name=""),
            value=value,
        )


class ChainedAdapter:
    def __init__(self, first: MessageAdapter, second: MessageAdapter) -> None:
        self._first = first
        self._second = second

    def adapt(self, message):
        mid = self._first.adapt(message)
        if mid is None:
            return None
        return self._second.adapt(mid)


class RouteBySchemaAdapter:
    """Dispatch on the flatbuffer file identifier."""

    def __init__(self, routes: Mapping[str, MessageAdapter]) -> None:
        self._routes = dict(routes)

    def adapt(self, message: KafkaMessage):
        schema = wire.get_schema(message.value())
        adapter = self._routes.get(schema)
        if adapter is None:
            raise UnroutedError(f"No adapter for schema {schema!r}")
        return adapter.adapt(message)

    def adapt_batch(self, raws: Sequence[KafkaMessage]) -> list:
        """Whole-poll dispatch: consecutive same-schema runs go down to
        the route's own ``adapt_batch`` when it has one (the ev44
        adapters' single-pass loop), one at a time otherwise; an
        unreadable identifier or unknown schema quarantines that message
        alone (:class:`AdaptFailure`)."""
        keys: list[str | AdaptFailure] = []
        for raw in raws:
            try:
                keys.append(wire.get_schema(raw.value()))
            except Exception as err:
                keys.append(AdaptFailure(error=err))
        out: list = [None] * len(raws)
        i, n = 0, len(raws)
        while i < n:
            key = keys[i]
            if isinstance(key, AdaptFailure):
                out[i] = key
                i += 1
                continue
            j = i
            while j < n and keys[j] == key:
                j += 1
            adapter = self._routes.get(key)
            if adapter is None:
                for k in range(i, j):
                    out[k] = AdaptFailure(
                        error=UnroutedError(f"No adapter for schema {key!r}"),
                        schema=key,
                    )
            else:
                out[i:j] = _adapt_run(adapter, raws[i:j])
            i = j
        return out


class RouteByTopicAdapter:
    """Dispatch on the Kafka topic."""

    def __init__(self, routes: Mapping[str, MessageAdapter]) -> None:
        self._routes = dict(routes)

    @property
    def topics(self) -> list[str]:
        return sorted(self._routes)

    def adapt(self, message: KafkaMessage):
        adapter = self._routes.get(message.topic())
        if adapter is None:
            raise UnroutedError(f"No adapter for topic {message.topic()!r}")
        return adapter.adapt(message)

    def adapt_batch(self, raws: Sequence[KafkaMessage]) -> list:
        """Whole-poll dispatch on topic runs (a consumer poll drains
        partitions in topic runs, so grouping is near-free); each run
        goes to its route's ``adapt_batch`` when present."""
        out: list = [None] * len(raws)
        i, n = 0, len(raws)
        while i < n:
            topic = raws[i].topic()
            j = i
            while j < n and raws[j].topic() == topic:
                j += 1
            adapter = self._routes.get(topic)
            if adapter is None:
                for k in range(i, j):
                    out[k] = AdaptFailure(
                        error=UnroutedError(f"No adapter for topic {topic!r}")
                    )
            else:
                out[i:j] = _adapt_run(adapter, raws[i:j])
            i = j
        return out


def _adapt_run(adapter, raws: Sequence[KafkaMessage]) -> list:
    """One homogeneous run through an adapter's batch form when it has
    one, else per-message with in-band failures."""
    sub = getattr(adapter, "adapt_batch", None)
    if sub is not None:
        return sub(raws)
    return [_adapt_one(adapter, raw) for raw in raws]


class AdaptingMessageSource:
    """Source combinator: raw KafkaMessages -> domain Messages with
    per-message error containment and drop accounting."""

    def __init__(
        self,
        source,
        adapter: MessageAdapter,
        *,
        raise_on_error: bool = False,
        stream_counter=None,
    ) -> None:
        self._source = source
        self._adapter = adapter
        self._raise = raise_on_error
        self._counter = stream_counter
        self.error_count = 0
        self.unrouted_count = 0

    @staticmethod
    def _raw_source_name(raw) -> str:
        """Best-effort source identity of an unmapped raw message: the Kafka
        key when present (ECDC keys messages by source), else unknown."""
        key = getattr(raw, "key", None)
        if callable(key):
            k = key()
            if k:
                return k.decode(errors="replace") if isinstance(k, bytes) else str(k)
        return "<unknown>"

    def _count(self, raw, adapted) -> None:
        """Fold one mapped/unmapped/dropped message into the StreamCounter
        (drained by the processor on the 30 s metrics rollover)."""
        topic = getattr(raw, "topic", lambda: "?")()
        if adapted is None:
            # Deliberately dropped (e.g. unsubscribed source on a routed
            # topic): counted under its raw source identity so the operator
            # can see what is being filtered.
            self._counter.record(topic, self._raw_source_name(raw), None)
            return
        msgs = (
            adapted
            if isinstance(adapted, Sequence) and not isinstance(adapted, Message)
            else [adapted]
        )
        for m in msgs:
            self._counter.record(topic, m.stream.name, m.stream.name)
            # Producer lag only makes sense for data-plane payloads whose
            # timestamp is a production time; run-control/command timestamps
            # are schedule times, possibly far in the past by design.
            if m.stream.kind in _LAG_TRACKED_KINDS:
                now_ns = time.time_ns()
                self._counter.record_lag(
                    topic,
                    m.stream.name,
                    m.stream.kind.value,
                    (now_ns - m.timestamp.ns) / 1e9,
                )
                # The e2e birth boundary (ADR 0120): the source
                # timestamp — ev44 reference time / payload time, just
                # extracted by the adapter — measured against the wall
                # clock AT CONSUME. Everything the later stages add on
                # top of this is the service's own latency.
                observe_stage("consume", m.timestamp.ns, now_ns=now_ns)

    def _observe_poll(self, raws: Sequence) -> None:
        """Per-poll decode telemetry (ADR 0125): batch size is the
        amortization factor of every whole-poll optimization, bytes the
        decode plane's throughput denominator."""
        DECODE_BATCH_SIZE.observe(float(len(raws)))
        nbytes = 0
        for raw in raws:
            value = getattr(raw, "value", None)
            if callable(value):
                try:
                    nbytes += len(value())
                except Exception as err:
                    # Telemetry must never break the consume path; the
                    # adapter layer will surface the broken message.
                    logger.debug("unsized raw message in poll: %s", err)
        if nbytes:
            DECODE_BYTES.inc(float(nbytes))

    def get_messages(self) -> list[Message]:
        raws = self._source.get_messages()
        if raws:
            self._observe_poll(raws)
        adapt_batch = getattr(self._adapter, "adapt_batch", None)
        if adapt_batch is not None:
            entries = adapt_batch(raws)
        else:
            entries = [_adapt_one(self._adapter, raw) for raw in raws]
        out: list[Message] = []
        for raw, adapted in zip(raws, entries):
            if isinstance(adapted, AdaptFailure):
                err = adapted.error
                if isinstance(err, UnroutedError):
                    self.unrouted_count += 1
                    if self._counter is not None:
                        self._counter.record(
                            getattr(raw, "topic", lambda: "?")(),
                            self._raw_source_name(raw),
                            None,
                        )
                    logger.debug("Unrouted message: %s", err)
                    continue
                self.error_count += 1
                DECODE_ERRORS.inc(
                    schema=adapted.schema or _schema_of(raw) or "unknown"
                )
                logger.error(
                    "Failed to adapt message on topic %s",
                    getattr(raw, "topic", lambda: "?")(),
                    exc_info=err,
                )
                if self._raise:
                    raise err
                continue
            if self._counter is not None:
                self._count(raw, adapted)
            if adapted is None:
                continue
            if isinstance(adapted, Sequence) and not isinstance(adapted, Message):
                out.extend(adapted)
            else:
                out.append(adapted)
        return out

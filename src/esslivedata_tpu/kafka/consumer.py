"""Manual partition assignment pinned at the high watermark.

Parity with reference ``kafka/consumer.py`` (assign_all_partitions:31,
topic validation :15, context-managed factories :88): services never
``subscribe`` (no consumer-group rebalancing, no offset commits — restart
semantics are "resume at live data", SURVEY.md §5 elastic recovery).
Instead every partition of every input topic is assigned explicitly with
the offset pinned at the *current high watermark*, so exactly the data
produced after assignment is consumed, deterministically.

Works against the confluent_kafka Consumer API shape; a structural
protocol keeps it testable (and usable) without the library.
"""

from __future__ import annotations

import logging
import threading
from collections.abc import Callable, Sequence
from contextlib import contextmanager
from typing import Any, Protocol

from ..telemetry.registry import REGISTRY, MetricFamily, Sample

__all__ = [
    "AssignableConsumer",
    "GroupMembership",
    "assign_all_partitions",
    "consumer_from_config",
    "kafka_client_config",
    "librdkafka_config",
    "subscribe_with_group",
    "validate_topics_exist",
]

logger = logging.getLogger(__name__)

_METADATA_TIMEOUT_S = 10.0


class AssignableConsumer(Protocol):
    """The metadata/assignment surface we rely on (confluent_kafka-shaped).

    Distinct from ``kafka.source.KafkaConsumer`` (the consume-side
    protocol): this one covers only the startup assignment handshake.
    """

    def list_topics(self, timeout: float) -> Any: ...

    def get_watermark_offsets(
        self, partition: Any, timeout: float
    ) -> tuple[int, int]: ...

    def assign(self, partitions: list[Any]) -> None: ...


def _validate(metadata, topics: Sequence[str]) -> None:
    known = set(metadata.topics)
    if missing := sorted(set(topics) - known):
        raise ValueError(
            f"Topics not found on broker: {missing}; available: "
            f"{sorted(known)[:20]}"
        )


def validate_topics_exist(
    consumer: AssignableConsumer, topics: Sequence[str]
) -> None:
    """Raise ValueError naming every requested topic the broker lacks."""
    _validate(consumer.list_topics(timeout=_METADATA_TIMEOUT_S), topics)


class _TopicPartition:
    """Stand-in when confluent_kafka is absent (tests, fake brokers)."""

    def __init__(self, topic: str, partition: int, offset: int = -1) -> None:
        self.topic = topic
        self.partition = partition
        self.offset = offset

    def __repr__(self) -> str:
        return f"TP({self.topic}[{self.partition}]@{self.offset})"


def _topic_partition_type():
    try:
        from confluent_kafka import TopicPartition

        return TopicPartition
    except ImportError:
        return _TopicPartition


def assign_all_partitions(
    consumer: AssignableConsumer,
    topics: Sequence[str],
    *,
    start_offsets: dict[str, int] | None = None,
) -> int:
    """Assign every partition of ``topics``; offsets at the high
    watermark, or at a caller-provided **bookmark** (durability plane,
    ADR 0118).

    Without ``start_offsets`` every partition pins at the current high
    watermark — exactly the data produced after assignment is consumed,
    and a restart loses the gap (the documented reference behavior).
    With it, a topic present in the dict seeks to its bookmarked offset
    instead, CLAMPED to the broker's retained ``[low, high]`` range: a
    bookmark below the low watermark (retention caught up) resumes at
    the oldest retained data, one above the high watermark (topic
    truncated/recreated since the checkpoint) falls back to live —
    both logged, neither fatal, because a clamped replay beats no
    replay. Topics absent from the dict keep the high-watermark pin.

    Returns the number of partitions assigned. Topics are validated
    (from the same single metadata fetch) so a typo fails loudly
    instead of consuming nothing forever.
    """
    TopicPartition = _topic_partition_type()

    metadata = consumer.list_topics(timeout=_METADATA_TIMEOUT_S)
    _validate(metadata, topics)
    assignments: list[Any] = []
    seeked = 0
    for topic in topics:
        bookmark = (start_offsets or {}).get(topic)
        for partition_id in metadata.topics[topic].partitions:
            tp = TopicPartition(topic, partition_id)
            low, high = consumer.get_watermark_offsets(
                tp, timeout=_METADATA_TIMEOUT_S
            )
            if bookmark is None:
                tp.offset = high
            else:
                tp.offset = max(low, min(int(bookmark), high))
                seeked += 1
                if tp.offset != int(bookmark):
                    logger.warning(
                        "bookmark %d for %s[%d] outside retained "
                        "[%d, %d]; clamped to %d",
                        bookmark,
                        topic,
                        partition_id,
                        low,
                        high,
                        tp.offset,
                    )
            assignments.append(tp)
    consumer.assign(assignments)
    logger.info(
        "Assigned %d partitions across %d topics (%d at bookmarks, "
        "rest at high watermark)",
        len(assignments),
        len(topics),
        seeked,
    )
    return len(assignments)


class GroupMembership:
    """Consumer-group membership/generation as scrapeable telemetry.

    Rebalances used to be invisible outside librdkafka's own logs: a
    replica could lose half its partitions and nothing on ``/metrics``
    moved. This class is the keyed collector that fixes it (the fleet
    plane's rebalance signal, ADR 0121): wire its ``on_assign``/
    ``on_revoke`` as the rebalance callbacks (or call
    :func:`subscribe_with_group`) and every rebalance surfaces as

    - ``livedata_kafka_group_generation{group}`` — assignments seen by
      THIS member (a local, monotone stand-in for the group protocol's
      generation, which librdkafka does not expose per-callback);
    - ``livedata_kafka_group_assigned_partitions{group}`` — current
      partition count (0 while revoked mid-rebalance);
    - ``livedata_kafka_group_rebalances_total{group,event}`` — assign/
      revoke callback fires.

    ``observer`` (optional) is called OUTSIDE the lock after every
    assign with ``(generation, partitions)`` — the REBALANCE SIGNAL,
    not a membership list: a member only learns its own
    ``TopicPartition`` assignment from the group protocol, never the
    peer roster. A fleet-aware caller reacts by re-resolving the
    replica set from its own source (static ``--fleet-replicas``
    config, a deployment registry) and handing THAT to
    ``FleetAssignment.apply_membership(members, generation)`` — the
    signal makes failover happen at group-protocol cadence, the
    roster comes from elsewhere.
    """

    def __init__(
        self,
        group_id: str,
        *,
        observer: Callable[[int, tuple], None] | None = None,
    ) -> None:
        self.group_id = group_id
        self._lock = threading.Lock()
        self._generation = 0
        self._assigns = 0
        self._revokes = 0
        self._partitions: tuple = ()
        self._observer = observer
        self._collector_key = f"kafka:group:{group_id}"
        REGISTRY.register_collector(self._collector_key, self._telemetry)

    # confluent_kafka rebalance-callback signatures -------------------------
    def on_assign(self, consumer, partitions) -> None:
        with self._lock:
            self._generation += 1
            self._assigns += 1
            self._partitions = tuple(partitions)
            generation = self._generation
            observer = self._observer
        logger.info(
            "group %s rebalance: %d partition(s) assigned "
            "(generation %d)",
            self.group_id,
            len(partitions),
            generation,
        )
        if observer is not None:
            observer(generation, tuple(partitions))

    def on_revoke(self, consumer, partitions) -> None:
        with self._lock:
            self._revokes += 1
            self._partitions = ()
        logger.info(
            "group %s rebalance: %d partition(s) revoked",
            self.group_id,
            len(partitions),
        )

    # -- introspection ------------------------------------------------------
    @property
    def generation(self) -> int:
        with self._lock:
            return self._generation

    @property
    def partitions(self) -> tuple:
        with self._lock:
            return self._partitions

    def _telemetry(self) -> list[MetricFamily]:
        gen_fam = MetricFamily(
            "livedata_kafka_group_generation",
            "gauge",
            "Consumer-group assignments this member has seen (monotone "
            "per process; a jump means a rebalance happened)",
        )
        parts_fam = MetricFamily(
            "livedata_kafka_group_assigned_partitions",
            "gauge",
            "Partitions currently assigned to this group member "
            "(0 while revoked mid-rebalance)",
        )
        rebalance_fam = MetricFamily(
            "livedata_kafka_group_rebalances",
            "counter",
            "Rebalance callbacks fired on this member, by event",
        )
        base = (("group", self.group_id),)
        with self._lock:
            gen_fam.samples.append(Sample("", base, self._generation))
            parts_fam.samples.append(
                Sample("", base, len(self._partitions))
            )
            rebalance_fam.samples.append(
                Sample(
                    "_total", base + (("event", "assign"),), self._assigns
                )
            )
            rebalance_fam.samples.append(
                Sample(
                    "_total", base + (("event", "revoke"),), self._revokes
                )
            )
        return [gen_fam, parts_fam, rebalance_fam]

    def close(self) -> None:
        REGISTRY.unregister_collector(self._collector_key, self._telemetry)


def subscribe_with_group(
    consumer, topics: Sequence[str], membership: GroupMembership
) -> None:
    """Group-managed subscription (the fleet-mode exception to this
    module's assign-at-high-watermark rule): the broker's group
    protocol partitions ``topics`` across every live member, and the
    ``membership`` monitor surfaces each rebalance as telemetry + the
    fleet observer hook. Topics are validated first, same as the
    assign path — a typo must fail loudly."""
    validate_topics_exist(consumer, topics)
    consumer.subscribe(
        list(topics),
        on_assign=membership.on_assign,
        on_revoke=membership.on_revoke,
    )
    logger.info(
        "subscribed %d topic(s) under group %s (membership-driven)",
        len(topics),
        membership.group_id,
    )


# Loader-config keys -> librdkafka settings. Everything the defaults/
# YAML files may declare must be translated here: a dropped key like
# security_protocol makes the consumer silently attempt PLAINTEXT against
# a SASL broker and hang.
_LIBRDKAFKA_KEYS = {
    "bootstrap_servers": "bootstrap.servers",
    "security_protocol": "security.protocol",
    "sasl_mechanism": "sasl.mechanism",
    "sasl_username": "sasl.username",
    "sasl_password": "sasl.password",
    "ssl_ca_location": "ssl.ca.location",
}

#: App-level tuning keys (consumed by the source/ingest layers, not
#: librdkafka) that may legitimately sit in the same loader config dicts:
#: the source's batch/queue sizes plus the pipelined-ingest hand-off
#: knobs (ADR 0111 — pipeline on/off, in-flight window bound, chunked
#: flatten threads), so one kafka config namespace provisions the whole
#: consume->ingest tier.
_APP_TUNING_KEYS = frozenset(
    {
        "max_poll_records",
        "poll_timeout_ms",
        "queue_max_batches",
        "pipeline",
        "pipeline_depth",
        "flatten_threads",
    }
)


def librdkafka_config(config: dict[str, Any]) -> dict[str, Any]:
    """Translate a loader config dict into librdkafka settings.

    App-level tuning keys (source-layer batch/queue sizes) are skipped;
    anything else unknown is rejected rather than dropped, so adding a key
    to the YAML defaults without teaching this translation fails loudly.
    """
    out: dict[str, Any] = {"bootstrap.servers": "localhost:9092"}
    unknown = set(config) - set(_LIBRDKAFKA_KEYS) - _APP_TUNING_KEYS
    if unknown:
        raise ValueError(
            f"Unrecognized kafka config keys {sorted(unknown)}; known: "
            f"{sorted(_LIBRDKAFKA_KEYS)} + tuning {sorted(_APP_TUNING_KEYS)}"
        )
    for key, value in config.items():
        if key in _LIBRDKAFKA_KEYS:
            out[_LIBRDKAFKA_KEYS[key]] = value
    return out


def kafka_client_config(
    *, bootstrap_override: str | None = None
) -> dict[str, Any]:
    """librdkafka settings for the current LIVEDATA_ENV.

    Loads the ``kafka`` config namespace (YAML defaults incl. SASL/SSL
    credentials in prod) and translates it; a CLI-provided bootstrap
    override wins over the file. Used by the service runner, dashboard
    transport, and tools so every client shares one authentication path.
    """
    from ..config.config_loader import load_config

    try:
        conf = librdkafka_config(load_config(namespace="kafka") or {})
    except FileNotFoundError:
        conf = librdkafka_config({})
    if bootstrap_override is not None:
        conf["bootstrap.servers"] = bootstrap_override
    return conf


@contextmanager
def consumer_from_config(
    config: dict[str, Any], topics: Sequence[str], *, group_id: str
):
    """Build a confluent_kafka Consumer from a loader config dict, assign
    all partitions, close on exit. ``group_id`` is required so callers
    (scripts, tools) never silently share a group with services — the
    service path builds its own consumer with its instrument-scoped id
    (services/service_factory.py)."""
    from confluent_kafka import Consumer

    consumer = Consumer(
        {
            **librdkafka_config(config),
            "group.id": group_id,
            "enable.auto.commit": False,
            "auto.offset.reset": "latest",
        }
    )
    try:
        assign_all_partitions(consumer, topics)
        yield consumer
    finally:
        consumer.close()

"""Thread-safe per-stream message counts and producer-lag accumulation.

Parity with reference ``kafka/stream_counter.py``: the adapter layer calls
``record`` each time a wire message is mapped (or fails to map) to a stream
and ``record_lag`` when a payload timestamp is available; the processor
drains both on the 30 s metrics rollover. EPICS noise suffixes (``.VAL``,
``.DMOV`` — only ``.RBV`` carries the readback) and streams known to belong
to another service (``out_of_scope``) are dropped so the status display is
not polluted by unmapped-but-expected traffic.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass

from ..core.job import StreamLag, StreamLagReport
from .stream_mapping import InputStreamKey

__all__ = ["StreamCounter", "StreamStat", "StreamStats"]

_IGNORED_SOURCE_SUFFIXES = (".DMOV", ".VAL")


@dataclass(frozen=True, slots=True)
class StreamStat:
    """Message count for one (topic, source) over a metrics window."""

    topic: str
    source_name: str
    stream: str | None  # resolved stream name, None if unmapped
    count: int


@dataclass(frozen=True, slots=True)
class StreamStats:
    window_seconds: float
    streams: tuple[StreamStat, ...]

    @property
    def unmapped(self) -> tuple[StreamStat, ...]:
        return tuple(s for s in self.streams if s.stream is None)


@dataclass(slots=True)
class _LagAgg:
    min_s: float
    max_s: float
    count: int


class StreamCounter:
    """Counts messages per (topic, source) and folds per-message producer lag.

    Producer lag is ``kafka_create_time - payload_timestamp`` in seconds:
    how far behind real time the producer published. Aggregated as
    (min, max, count) per (topic, source, schema) so one insane timestamp is
    visible without storing every sample.
    """

    def __init__(self, *, out_of_scope: tuple[InputStreamKey, ...] = ()) -> None:
        self._lock = threading.Lock()
        self._counts: dict[tuple[str, str], tuple[str | None, int]] = {}
        self._lag: dict[tuple[str, str, str], _LagAgg] = {}
        self._out_of_scope = {(k.topic, k.source_name) for k in out_of_scope}
        # Cumulative per-(topic, source) totals drain() never resets:
        # the telemetry collector (ADR 0116) exposes monotone message
        # counters while the 30 s metrics rollover keeps its own
        # drain-and-reset window semantics.
        self._cum_counts: dict[tuple[str, str], int] = {}

    def record(self, topic: str, source_name: str, stream: str | None) -> None:
        if source_name.endswith(_IGNORED_SOURCE_SUFFIXES):
            return
        key = (topic, source_name)
        if key in self._out_of_scope:
            return
        with self._lock:
            _, count = self._counts.get(key, (None, 0))
            self._counts[key] = (stream, count + 1)
            self._cum_counts[key] = self._cum_counts.get(key, 0) + 1

    def cumulative_counts(self) -> dict[tuple[str, str], int]:
        """Monotone per-(topic, source) totals since construction."""
        with self._lock:
            return dict(self._cum_counts)

    def record_lag(
        self, topic: str, source_name: str, schema: str, lag_s: float
    ) -> None:
        if source_name.endswith(_IGNORED_SOURCE_SUFFIXES):
            return
        key = (topic, source_name, schema)
        with self._lock:
            agg = self._lag.get(key)
            if agg is None:
                self._lag[key] = _LagAgg(min_s=lag_s, max_s=lag_s, count=1)
            else:
                agg.min_s = min(agg.min_s, lag_s)
                agg.max_s = max(agg.max_s, lag_s)
                agg.count += 1

    def drain(self, window_seconds: float) -> StreamStats:
        """Return accumulated counts and reset."""
        with self._lock:
            counts, self._counts = self._counts, {}
        return StreamStats(
            window_seconds=window_seconds,
            streams=tuple(
                StreamStat(topic=t, source_name=s, stream=stream, count=n)
                for (t, s), (stream, n) in sorted(counts.items())
            ),
        )

    def drain_lag(self) -> StreamLagReport | None:
        """Return accumulated per-stream lag and reset; None if empty.

        Ordered by key so successive windows list streams in stable
        positions for line-by-line comparison.
        """
        with self._lock:
            lag, self._lag = self._lag, {}
        if not lag:
            return None
        return StreamLagReport(
            lags=[
                StreamLag(
                    stream_name=f"{topic}/{source}[{schema}]",
                    lag_s=agg.max_s,
                    min_s=agg.min_s,
                    max_s=agg.max_s,
                    count=agg.count,
                )
                for (topic, source, schema), agg in sorted(lag.items())
            ]
        )

"""Synthesize a chopper-cascade trigger from chopper PV streams.

Parity with reference ``kafka/chopper_synthesizer.py:148``: a MessageSource
decorator that forwards everything verbatim while

- caching per-chopper ``<chopper>/rotation_speed_setpoint`` values,
- plateau-detecting each chopper's noisy ``<chopper>/delay`` readback with a
  rolling-window stability detector, emitting a synthetic
  ``<chopper>/delay_setpoint`` f144 on each new lock,
- emitting a synthetic primary tick on the ``chopper_cascade`` logical
  stream when every configured chopper has both a cached speed setpoint and
  a locked delay setpoint — only on cycles where an input actually changed.

Chopperless instruments (empty ``chopper_names``) get exactly one vacuous
cascade tick on the first ``get_messages`` call. The cascade tick is the
wavelength-LUT job's primary dynamic stream: its arrival drives a LUT
recompute (see workflows/wavelength_lut_workflow.py).
"""

from __future__ import annotations

import logging
from collections import deque
from collections.abc import Sequence
from dataclasses import dataclass

import numpy as np

from ..config.chopper import (
    CHOPPER_CASCADE_SOURCE,
    delay_readback_stream,
    delay_setpoint_stream,
    speed_setpoint_stream,
)
from ..core.message import Message, MessageSource, StreamId, StreamKind
from ..core.timestamp import Timestamp
from ..preprocessors.to_nxlog import LogData

__all__ = ["CHOPPER_CASCADE_SOURCE", "CHOPPER_CASCADE_STREAM", "ChopperSynthesizer"]

logger = logging.getLogger(__name__)

CHOPPER_CASCADE_STREAM = StreamId(kind=StreamKind.LOG, name=CHOPPER_CASCADE_SOURCE)


def _cascade_tick(time: Timestamp | None = None) -> Message[LogData]:
    """The 'all choppers reached setpoints' tick; value unused downstream.

    Timestamped with the data time of the triggering input so it rides the
    system's data-time clock (batchers window on message timestamps, never
    wall clock); the chopperless bootstrap tick has no input and falls back
    to wall clock.
    """
    time = time if time is not None else Timestamp.now()
    return Message(
        timestamp=time,
        stream=CHOPPER_CASCADE_STREAM,
        value=LogData(time=time.ns, value=1),
    )


class _StabilityDetector:
    """Rolling-window plateau detector.

    Locks when the window's std dev drops below ``atol``; the locked value
    is the window mean. The same ``atol`` decides whether a new mean has
    drifted far enough from the previous lock to count as a new setpoint,
    so noise rejection and change detection share one knob.
    """

    def __init__(self, *, window_size: int, atol: float) -> None:
        self._buffer: deque[float] = deque(maxlen=window_size)
        self._atol = atol
        self._locked: float | None = None

    def add(self, sample: float) -> float | None:
        """Append a sample; return a newly locked value if it changed."""
        self._buffer.append(sample)
        if len(self._buffer) < self._buffer.maxlen:
            return None
        arr = np.fromiter(self._buffer, dtype=float)
        if arr.std() >= self._atol:
            return None
        mean = float(arr.mean())
        if self._locked is None or abs(mean - self._locked) > self._atol:
            self._locked = mean
            return mean
        return None

    @property
    def locked(self) -> float | None:
        return self._locked


@dataclass(slots=True)
class _ChopperState:
    detector: _StabilityDetector
    speed_setpoint: float | None = None
    delay_setpoint: float | None = None

    def is_locked(self) -> bool:
        return self.speed_setpoint is not None and self.delay_setpoint is not None


class ChopperSynthesizer:
    """MessageSource decorator injecting synthetic chopper-cascade triggers."""

    def __init__(
        self,
        wrapped: MessageSource[Message],
        *,
        chopper_names: Sequence[str] = (),
        delay_window_size: int = 5,
        delay_atol: float = 1000.0,
        refresh_every: int = 256,
    ) -> None:
        self._wrapped = wrapped
        self._chopper_names = tuple(chopper_names)
        # Re-emit the current tick every N cycles while locked so a LUT job
        # started *after* the original tick still receives its primary
        # trigger (jobs only see the current window; there is no replay).
        # The LUT workflow dedupes on setpoint signature, so refresh ticks
        # are cheap no-ops for already-computed jobs.
        self._refresh_every = max(1, refresh_every)
        self._cycle = 0
        self._last_data_time: Timestamp | None = None
        self._states = {
            name: _ChopperState(
                detector=_StabilityDetector(
                    window_size=delay_window_size, atol=delay_atol
                )
            )
            for name in self._chopper_names
        }
        self._delay_streams = {
            delay_readback_stream(n): n for n in self._chopper_names
        }
        self._speed_streams = {
            speed_setpoint_stream(n): n for n in self._chopper_names
        }
        self._emitted_initial_tick = False
        self._was_all_locked = False

    def get_messages(self) -> Sequence[Message]:
        synthetic: list[Message] = []
        forwarded: list[Message] = []
        self._cycle += 1

        if not self._chopper_names and not self._emitted_initial_tick:
            self._emitted_initial_tick = True
            synthetic.append(_cascade_tick())
            logger.info("chopper_cascade initial tick emitted (no choppers)")

        any_changed = False
        change_time: Timestamp | None = None
        for msg in self._wrapped.get_messages():
            forwarded.append(msg)
            if (
                self._last_data_time is None
                or msg.timestamp > self._last_data_time
            ):
                self._last_data_time = msg.timestamp
            if self._handle(msg, synthetic):
                any_changed = True
                if change_time is None or msg.timestamp > change_time:
                    change_time = msg.timestamp

        if self._chopper_names:
            all_locked = all(s.is_locked() for s in self._states.values())
            if any_changed and all_locked:
                synthetic.append(_cascade_tick(change_time))
                if not self._was_all_locked:
                    logger.info(
                        "chopper_cascade all locked: %s",
                        list(self._chopper_names),
                    )
            elif all_locked and self._cycle % self._refresh_every == 0:
                # Periodic refresh, timestamped on the data clock (last seen
                # data time) so replay never produces wall-clock windows.
                synthetic.append(_cascade_tick(self._last_data_time))
            self._was_all_locked = all_locked
        elif (
            self._emitted_initial_tick
            and self._cycle % self._refresh_every == 0
        ):
            synthetic.append(_cascade_tick(self._last_data_time))

        return [*synthetic, *forwarded]

    def _handle(self, msg: Message, synthetic: list[Message]) -> bool:
        """Update chopper state from ``msg``; True if an input changed."""
        name = msg.stream.name
        if (chopper := self._delay_streams.get(name)) is not None:
            return self._handle_delay(chopper, msg, synthetic)
        if (chopper := self._speed_streams.get(name)) is not None:
            return self._handle_speed(chopper, msg)
        return False

    def _handle_delay(
        self, chopper: str, msg: Message, synthetic: list[Message]
    ) -> bool:
        state = self._states[chopper]
        new_setpoint = None
        for sample in np.atleast_1d(msg.value.value):
            if (locked := state.detector.add(float(sample))) is not None:
                new_setpoint = locked
        if new_setpoint is None:
            return False
        time_ns = int(msg.value.time[-1])
        synthetic.append(
            Message(
                timestamp=Timestamp.from_ns(time_ns),
                stream=StreamId(
                    kind=StreamKind.LOG, name=delay_setpoint_stream(chopper)
                ),
                value=LogData(time=time_ns, value=new_setpoint),
            )
        )
        state.delay_setpoint = new_setpoint
        logger.info("chopper %s delay locked at %s", chopper, new_setpoint)
        return True

    def _handle_speed(self, chopper: str, msg: Message) -> bool:
        new_speed = float(np.atleast_1d(msg.value.value)[-1])
        state = self._states[chopper]
        if state.speed_setpoint == new_speed:
            return False
        state.speed_setpoint = new_speed
        return True

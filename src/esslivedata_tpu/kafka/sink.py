"""Sinks + per-type serializers.

Parity with reference ``kafka/sink.py`` (KafkaSink:53, MessageSerializer:40,
drop-on-BufferError backpressure :110-118, UnrollingSinkAdapter:179) and
``kafka/sink_serializers.py`` (results->da00:78, logs->f144:95,
status->x5f2:108, commands/acks->JSON:160-182). Serialization errors are
contained per message; producer buffer-full drops the message rather than
blocking the hot loop.
"""

from __future__ import annotations

import json
import logging
import os
import socket
import threading
from collections.abc import Sequence
from dataclasses import dataclass
from typing import Protocol, runtime_checkable

import numpy as np
from pydantic import BaseModel

from ..core.message import Message, StreamKind
from ..preprocessors.to_nxlog import LogData
from ..utils.labeled import DataArray
from . import wire
from .da00_compat import dataarray_to_da00
from .stream_mapping import LivedataTopics

__all__ = [
    "FakeProducer",
    "KafkaProducer",
    "KafkaSink",
    "MessageSerializer",
    "SerializedMessage",
    "UnrollingSinkAdapter",
    "make_default_serializer",
]

logger = logging.getLogger(__name__)


@dataclass(frozen=True, slots=True)
class SerializedMessage:
    topic: str
    value: bytes
    key: bytes | None = None


@runtime_checkable
class MessageSerializer(Protocol):
    def serialize(self, message: Message) -> SerializedMessage: ...


@runtime_checkable
class KafkaProducer(Protocol):
    def produce(self, topic: str, value: bytes, key: bytes | None = None) -> None: ...

    def flush(self, timeout: float = 0.0) -> None: ...


class FakeProducer:
    """In-memory producer double; can simulate a full buffer."""

    def __init__(self, *, buffer_errors: int = 0) -> None:
        self.messages: list[SerializedMessage] = []
        self._buffer_errors = buffer_errors

    def produce(self, topic: str, value: bytes, key: bytes | None = None) -> None:
        if self._buffer_errors > 0:
            self._buffer_errors -= 1
            raise BufferError("queue full")
        self.messages.append(SerializedMessage(topic=topic, value=value, key=key))

    def flush(self, timeout: float = 0.0) -> None:
        pass


class DefaultSerializer:
    """Routes by StreamKind + payload type to the right wire format."""

    def __init__(self, topics: LivedataTopics, service_id: str = "") -> None:
        self._topics = topics
        self._service_id = service_id

    def serialize(self, message: Message) -> SerializedMessage:
        kind = message.stream.kind
        value = message.value
        ts = message.timestamp.ns
        name = message.stream.name
        if kind in (StreamKind.LIVEDATA_DATA,) and isinstance(value, DataArray):
            return SerializedMessage(
                topic=self._topics.data,
                value=wire.encode_da00(name, ts, dataarray_to_da00(value)),
                key=name.encode(),
            )
        if kind == StreamKind.LIVEDATA_NICOS_DATA:
            if isinstance(value, LogData):
                return SerializedMessage(
                    topic=self._topics.nicos,
                    value=wire.encode_f144(name, value.value, int(value.time[-1])),
                    key=name.encode(),
                )
            if isinstance(value, DataArray):
                # Contracted device outputs (core/nicos_devices.py): da00
                # keyed by stable device name; the start_time coord rides
                # along as the generation change-detector.
                return SerializedMessage(
                    topic=self._topics.nicos,
                    value=wire.encode_da00(name, ts, dataarray_to_da00(value)),
                    key=name.encode(),
                )
            return SerializedMessage(
                topic=self._topics.nicos,
                value=wire.encode_f144(name, np.asarray(value), ts),
                key=name.encode(),
            )
        if kind == StreamKind.LIVEDATA_STATUS and isinstance(value, BaseModel):
            # NICOS wire contract (kafka/nicos_status.py): service and
            # per-job heartbeats carry a NICOS status code + typed payload
            # in status_json, addressed by the NICOS identity conventions.
            from ..core.job import JobStatus, ServiceStatus
            from .nicos_status import (
                job_status_to_x5f2,
                service_status_to_x5f2,
            )

            if isinstance(value, ServiceStatus):
                payload = service_status_to_x5f2(
                    value,
                    worker=self._service_id,
                    host_name=socket.gethostname(),
                    process_id=os.getpid(),
                )
            elif isinstance(value, JobStatus):
                payload = job_status_to_x5f2(
                    value,
                    host_name=socket.gethostname(),
                    process_id=os.getpid(),
                )
            else:
                payload = wire.encode_x5f2(
                    wire.X5f2Status(
                        software_name="esslivedata-tpu",
                        software_version="0.1.0",
                        service_id=self._service_id,
                        host_name=socket.gethostname(),
                        process_id=os.getpid(),
                        update_interval_ms=2000,
                        status_json=value.model_dump_json(),
                    )
                )
            return SerializedMessage(
                topic=self._topics.status, value=payload
            )
        if kind == StreamKind.LIVEDATA_RESPONSES:
            payload = (
                value.model_dump(mode="json")
                if isinstance(value, BaseModel)
                else value
            )
            return SerializedMessage(
                topic=self._topics.responses,
                value=json.dumps(payload).encode(),
            )
        if kind == StreamKind.LIVEDATA_COMMANDS:
            payload = (
                value.model_dump(mode="json")
                if isinstance(value, BaseModel)
                else value
            )
            return SerializedMessage(
                topic=self._topics.commands,
                value=json.dumps(payload).encode(),
            )
        raise ValueError(
            f"No serializer for kind={kind} value type {type(value).__name__}"
        )


def make_default_serializer(
    topics: LivedataTopics, service_id: str = ""
) -> DefaultSerializer:
    return DefaultSerializer(topics, service_id)


class KafkaSink:
    """MessageSink publishing through a producer with drop-on-full.

    Error policy mirrors the consume side's circuit breaker: transient
    produce/flush exceptions are contained (counted, logged) — a broker
    hiccup must not crash the service worker per message — but after
    ``MAX_CONSECUTIVE_ERRORS`` in a row the breaker opens and the error
    propagates, handing the supervisor a restart instead of a silent
    black hole.
    """

    #: Consecutive produce failures before the breaker opens.
    MAX_CONSECUTIVE_ERRORS = 10

    def __init__(self, producer: KafkaProducer, serializer: MessageSerializer):
        self._producer = producer
        self._serializer = serializer
        self.dropped = 0
        self.serialize_errors = 0
        self.produce_errors = 0
        self.flush_errors = 0
        # Per-path failure continuity: a healthy flush must not mask a
        # persistently failing produce (and vice versa), so each path
        # trips its own breaker.
        self._consecutive_produce = 0
        self._consecutive_flush = 0
        # Pipelined ingest publishes results from the step worker while
        # the service thread publishes heartbeats/acks (ADR 0111): the
        # error counters above are read-modify-writes, and interleaved
        # streaks must not lose increments (a delayed breaker trip
        # black-holes messages for longer). librdkafka's produce() is
        # itself thread-safe; the lock covers this sink's accounting.
        self._lock = threading.Lock()

    def metrics(self) -> dict[str, int]:
        """Coherent snapshot of the sink/breaker counters — the
        telemetry collector's read (ADR 0116); one lock acquisition so
        a streak's dropped/consecutive pair can never tear."""
        with self._lock:
            return {
                "dropped": self.dropped,
                "serialize_errors": self.serialize_errors,
                "produce_errors": self.produce_errors,
                "flush_errors": self.flush_errors,
                "consecutive_produce_failures": self._consecutive_produce,
                "consecutive_flush_failures": self._consecutive_flush,
            }

    def _trip_or_warn(
        self, consecutive: int, what: str, exc: BaseException
    ) -> None:
        if consecutive >= self.MAX_CONSECUTIVE_ERRORS:
            logger.error(
                "Producer circuit breaker open after %d consecutive "
                "%s failures",
                consecutive,
                what,
            )
            raise exc
        # (Only a produce failure drops a message; a failed flush(0)
        # leaves the batch queued in the producer.)
        logger.warning("%s failed (%d consecutive)", what, consecutive)

    def publish_messages(self, messages: Sequence[Message]) -> None:
        for msg in messages:
            try:
                sm = self._serializer.serialize(msg)
            except Exception:
                with self._lock:
                    self.serialize_errors += 1
                logger.exception("Failed to serialize %s", msg.stream)
                continue
            try:
                self._producer.produce(sm.topic, sm.value, sm.key)
            except BufferError as err:
                # Producer queue full: drop rather than stall the hot
                # loop (reference sink.py:110-118) — but during an
                # extended broker outage an async producer fails
                # EXACTLY this way (the local queue never drains), so
                # sustained drops must trip the breaker too instead of
                # black-holing every message behind per-drop warnings.
                with self._lock:
                    self.dropped += 1
                    self._consecutive_produce += 1
                    consecutive = self._consecutive_produce
                self._trip_or_warn(consecutive, "produce (queue full)", err)
            except Exception as err:
                with self._lock:
                    self.produce_errors += 1
                    self._consecutive_produce += 1
                    consecutive = self._consecutive_produce
                self._trip_or_warn(consecutive, "produce", err)
            else:
                with self._lock:
                    self._consecutive_produce = 0
        try:
            self._producer.flush(0)
        except Exception as err:
            with self._lock:
                self.flush_errors += 1
                self._consecutive_flush += 1
                consecutive = self._consecutive_flush
            self._trip_or_warn(consecutive, "flush", err)
        else:
            with self._lock:
                self._consecutive_flush = 0


class UnrollingSinkAdapter:
    """Unpacks Message[dict[str, DataArray]] (a job's result group) into one
    message per output (reference sink.py:179)."""

    def __init__(self, sink) -> None:
        self._sink = sink

    def metrics(self) -> dict[str, int]:
        """Pass through the wrapped sink's counters (duck-typed; the
        telemetry collector walks one adapter layer this way)."""
        inner = getattr(self._sink, "metrics", None)
        return inner() if callable(inner) else {}

    def publish_messages(self, messages: Sequence[Message]) -> None:
        flat: list[Message] = []
        for msg in messages:
            if isinstance(msg.value, dict):
                for out_name, da in msg.value.items():
                    flat.append(
                        Message(
                            timestamp=msg.timestamp,
                            stream=msg.stream.__class__(
                                kind=msg.stream.kind,
                                name=f"{msg.stream.name}/{out_name}",
                            ),
                            value=da,
                        )
                    )
            else:
                flat.append(msg)
        self._sink.publish_messages(flat)

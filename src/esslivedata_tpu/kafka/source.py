"""Message sources over a narrow consumer protocol.

Parity with reference ``kafka/source.py``: ``KafkaMessageSource`` (bounded
consume per poll, :28), ``BackgroundMessageSource`` (:80) — a daemon consume
thread overlapping broker I/O with compute, a bounded drop-oldest queue
(:199-213), a circuit breaker opening after consecutive errors (:225-240)
and health reporting (:295). The consumer protocol is deliberately tiny so
tests inject ``FakeConsumer`` without a broker (SURVEY.md section 4.2).
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque
from collections.abc import Sequence
from dataclasses import dataclass
from enum import Enum

from .errors import is_fatal
from typing import Protocol, runtime_checkable

__all__ = [
    "BackgroundMessageSource",
    "ConsumerHealth",
    "FakeConsumer",
    "FakeKafkaMessage",
    "KafkaConsumer",
    "KafkaMessage",
    "KafkaMessageSource",
]

logger = logging.getLogger(__name__)


@runtime_checkable
class KafkaMessage(Protocol):
    def value(self) -> bytes: ...

    def topic(self) -> str: ...

    def error(self):  # None or error object
        ...


@runtime_checkable
class KafkaConsumer(Protocol):
    def consume(
        self, num_messages: int, timeout: float
    ) -> Sequence[KafkaMessage]: ...


@dataclass(frozen=True, slots=True)
class FakeKafkaMessage:
    _value: bytes
    _topic: str
    _error: object = None

    def value(self) -> bytes:
        return self._value

    def topic(self) -> str:
        return self._topic

    def error(self):
        return self._error


class FakeConsumer:
    """Replays scripted message batches; raising entries simulate failures."""

    def __init__(self, batches: Sequence[Sequence[KafkaMessage]] = ()) -> None:
        self._batches: deque = deque(list(b) for b in batches)
        self.consume_calls = 0

    def push(self, batch: Sequence[KafkaMessage]) -> None:
        self._batches.append(list(batch))

    def consume(self, num_messages: int, timeout: float) -> list[KafkaMessage]:
        self.consume_calls += 1
        if not self._batches:
            return []
        item = self._batches.popleft()
        if isinstance(item, Exception):
            raise item
        return list(item)[:num_messages]


class KafkaMessageSource:
    """Synchronous source: one bounded consume per poll, fatal-error filter."""

    def __init__(
        self,
        consumer: KafkaConsumer,
        *,
        max_messages: int = 100,
        timeout_s: float = 0.05,
    ) -> None:
        self._consumer = consumer
        self._max_messages = max_messages
        self._timeout_s = timeout_s

    def get_messages(self) -> list[KafkaMessage]:
        messages = self._consumer.consume(self._max_messages, self._timeout_s)
        good = []
        for msg in messages:
            err = msg.error()
            if err is not None:
                if is_fatal(err):
                    # Auth/misconfiguration: crash, don't spin (kafka/errors.py).
                    raise RuntimeError(f"Fatal Kafka error: {err}")
                logger.warning("Kafka message error: %s", err)
                continue
            good.append(msg)
        return good


class ConsumerHealth(Enum):
    OK = "ok"
    STALE = "stale"
    STOPPED = "stopped"


class BackgroundMessageSource:
    """Daemon consume thread feeding a bounded drop-oldest batch queue.

    Overlaps broker I/O with the worker's compute (thread boundary #1 in
    the reference call stack, SURVEY.md section 3.1). After
    ``max_consecutive_errors`` the circuit breaker opens: the thread stops
    and ``get_messages`` raises, killing the worker loop so the supervisor
    restarts the process with fresh connections.
    """

    def __init__(
        self,
        consumer: KafkaConsumer,
        *,
        max_messages: int = 100,
        timeout_s: float = 0.05,
        max_queued_batches: int = 1000,
        max_consecutive_errors: int = 10,
        health_timeout_s: float = 60.0,
    ) -> None:
        self._consumer = consumer
        self._max_messages = max_messages
        self._timeout_s = timeout_s
        self._queue: deque[list[KafkaMessage]] = deque(maxlen=max_queued_batches)
        self._lock = threading.Lock()
        self._running = threading.Event()
        self._thread: threading.Thread | None = None
        self._max_consecutive_errors = max_consecutive_errors
        self._consecutive_errors = 0
        self._broken = False
        self._health_timeout_s = health_timeout_s
        self._last_success = time.monotonic()
        self._dropped_batches = 0
        self._consumed_messages = 0
        # Next-consume offset per topic of everything HANDED TO the
        # worker (not merely consumed into the queue): the durability
        # plane's bookmark surface (ADR 0118). Updated under the queue
        # lock on the worker side, so a checkpoint taken between
        # process cycles sees exactly the delivered frontier.
        # Bookmarks are PER TOPIC, which is only exact for topics with
        # one partition (the file broker always; per-instrument Kafka
        # topics typically): a topic observed on >= 2 partitions is
        # excluded from positions() — one merged number would seek
        # every partition to the max and silently skip the slower
        # partitions' gap. Excluded topics resume at the high
        # watermark, the documented pre-durability behavior.
        self._delivered_offsets: dict[str, int] = {}
        self._topic_partitions: dict[str, set] = {}
        self._multi_partition_logged: set[str] = set()

    # -- lifecycle --------------------------------------------------------
    def start(self) -> None:
        if self._thread is not None:
            return
        self._running.set()
        self._thread = threading.Thread(
            target=self._consume_loop, name="kafka-consume", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._running.clear()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    def __enter__(self) -> "BackgroundMessageSource":
        self.start()
        return self

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- consume thread ---------------------------------------------------
    def _consume_loop(self) -> None:
        while self._running.is_set():
            try:
                batch = self._consumer.consume(self._max_messages, self._timeout_s)
            except Exception:
                self._consecutive_errors += 1
                logger.exception(
                    "Consume error (%d consecutive)", self._consecutive_errors
                )
                if self._consecutive_errors >= self._max_consecutive_errors:
                    logger.error("Circuit breaker open: stopping consume thread")
                    self._broken = True
                    self._running.clear()
                    return
                time.sleep(min(0.1 * self._consecutive_errors, 1.0))
                continue
            self._consecutive_errors = 0
            self._last_success = time.monotonic()
            fatal = next(
                (
                    m.error()
                    for m in batch
                    if m.error() is not None and is_fatal(m.error())
                ),
                None,
            )
            good = [m for m in batch if m.error() is None]
            if good:
                # Enqueue before opening the circuit: good messages consumed
                # alongside a fatal error event must still reach the worker.
                with self._lock:
                    if len(self._queue) == self._queue.maxlen:
                        self._dropped_batches += 1
                    self._queue.append(good)
                    self._consumed_messages += len(good)
            if fatal is not None:
                logger.error("Fatal Kafka error, opening circuit: %s", fatal)
                self._broken = True
                self._running.clear()
                return

    # -- worker side ------------------------------------------------------
    @staticmethod
    def _message_next_offset(message) -> int | None:
        """The resume offset AFTER ``message``: file-broker messages
        carry ``next_offset()`` (byte positions), confluent messages
        ``offset()`` (message index — resume at +1). None when the
        transport exposes neither (in-memory fakes): those deployments
        simply have no bookmarks, which is the pre-durability
        behavior."""
        probe = getattr(message, "next_offset", None)
        if probe is not None:
            try:
                value = probe()
                return None if value is None or value < 0 else int(value)
            except Exception:  # pragma: no cover - defensive
                return None
        probe = getattr(message, "offset", None)
        if probe is not None:
            try:
                value = probe()
                return (
                    None if value is None or value < 0 else int(value) + 1
                )
            except Exception:  # pragma: no cover - defensive
                return None
        return None

    def get_messages(self) -> list[KafkaMessage]:
        # Drain before checking the breaker: good messages enqueued alongside
        # the fatal error event must still reach the worker; only once the
        # queue is empty does the open circuit surface as an error.
        with self._lock:
            out: list[KafkaMessage] = []
            while self._queue:
                out.extend(self._queue.popleft())
            for message in out:
                next_offset = self._message_next_offset(message)
                if next_offset is not None:
                    topic = message.topic()
                    partition_probe = getattr(message, "partition", None)
                    if partition_probe is not None:
                        try:
                            self._topic_partitions.setdefault(
                                topic, set()
                            ).add(partition_probe())
                        except Exception:  # pragma: no cover
                            logger.debug(
                                "partition probe failed for %s",
                                topic,
                                exc_info=True,
                            )
                    if next_offset > self._delivered_offsets.get(topic, -1):
                        self._delivered_offsets[topic] = next_offset
        if not out and self._broken:
            raise RuntimeError(
                "Kafka consumer circuit breaker open (repeated consume errors)"
            )
        return out

    @property
    def health(self) -> ConsumerHealth:
        if self._broken or (
            self._thread is not None and not self._thread.is_alive()
            and self._running.is_set()
        ):
            return ConsumerHealth.STOPPED
        if time.monotonic() - self._last_success > self._health_timeout_s:
            return ConsumerHealth.STALE
        return ConsumerHealth.OK

    @property
    def is_healthy(self) -> bool:
        return self.health == ConsumerHealth.OK

    def positions(self) -> dict[str, int]:
        """Per-topic next-consume offsets of everything handed to the
        worker — the processor's checkpoint bookmarks (ADR 0118). The
        worker takes these only at quiescent window boundaries, where
        delivered == folded-into-state, so bookmark + state restore +
        replay is exactly-once. Topics observed on more than one
        partition are EXCLUDED (logged once): a single merged offset
        cannot bookmark several partitions without skipping the slower
        ones' gap on restore — those topics resume at live instead."""
        with self._lock:
            out = {}
            for topic, offset in self._delivered_offsets.items():
                if len(self._topic_partitions.get(topic, ())) > 1:
                    if topic not in self._multi_partition_logged:
                        self._multi_partition_logged.add(topic)
                        logger.warning(
                            "topic %s spans multiple partitions: "
                            "excluded from checkpoint bookmarks "
                            "(restart resumes it at the high "
                            "watermark)",
                            topic,
                        )
                    continue
                out[topic] = offset
            return out

    @property
    def metrics(self) -> dict[str, int]:
        with self._lock:
            return {
                "queued_batches": len(self._queue),
                "dropped_batches": self._dropped_batches,
                "consumed_messages": self._consumed_messages,
            }

"""Per-service routing assembly (reference: kafka/routes.py:33
RoutingAdapterBuilder): fluent construction of the topic -> schema ->
adapter tree each service consumes."""

from __future__ import annotations

from .message_adapter import (
    CommandsAdapter,
    KafkaToAd00Adapter,
    KafkaToDa00Adapter,
    KafkaToDetectorEventsAdapter,
    KafkaToF144Adapter,
    KafkaToMonitorEventsAdapter,
    KafkaToRunControlAdapter,
    MessageAdapter,
    NullAdapter,
    RouteBySchemaAdapter,
    RouteByTopicAdapter,
)
from .stream_mapping import StreamMapping

__all__ = ["RoutingAdapterBuilder"]


class RoutingAdapterBuilder:
    def __init__(
        self,
        *,
        stream_mapping: StreamMapping,
        batch_decode: bool | None = None,
    ) -> None:
        #: Forwarded to the ev44 adapters (ADR 0125): None defers to the
        #: LIVEDATA_BATCH_DECODE env gate at adapter construction.
        self._mapping = stream_mapping
        self._batch_decode = batch_decode
        self._routes: dict[str, MessageAdapter] = {}

    def _add_topics(self, topics, adapter: MessageAdapter) -> None:
        for topic in topics:
            existing = self._routes.get(topic)
            if isinstance(existing, RouteBySchemaAdapter):
                raise ValueError(f"Topic {topic} already routed")
            self._routes[topic] = adapter

    def with_detector_route(self, *, merge_detectors: bool = False):
        self._add_topics(
            self._mapping.detector_topics,
            RouteBySchemaAdapter(
                {
                    "ev44": KafkaToDetectorEventsAdapter(
                        self._mapping,
                        merge_detectors=merge_detectors,
                        batch_wire=self._batch_decode,
                    )
                }
            ),
        )
        return self

    def with_monitor_route(self):
        self._add_topics(
            self._mapping.monitor_topics,
            RouteBySchemaAdapter(
                {
                    "ev44": KafkaToMonitorEventsAdapter(
                        self._mapping, batch_wire=self._batch_decode
                    ),
                    "da00": KafkaToDa00Adapter(self._mapping),
                }
            ),
        )
        return self

    def with_area_detector_route(self):
        self._add_topics(
            self._mapping.area_detector_topics,
            RouteBySchemaAdapter({"ad00": KafkaToAd00Adapter(self._mapping)}),
        )
        return self

    def with_logdata_route(self):
        # Forwarder log topics interleave f144 numeric data with al00
        # (alarm) and ep01 (connection status) for the same PVs
        # (reference: kafka/routes.py:103-121); those are expected
        # traffic, dropped deliberately rather than counted unrouted.
        self._add_topics(
            self._mapping.log_topics,
            RouteBySchemaAdapter(
                {
                    "f144": KafkaToF144Adapter(self._mapping),
                    "al00": NullAdapter(),
                    "ep01": NullAdapter(),
                }
            ),
        )
        return self

    def with_run_control_route(self):
        self._add_topics(
            self._mapping.run_control_topics,
            RouteBySchemaAdapter(
                {
                    "pl72": KafkaToRunControlAdapter(),
                    "6s4t": KafkaToRunControlAdapter(),
                }
            ),
        )
        return self

    def with_commands_route(self):
        self._routes[self._mapping.livedata.commands] = CommandsAdapter()
        self._routes[self._mapping.livedata.roi] = CommandsAdapter()
        return self

    def build(self) -> RouteByTopicAdapter:
        return RouteByTopicAdapter(self._routes)

"""File-backed broker: multi-process pub/sub without a Kafka deployment.

The integration test layer (tests/integration/, reference
tests/integration/backend.py) spawns real service subprocesses and a real
dashboard process and needs a broker they can all reach. Docker is not
available in every environment this runs in, so topics are append-only
files in a shared directory:

    <root>/<topic>.log     frames of [key_len u32][value_len u32][key][value]

Appends happen under an exclusive ``flock`` and as a single ``write`` so
concurrent producers interleave only at frame boundaries; consumers track
a *byte* offset per topic and only surface complete frames, so a reader
racing a writer sees the prefix. Offsets double as Kafka watermarks
(low = 0, high = file size), which lets ``assign_all_partitions`` pin a
restarted service at live data exactly as it does against a real broker.

This is a test/dev transport: single partition per topic, no retention,
no replication. The point is that every byte still crosses a process
boundary through the same consumer/producer protocols the confluent
client implements, so crash/restart/adoption scenarios exercise the real
code paths.
"""

from __future__ import annotations

import fcntl
import logging
import struct
import time
from pathlib import Path

__all__ = [
    "FileBrokerConsumer",
    "FileBrokerProducer",
    "ensure_topics",
]

logger = logging.getLogger(__name__)

_HEADER = struct.Struct("<II")


def _topic_path(root: Path, topic: str) -> Path:
    if "/" in topic or topic.startswith("."):
        raise ValueError(f"Invalid topic name {topic!r}")
    return root / f"{topic}.log"


def ensure_topics(root: str | Path, topics) -> None:
    """Create empty topic files (the broker-side 'create topics' admin op;
    consumers validate topic existence at startup)."""
    root = Path(root)
    root.mkdir(parents=True, exist_ok=True)
    for topic in topics:
        _topic_path(root, topic).touch()


class FileMessage:
    """confluent_kafka.Message-shaped record."""

    __slots__ = ("_topic", "_value", "_key", "_next_offset")

    def __init__(
        self,
        topic: str,
        value: bytes,
        key: bytes | None,
        next_offset: int = -1,
    ) -> None:
        self._topic = topic
        self._value = value
        self._key = key
        self._next_offset = next_offset

    def topic(self) -> str:
        return self._topic

    def value(self) -> bytes:
        return self._value

    def key(self) -> bytes | None:
        return self._key

    def next_offset(self) -> int:
        """The byte offset a consumer resuming AFTER this message
        should seek to (the durability plane's bookmark unit on this
        broker, ADR 0118). File-broker offsets are byte positions —
        the confluent path uses message ``offset() + 1`` instead; the
        transport layer (kafka/source.py) probes for whichever the
        message carries."""
        return self._next_offset

    def error(self):
        return None


class FileBrokerProducer:
    def __init__(self, root: str | Path) -> None:
        self._root = Path(root)
        self._root.mkdir(parents=True, exist_ok=True)

    def produce(self, topic: str, value: bytes, key=None) -> None:
        if isinstance(key, str):
            key = key.encode()
        frame = (
            _HEADER.pack(len(key or b""), len(value))
            + (key or b"")
            + value
        )
        path = _topic_path(self._root, topic)
        with open(path, "ab") as f:
            fcntl.flock(f.fileno(), fcntl.LOCK_EX)
            try:
                f.write(frame)
                f.flush()
            finally:
                fcntl.flock(f.fileno(), fcntl.LOCK_UN)

    def poll(self, timeout: float = 0.0) -> int:
        return 0

    def flush(self, timeout: float = 0.0) -> int:
        return 0


class _TopicMeta:
    def __init__(self) -> None:
        self.partitions = {0: object()}


class _Metadata:
    def __init__(self, names) -> None:
        self.topics = {name: _TopicMeta() for name in names}


class FileBrokerConsumer:
    """Both halves of the consumer surface: the assignment handshake
    (list_topics/get_watermark_offsets/assign) and the consume loop."""

    def __init__(self, root: str | Path) -> None:
        self._root = Path(root)
        # topic -> next byte offset to read
        self._offsets: dict[str, int] = {}
        # round-robin cursor over topics (see _consume_once)
        self._rr = 0

    # -- assignment surface ------------------------------------------------
    def list_topics(self, timeout: float = 0.0) -> _Metadata:
        return _Metadata(
            p.stem for p in sorted(self._root.glob("*.log"))
        )

    def get_watermark_offsets(
        self, partition, timeout: float = 0.0
    ) -> tuple[int, int]:
        path = _topic_path(self._root, partition.topic)
        return (0, path.stat().st_size if path.exists() else 0)

    def assign(self, partitions) -> None:
        for tp in partitions:
            offset = getattr(tp, "offset", -1)
            if offset is None or offset < 0:
                offset = 0
            self._offsets[tp.topic] = offset

    def subscribe(self, topics) -> None:
        """Subscribe-at-end (the dashboard's live-data semantics)."""
        for topic in topics:
            path = _topic_path(self._root, topic)
            self._offsets[topic] = (
                path.stat().st_size if path.exists() else 0
            )

    # -- consume loop ------------------------------------------------------
    def consume(self, num_messages: int, timeout: float = 0.0):
        out = self._consume_once(num_messages)
        if not out and timeout > 0:
            # Honor the blocking contract the confluent client has: the
            # service consume thread loops on consume() with no sleep of
            # its own, so returning instantly on empty would busy-spin a
            # core per service doing stat() calls.
            time.sleep(timeout)
            out = self._consume_once(num_messages)
        return out

    def _consume_once(self, num_messages: int) -> list[FileMessage]:
        out: list[FileMessage] = []
        # Rotate the starting topic across calls: with a fixed order, a
        # sustained high-volume first topic (detector data) would fill the
        # whole budget every call and starve status/command topics.
        topics = list(self._offsets)
        if not topics:
            return out
        self._rr %= len(topics)
        order = topics[self._rr:] + topics[: self._rr]
        self._rr = (self._rr + 1) % len(topics)
        for topic in order:
            if len(out) >= num_messages:
                break
            out.extend(
                self._read_topic(topic, num_messages - len(out))
            )
        return out

    def _read_topic(self, topic: str, limit: int) -> list[FileMessage]:
        path = _topic_path(self._root, topic)
        try:
            size = path.stat().st_size
        except FileNotFoundError:
            return []
        offset = self._offsets.get(topic, 0)
        if size <= offset:
            return []
        out: list[FileMessage] = []
        with open(path, "rb") as f:
            f.seek(offset)
            while len(out) < limit:
                header = f.read(_HEADER.size)
                if len(header) < _HEADER.size:
                    break
                key_len, value_len = _HEADER.unpack(header)
                payload = f.read(key_len + value_len)
                if len(payload) < key_len + value_len:
                    # Partial frame: a writer is mid-append; retry later.
                    break
                offset = f.tell()
                out.append(
                    FileMessage(
                        topic,
                        payload[key_len:],
                        payload[:key_len] or None,
                        next_offset=offset,
                    )
                )
        self._offsets[topic] = offset
        return out

    def positions(self) -> dict[str, int]:
        """Next-read byte offset per assigned topic — the consumer-side
        bookmark surface (durability plane, ADR 0118)."""
        return dict(self._offsets)

    def close(self) -> None:
        self._offsets.clear()

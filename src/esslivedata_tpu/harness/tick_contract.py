"""Tick-program contract registry: the bridge graftlint's trace pass
lowers (ADR 0123).

Every workflow family whose hot path is the one-dispatch tick program
(ADR 0114) registers a :class:`TickProgramSpec` here: a device-free
builder that constructs a small synthetic instance of the family and
assembles the EXACT jitted program the live ``JobManager`` would
dispatch — same ``event_ingest`` offer, same ``publish_offer``, same
``plan_members`` plan, same ``TickCombiner._build`` — against a
zero-filled padded batch (the ``plan_warmup`` extraction pattern:
offers are side-effect free, lowering reads avals, never values).

The trace pass (``tools/graftlint/trace``) AOT-lowers each build under
``JAX_PLATFORMS=cpu`` and proves the performance contract statically:
one executable per tick (JGL101), every rolling-state invar donated in
the lowered computation (JGL102), digest-keyed table swaps re-lower to
an identical program (JGL103), no host callbacks in the traced body
(JGL104), and output avals matching the family's declared wire schema
(JGL105 — the ``TICK_WIRE_SCHEMA`` constant each family module pins
next to its publish program).

Builders run on the CPU backend with no accelerator attached; the
synthetic geometries are deliberately tiny (a 12x12 logical grid, 48
calibrated pixels) so a full registry sweep lowers in seconds. The
``variant`` argument selects the table epoch: ``"base"`` is the
shipped configuration, ``"swap"`` rebuilds with a different
digest-keyed table of identical shapes (a recalibration, a flat-field
update, a re-centred Q map) — the JGL103 proof compares the two
lowerings byte for byte.
"""

from __future__ import annotations

import dataclasses
import importlib
import inspect
from collections.abc import Callable, Mapping
from dataclasses import dataclass

import numpy as np

__all__ = [
    "ContractBuildError",
    "REGISTRY",
    "TickProgram",
    "TickProgramBuild",
    "TickProgramSpec",
    "iter_contracts",
    "register_tick_program",
]

#: Synthetic staged-batch padding: one power-of-two bucket, matching
#: what a quiet live stream carries (ops/event_batch.bucket_size).
_PADDED = 256


class ContractBuildError(RuntimeError):
    """A family's builder could not assemble its tick program — the
    family is NOT contract-verifiable, which the trace pass reports as
    a run error (never a silent skip)."""


@dataclass(frozen=True)
class TickProgram:
    """One lowered-checkable program of a family's tick.

    ``state_positions`` are the flat argument positions that hold
    rolling device state (``args[0]`` of each planned member — the
    ``make_publish_offer`` contract), derived from the *protocol*, not
    from the publisher's declared ``donate`` tuple, so JGL102 proves
    donation rather than echoing the call site. ``staged_positions``
    are the shared staged-wire arguments, which must NEVER be donated
    (other window consumers hold references). ``outputs`` is the
    abstract output tree of the member publish program(s) —
    name -> ``jax.ShapeDtypeStruct``.
    """

    label: str
    fn: Callable
    args: tuple
    state_positions: tuple[int, ...]
    staged_positions: tuple[int, ...]
    outputs: Mapping[str, object]


@dataclass(frozen=True)
class TickProgramBuild:
    """Builder result: the tick's program set plus identity-free
    program-key material (staged + member arg signatures, static split,
    inclusion flags) — what :meth:`~..ops.tick.TickCombiner._program_key`
    keys on with object identities erased, so two independently built
    epochs can be compared for swap-stability."""

    programs: tuple[TickProgram, ...]
    key_material: tuple


@dataclass(frozen=True)
class TickProgramSpec:
    family: str
    build: Callable[[str], TickProgramBuild]
    #: Declared wire schema: output name -> (ndim, dtype name). The
    #: family module pins this next to its publish program
    #: (``TICK_WIRE_SCHEMA``); JGL105 proves the traced avals match.
    wire_schema: Mapping[str, tuple[int, str]]
    #: ``"module.path:ClassName"`` of the owning workflow — findings
    #: anchor to its defining file so suppressions/baselines work.
    anchor: str
    #: What the ``"swap"`` variant swaps (None: the family has no
    #: digest-keyed table and JGL103 does not apply).
    swap_variant: str | None = None
    #: The factored halves of ``build``: ``make_workflow(variant)``
    #: constructs the synthetic workflow instance, ``assemble(wf)``
    #: turns one into the tick program. ``build`` is their composition.
    #: The protocol pass (JGL205) needs them separately: it dumps one
    #: instance's state into a second and re-assembles, proving the
    #: checkpoint codec round-trips the family at lowering level.
    make_workflow: Callable[[str], object] | None = None
    assemble: Callable[[object], TickProgramBuild] | None = None

    def source_location(self) -> tuple[str, int]:
        """(repo-relative path, line) of the owning workflow class;
        falls back to this registry when the anchor will not resolve."""
        try:
            mod_name, cls_name = self.anchor.split(":")
            cls = getattr(importlib.import_module(mod_name), cls_name)
            path = inspect.getsourcefile(cls)
            line = inspect.getsourcelines(cls)[1]
            return _repo_relative(path), line
        except Exception:
            return _repo_relative(__file__), 1


def _repo_relative(path: str) -> str:
    """Best-effort repo-relative form (``src/...``) so trace findings
    match the paths the static passes lint (suppression + baseline
    matching is path-keyed)."""
    import os

    p = os.path.abspath(path)
    for cwd in (os.getcwd(),):
        if p.startswith(cwd + os.sep):
            return os.path.relpath(p, cwd)
    return path


REGISTRY: dict[str, TickProgramSpec] = {}


def register_tick_program(
    family: str,
    *,
    anchor: str,
    wire_schema: Mapping[str, tuple[int, str]],
    swap_variant: str | None = None,
    stream: str | None = None,
) -> Callable:
    """Register ``make_workflow(variant) -> workflow`` for a family.

    ``stream`` names the synthetic event stream the tick ingests (an
    event family assembles via :func:`event_family_build`); None marks
    a publish-only family (:func:`publish_family_build`). The spec's
    ``build`` stays the one-call composition the trace pass lowers;
    the factored halves let the protocol pass re-assemble a restored
    instance (JGL205)."""

    def register(make_workflow: Callable[[str], object]):
        if family in REGISTRY:
            raise ValueError(f"duplicate tick-contract family {family!r}")
        if stream is None:
            def assemble(workflow) -> TickProgramBuild:
                return publish_family_build(workflow)
        else:
            def assemble(workflow) -> TickProgramBuild:
                return event_family_build(workflow, stream=stream)

        def build(variant: str) -> TickProgramBuild:
            return assemble(make_workflow(variant))

        REGISTRY[family] = TickProgramSpec(
            family=family,
            build=build,
            wire_schema=dict(wire_schema),
            anchor=anchor,
            swap_variant=swap_variant,
            make_workflow=make_workflow,
            assemble=assemble,
        )
        return make_workflow

    return register


def iter_contracts() -> list[TickProgramSpec]:
    return [REGISTRY[name] for name in sorted(REGISTRY)]


# -- shared assembly -------------------------------------------------------


def _zero_staged(padded: int = _PADDED):
    """A zero-filled padded window, the ``plan_warmup`` synthetic batch:
    every entry is pixel_id -1 padding, so staging it is value-inert —
    only its signature (and the staged avals) reach the program."""
    from ..ops.event_batch import EventBatch
    from ..preprocessors.event_data import StagedEvents

    return StagedEvents(
        batch=EventBatch(
            pixel_id=np.full(padded, -1, dtype=np.int32),
            toa=np.zeros(padded, dtype=np.float32),
            n_valid=0,
        ),
        first_timestamp=None,
        last_timestamp=None,
        n_chunks=1,
    )


def _member_key_material(plan) -> tuple:
    """``member_signature`` with publisher identity erased: the args
    signature, static split and inclusion flag per member — everything
    identity-free that determines the compiled program."""
    return tuple(
        (req.publisher._signature(req.args), tuple(sorted(skeys)), inc)
        for _i, req, skeys, _spec, _names, inc, _c, _s in plan
    )


def _plan_one(workflow):
    """Plan the workflow's single-member publish exactly as the live
    tick planner would; raises :class:`ContractBuildError` when the
    family is not tick-eligible (that would itself be a regression —
    every registered family rides the one-dispatch tick)."""
    from ..ops.publish import PublishRequest, plan_members

    offer = workflow.publish_offer()
    if offer is None:
        raise ContractBuildError("publish_offer() returned None")
    plan, errors = plan_members(
        [PublishRequest(offer.publisher, offer.args, offer.static_token)]
    )
    if errors or not plan:
        raise ContractBuildError(
            f"publish plan failed: {errors.get(0)!r}"
        )
    return offer, plan


def _member_outputs(offer):
    """Abstract output tree of the member's publish program — the
    JGL105 subject, evaluated with ``jax.eval_shape`` (no compile)."""
    import jax

    return jax.eval_shape(
        lambda *a: offer.publisher._program(*a)[0], *offer.args
    )


def event_family_build(workflow, *, stream: str) -> TickProgramBuild:
    """Assemble the one-dispatch tick program for an event family:
    ingest offer -> publish offer -> planned member -> staged wire ->
    ``TickCombiner._build`` — the exact live composition (ADR 0114),
    against a zero-filled padded batch."""
    from ..ops.publish import PackedPublisher
    from ..ops.tick import TickCombiner

    ingest = workflow.event_ingest(stream, _zero_staged())
    if ingest is None:
        raise ContractBuildError(
            f"event_ingest({stream!r}) declined the synthetic window"
        )
    offer, plan = _plan_one(workflow)
    if not offer.args or offer.args[0] is not ingest.get_state():
        # The _split_tick_groups eligibility check: args[0] IS the
        # rolling ingest state, or the family cannot ride the tick.
        raise ContractBuildError(
            "publish_offer args[0] is not the ingest state — the "
            "family would degrade to separate dispatches"
        )
    staged = ingest.hist.tick_staging(
        ingest.batch, None, batch_tag=ingest.batch_tag
    )
    members = [
        (req.publisher, len(req.args), skeys, inc)
        for _i, req, skeys, _spec, _names, inc, _c, _s in plan
    ]
    fn = TickCombiner()._build(ingest.hist, len(staged), members)
    flat_args = tuple(staged) + tuple(
        a for _i, req, *_ in plan for a in req.args
    )
    return TickProgramBuild(
        programs=(
            TickProgram(
                label="tick",
                fn=fn,
                args=flat_args,
                # Single member: its rolling state sits right behind
                # the staged prefix (the make_publish_offer contract).
                state_positions=(len(staged),),
                staged_positions=tuple(range(len(staged))),
                outputs=_member_outputs(offer),
            ),
        ),
        key_material=(
            PackedPublisher._signature(tuple(staged)),
            _member_key_material(plan),
        ),
    )


def publish_family_build(workflow) -> TickProgramBuild:
    """Assemble the combined-publish program for a non-event family
    (the da00-path workloads): no staged wire, the member's packed
    publish is the whole per-tick dispatch (ADR 0113)."""
    from ..ops.publish import PublishCombiner

    offer, plan = _plan_one(workflow)
    members = [
        (req.publisher, len(req.args), skeys, inc)
        for _i, req, skeys, _spec, _names, inc, _c, _s in plan
    ]
    fn = PublishCombiner._build(members)
    flat_args = tuple(a for _i, req, *_ in plan for a in req.args)
    return TickProgramBuild(
        programs=(
            TickProgram(
                label="publish",
                fn=fn,
                args=flat_args,
                state_positions=(0,),
                staged_positions=(),
                outputs=_member_outputs(offer),
            ),
        ),
        key_material=(None, _member_key_material(plan)),
    )


# -- family registrations --------------------------------------------------
#
# Geometries are the test-suite synthetics (tests/workflows,
# tests/workloads): tiny, deterministic, and shaped like the real
# thing. The "swap" variant of each table-carrying family rebuilds
# with a same-shape different-content table — the digest changes, the
# lowering must not.


def _logical_grid(*, swapped: bool = False) -> np.ndarray:
    det = np.arange(144, dtype=np.int64).reshape(12, 12)
    return np.flipud(det).copy() if swapped else det


@register_tick_program(
    "detector_view",
    anchor="esslivedata_tpu.workflows.detector_view.workflow:"
    "DetectorViewWorkflow",
    wire_schema={},  # installed below, next to the family module's pin
    swap_variant="projection LUT rebuilt from a flipped logical grid",
    stream="det0",
)
def _make_detector_view(variant: str):
    from ..workflows.detector_view.projectors import project_logical
    from ..workflows.detector_view.workflow import DetectorViewWorkflow

    projection = project_logical(_logical_grid(swapped=variant == "swap"))
    return DetectorViewWorkflow(projection=projection)


@register_tick_program(
    "monitor",
    anchor="esslivedata_tpu.workflows.monitor_workflow:MonitorWorkflow",
    wire_schema={},
    stream="mon0",
)
def _make_monitor(variant: str):
    from ..workflows.monitor_workflow import MonitorWorkflow

    return MonitorWorkflow()


@register_tick_program(
    "q_sans",
    anchor="esslivedata_tpu.workflows.sans:SansIQWorkflow",
    wire_schema={},
    swap_variant="Q map rebuilt under a shifted beam centre",
    stream="det0",
)
def _make_q_sans(variant: str):
    from ..workflows.sans import SansIQParams, SansIQWorkflow

    n_pix = 64
    rng = np.random.default_rng(7)
    positions = np.column_stack(
        [
            rng.uniform(-0.3, 0.3, n_pix),
            rng.uniform(-0.3, 0.3, n_pix),
            np.full(n_pix, 5.0),
        ]
    )
    params = SansIQParams(
        beam_center_x=0.01 if variant == "swap" else 0.0
    )
    return SansIQWorkflow(
        positions=positions,
        pixel_ids=np.arange(n_pix),
        params=params,
    )


@register_tick_program(
    "powder_focus",
    anchor="esslivedata_tpu.workloads.powder_focus:PowderFocusWorkflow",
    wire_schema={},
    swap_variant="calibration epoch bumped via with_columns(difc=...)",
    stream="det0",
)
def _make_powder_focus(variant: str):
    from ..workloads.calibration import CalibrationTable
    from ..workloads.powder_focus import PowderFocusWorkflow

    n_pix = 48
    table = CalibrationTable(
        name="contract_cal",
        version=1,
        columns={
            "difc": np.linspace(4000.0, 6000.0, n_pix),
            "tzero": np.full(n_pix, -2.0),
        },
    )
    if variant == "swap":
        table = table.with_columns(
            difc=np.asarray(table.columns["difc"]) * 1.01
        )
    return PowderFocusWorkflow(calibration=table)


@register_tick_program(
    "imaging",
    anchor="esslivedata_tpu.workloads.imaging:ImagingViewWorkflow",
    wire_schema={},
    swap_variant="flat-field table swapped via set_flatfield's epoch",
    stream="det0",
)
def _make_imaging(variant: str):
    from ..workloads.calibration import CalibrationTable
    from ..workloads.imaging import ImagingViewWorkflow

    ny, nx = 8, 8
    det = np.arange(ny * nx, dtype=np.int64).reshape(ny, nx)
    flat = np.ones(ny * nx, dtype=np.float32)
    if variant == "swap":
        flat = flat * 1.25
    calibration = CalibrationTable(
        name="contract_ff", version=1, columns={"flatfield": flat}
    )
    return ImagingViewWorkflow(detector_number=det, calibration=calibration)


@register_tick_program(
    "correlation",
    anchor="esslivedata_tpu.workloads.correlation:"
    "TimeseriesCorrelationWorkflow",
    wire_schema={},
)
def _make_correlation(variant: str):
    from ..workloads.correlation import TimeseriesCorrelationWorkflow

    return TimeseriesCorrelationWorkflow(streams=("a", "b", "c"))


def _install_wire_schemas() -> None:
    """Adopt each family module's ``TICK_WIRE_SCHEMA`` pin. Kept IN the
    family modules (next to the publish programs they constrain) so a
    program edit and its schema ride the same diff; resolved lazily so
    importing this registry stays cheap."""
    anchors = {
        "detector_view": (
            "esslivedata_tpu.workflows.detector_view.workflow"
        ),
        "monitor": "esslivedata_tpu.workflows.monitor_workflow",
        "q_sans": "esslivedata_tpu.workflows.qshared",
        "powder_focus": "esslivedata_tpu.workloads.powder_focus",
        "imaging": "esslivedata_tpu.workloads.imaging",
        "correlation": "esslivedata_tpu.workloads.correlation",
    }
    for family, module_name in anchors.items():
        module = importlib.import_module(module_name)
        schema = getattr(module, "TICK_WIRE_SCHEMA")
        REGISTRY[family] = dataclasses.replace(
            REGISTRY[family], wire_schema=dict(schema)
        )


_SCHEMAS_INSTALLED = False


def load_registry() -> list[TickProgramSpec]:
    """The trace pass's entry point: registrations plus the family
    modules' wire-schema pins, resolved once."""
    global _SCHEMAS_INSTALLED
    if not _SCHEMAS_INSTALLED:
        _install_wire_schemas()
        _SCHEMAS_INSTALLED = True
    return iter_contracts()

"""Deterministic fault injection for the serving path (ADR 0120).

The containment code claims to survive four fault classes: a
post-donation dispatch failure (``note_state_lost`` + re-seed, ADR
0113/0114/0118), wedged/slow SSE subscribers (bounded queues +
coalesce-to-keyframe, ADR 0117), slow-tick storms (watchdog +
link-policy backoff, ADR 0111/0116), and a consumer restart mid-window
(replay through the normal ingest path, ADR 0118). This module injects
exactly those faults — through hooks the production classes already
carry (``JobManager.set_chaos``, ``IngestPipeline.set_chaos``,
``BroadcastServer.set_chaos``) — behind a **seeded schedule**, so a
chaos run is an ordinary deterministic test: same spec, same seed,
same windows => same faults at the same ticks.

Two scheduling modes, combinable per site:

- ``at``: explicit fire ticks — ``{"tick_dispatch": {5, 17}}`` fails
  the 6th and 18th consultation of that site. Exact, reviewable; what
  the bench scenario and the tests use.
- ``rate``: a per-consultation Bernoulli draw from a per-site
  ``random.Random`` seeded with ``(seed, site)`` — reproducible
  *storms* whose density scales with run length.

Each site keeps its own consultation counter, so determinism holds per
site regardless of interleaving across sites. Counters and draws are
lock-guarded: sites are consulted from worker threads (decode worker,
step worker, subscriber drains).

Every fired injection counts into
``livedata_chaos_injections_total{site}`` — the SLO gate reads it to
prove the chaos actually ran (a green gate over a chaos run that
injected nothing proves nothing).

``ChaosError`` deliberately subclasses ``RuntimeError``: the
containment sites catch ``Exception`` and must treat an injected fault
exactly like a real one — no special-casing, or the drill stops
rehearsing the incident.
"""

from __future__ import annotations

import threading
import time
import zlib
from collections.abc import Mapping
from dataclasses import dataclass, field
from random import Random

from ..telemetry.registry import REGISTRY

__all__ = [
    "CHAOS_INJECTIONS",
    "ChaosError",
    "ChaosSchedule",
    "ChaosSpec",
    "SITES",
]

#: The known injection sites and who consults them:
#:
#: ==================  ====================================================
#: site                consulted by
#: ==================  ====================================================
#: ``tick_dispatch``   JobManager._run_tick_programs, AFTER the dispatch —
#:                     a fire is a post-donation failure (state_lost path)
#: ``slow_tick``       JobManager.process_jobs entry — a fire stalls the
#:                     window (slow-tick storm)
#: ``decode_stall``    IngestPipeline decode worker — a fire stalls the
#:                     decode stage (pipeline backpressure)
#: ``subscriber_stall``  Subscription.next_blob_meta — a fire stalls that
#:                     consumer's dequeue (slow/wedged SSE reader)
#: ``consumer_restart``  harness/load.py's drive loop — a fire pauses
#:                     ingest for ``restart_gap_windows`` (the consume
#:                     thread died and came back; accumulation must show
#:                     a gap, never a reset)
#: ``relay_upstream_drop``  fleet/relay.py's pump/worker loop — a fire
#:                     drops the relay's upstream subscription(s) so it
#:                     must reconnect and resync (ADR 0121); downstream
#:                     subscribers must see at most one resync keyframe
#:                     per stream and NO unsignaled reset
#: ==================  ====================================================
SITES = (
    "tick_dispatch",
    "slow_tick",
    "decode_stall",
    "subscriber_stall",
    "consumer_restart",
    "relay_upstream_drop",
)

CHAOS_INJECTIONS = REGISTRY.counter(
    "livedata_chaos_injections",
    "Faults fired by the chaos schedule (harness/chaos.py), by site",
    labelnames=("site",),
)


class ChaosError(RuntimeError):
    """An injected fault. Containment must treat it like any real
    failure (it arrives through the same ``except Exception`` paths)."""


@dataclass(frozen=True)
class ChaosSpec:
    """Declarative schedule: which sites fire when (see module docs).

    ``delay_s`` parameterizes the stall sites (how long a fired stall
    sleeps); raise-sites ignore it. Frozen so a spec can be embedded in
    a bench line / test id and re-run verbatim.
    """

    seed: int = 0
    #: site -> explicit consultation indices (0-based) that fire.
    at: Mapping[str, frozenset[int]] = field(default_factory=dict)
    #: site -> per-consultation fire probability in [0, 1].
    rate: Mapping[str, float] = field(default_factory=dict)
    #: site -> stall duration for delay sites (seconds).
    delay_s: Mapping[str, float] = field(default_factory=dict)
    #: windows of ingest silence per fired ``consumer_restart``.
    restart_gap_windows: int = 3

    def with_site(self, site: str, ticks) -> "ChaosSpec":
        """A copy with explicit fire ticks added for ``site``."""
        merged = dict(self.at)
        merged[site] = frozenset(ticks)
        return ChaosSpec(
            seed=self.seed,
            at=merged,
            rate=dict(self.rate),
            delay_s=dict(self.delay_s),
            restart_gap_windows=self.restart_gap_windows,
        )


class ChaosSchedule:
    """The live consultable form of a :class:`ChaosSpec`."""

    def __init__(self, spec: ChaosSpec | None = None, **kwargs) -> None:
        self.spec = spec if spec is not None else ChaosSpec(**kwargs)
        self._lock = threading.Lock()
        self._counts: dict[str, int] = {}
        self._fired: dict[str, int] = {}
        # Per-site RNG streams: (seed, site) keyed so adding a site to
        # the spec never shifts another site's draw sequence.
        self._rngs = {
            site: Random((self.spec.seed << 32) ^ zlib.crc32(site.encode()))
            for site in set(self.spec.rate)
        }

    # -- consultation -------------------------------------------------------
    def fires(self, site: str) -> bool:
        """Advance ``site``'s consultation counter; True when this
        consultation is scheduled to fault."""
        with self._lock:
            tick = self._counts.get(site, 0)
            self._counts[site] = tick + 1
            fired = tick in self.spec.at.get(site, ())
            rng = self._rngs.get(site)
            if not fired and rng is not None:
                fired = rng.random() < self.spec.rate.get(site, 0.0)
            if fired:
                self._fired[site] = self._fired.get(site, 0) + 1
        if fired:
            CHAOS_INJECTIONS.labels(site=site).inc()
        return fired

    def check(self, site: str) -> None:
        """Raise :class:`ChaosError` when ``site`` fires (raise-sites:
        ``tick_dispatch``)."""
        if self.fires(site):
            raise ChaosError(f"injected fault at {site}")

    def maybe_delay(self, site: str) -> None:
        """Sleep the site's configured stall when it fires (delay
        sites). Callers hold NO locks here by contract — the stall
        models slow work, not a lock convoy (graftlint JGL023)."""
        if self.fires(site):
            time.sleep(self.spec.delay_s.get(site, 0.05))

    # -- reporting ----------------------------------------------------------
    def injected(self) -> dict[str, int]:
        """Faults fired so far, by site (the harness report embeds it;
        the SLO gate cross-checks the registry counter)."""
        with self._lock:
            return dict(self._fired)

    def consultations(self) -> dict[str, int]:
        with self._lock:
            return dict(self._counts)

"""Production-traffic harness (ADR 0120): deterministic chaos + load.

The serving stack asserts mechanism invariants (1 dispatch/tick, flat
fan-out bytes) all over its test suite; this package asserts the
*product* under adversity. ``chaos`` is the seeded fault-injection
schedule the JobManager / ingest pipeline / broadcast hub consult;
``load`` drives fake producers and simulated SSE subscribers through
the real serving path and reports the SLO surface
(``scripts/slo_gate.py`` evaluates it, ``bench.py --slo`` grades it).
"""

from .chaos import ChaosError, ChaosSchedule, ChaosSpec
from .load import LoadConfig, LoadHarness

__all__ = [
    "ChaosError",
    "ChaosSchedule",
    "ChaosSpec",
    "LoadConfig",
    "LoadHarness",
]

"""Explicit state-machine models of the guarded distributed protocols
(graftlint protocol pass, JGL200-series — the ADR 0124 companion of
``tick_contract.py``).

Each model writes one protocol down as a tiny, explicitly-enumerable
transition system whose *shape* mirrors the owning source module:

- :class:`CheckpointModel` — the write-tmp/fsync/rename/gc discipline
  of ``durability/checkpoint.py``, with a crash candidate at every
  micro-step boundary (each ``os.replace``/fsync is one transition).
- :class:`ReplayModel` — the quiescent-checkpoint + seek-to-bookmark
  exactly-once arithmetic of ``core/orchestrating_processor.py`` and
  ``durability/replay.py``.
- :class:`RelayModel` — the resync classification of
  ``fleet/relay.py`` over ``<boot>:<epoch>:<seq>`` ids.
- :class:`FleetModel` — rendezvous ownership of
  ``fleet/assignment.py`` under membership churn, using the REAL
  :func:`~..fleet.assignment.rendezvous_owner` (the model checks the
  protocol around the hash, never a reimplementation of the hash).
- :class:`EpochModel` — the epoch-bump⇒keyframe discipline spanning
  ``core/job.py`` and ``serving/delta.py``.

Models are **parameterized by source-derived facts**: the protocol
pass's binding layer (``tools/graftlint/protocol/bindings.py``)
inspects the real functions with the v3 dataflow machinery and answers
questions like "does ``atomic_write`` fsync before ``os.replace`` on
every path?". A guard that is present keeps its transition in the
model; a guard the source has lost WEAKENS the model, and exhaustive
exploration then finds the interleaving/crash point the guard existed
to exclude — reported with a minimal counterexample trace under the
invariant's own rule id (JGL201–JGL204), not as generic drift.

Crash semantics are *pessimistic and deterministic*: at a crash, every
non-durable artifact is lost (a rename without a directory fsync is
undone, file content never fsynced is torn). Sound for safety — the
adversarial disk does the worst thing it is allowed to — and it keeps
the crash branch singular, so state spaces stay in the hundreds.

This module imports no jax and is importable everywhere the static
passes run; only ``fleet.assignment`` (pure Python) is reached.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import ClassVar, Hashable, NamedTuple

__all__ = [
    "CheckpointModel",
    "EpochModel",
    "FleetModel",
    "MODELS",
    "ProtocolModel",
    "ReplayModel",
    "RelayModel",
    "Step",
    "build_model",
]


@dataclass(frozen=True)
class Step:
    """One enabled transition out of a state.

    ``invisible`` marks a transition the explorer may use for
    partial-order reduction: the model asserts it commutes with every
    other enabled transition AND cannot change the invariant's verdict
    on any state it is taken from (the ample-set conditions). Flag
    conservatively — a wrongly-flagged transition hides interleavings.
    """

    label: str
    target: Hashable
    invisible: bool = False


@dataclass
class ProtocolModel:
    """Base: a named, fact-parameterized transition system.

    Subclasses define ``FACTS`` (every fact key they understand, all
    defaulting True = "the guard is present in the source"), ``RULE``
    (the invariant's finding code) and the three exploration hooks.
    """

    facts: dict[str, bool] = field(default_factory=dict)

    NAME: ClassVar[str] = ""
    RULE: ClassVar[str] = ""
    FACTS: ClassVar[tuple[str, ...]] = ()

    def __post_init__(self) -> None:
        unknown = set(self.facts) - set(self.FACTS)
        if unknown:
            raise ValueError(
                f"{self.NAME} model: unknown fact(s) {sorted(unknown)}"
            )
        merged = {key: True for key in self.FACTS}
        merged.update(self.facts)
        self.facts = merged

    def fact(self, key: str) -> bool:
        return self.facts[key]

    # -- exploration hooks --------------------------------------------------
    def initial(self) -> Hashable:
        raise NotImplementedError

    def steps(self, state: Hashable) -> list[Step]:
        raise NotImplementedError

    def invariant(self, state: Hashable) -> str | None:
        """A violation message for ``state``, or None. Most models
        stamp the message into the state at the offending transition
        and just read it back here."""
        raise NotImplementedError


# ---------------------------------------------------------------------------
# Model 1: checkpoint write/GC with crash points (JGL202)
# ---------------------------------------------------------------------------

#: Artifact lifecycle phases (one per ``atomic_write`` micro-step).
_ABSENT, _TMP, _RENAMED, _DURABLE = 0, 1, 2, 3


class _CkptState(NamedTuple):
    pc: int
    s2_phase: int
    s2_synced: bool
    m2_phase: int
    m2_synced: bool
    g1_present: bool
    crashed: bool
    crash_msg: str  # invariant verdict, computed at crash time


class CheckpointModel(ProtocolModel):
    """``CheckpointPlane.checkpoint`` as micro-steps over two artifacts
    (the generation-2 state file and manifest) plus GC of generation 1,
    starting from a durable generation 1 and ``keep=1`` (the smallest
    retention where GC has teeth). A crash is enabled at every step
    boundary; the invariant is JGL202's first clause: after ANY crash,
    at least one fully-consistent generation is recoverable by the
    ``load_latest_manifest`` fallback walk."""

    NAME = "checkpoint"
    RULE = "JGL202"
    FACTS = (
        "atomic_write.fsync_file",
        "atomic_write.fsync_dir",
        "checkpoint.states_before_manifest",
        "checkpoint.gc_after_manifest",
    )

    def _program(self) -> list[str]:
        """The writer's micro-step sequence, shaped by the facts: a
        missing fsync drops its step, a wrong ordering reorders the
        blocks exactly as the mutated source would execute them."""
        write = ["write_tmp", "fsync_tmp", "rename", "fsync_dir"]
        if not self.fact("atomic_write.fsync_file"):
            write.remove("fsync_tmp")
        if not self.fact("atomic_write.fsync_dir"):
            write.remove("fsync_dir")
        states = [f"state2.{op}" for op in write]
        manifest = [f"manifest2.{op}" for op in write]
        if not self.fact("checkpoint.states_before_manifest"):
            # Manifest-first source order: the GC call keeps its place
            # right after the manifest write inside checkpoint().
            return manifest + ["gc_generation1"] + states
        if not self.fact("checkpoint.gc_after_manifest"):
            return states + ["gc_generation1"] + manifest
        return states + manifest + ["gc_generation1"]

    def initial(self) -> _CkptState:
        return _CkptState(0, _ABSENT, False, _ABSENT, False, True, False, "")

    def _apply(self, state: _CkptState, op: str) -> _CkptState:
        if op == "gc_generation1":
            return state._replace(g1_present=False)
        artifact, micro = op.split(".")
        phase_f, sync_f = (
            ("s2_phase", "s2_synced")
            if artifact == "state2"
            else ("m2_phase", "m2_synced")
        )
        phase = getattr(state, phase_f)
        synced = getattr(state, sync_f)
        if micro == "write_tmp":
            phase, synced = _TMP, False
        elif micro == "fsync_tmp":
            synced = True
        elif micro == "rename":
            phase = _RENAMED
        elif micro == "fsync_dir":
            phase = _DURABLE
        return state._replace(**{phase_f: phase, sync_f: synced})

    @staticmethod
    def _after_crash(phase: int, synced: bool) -> str:
        """What the adversarial disk leaves of one artifact: 'ok',
        'torn' (entry survived, content never fsynced) or 'absent'."""
        if phase == _DURABLE:
            return "ok" if synced else "torn"
        return "absent"

    def _recoverable(self, state: _CkptState) -> bool:
        """``load_latest_manifest``'s walk over the post-crash disk:
        newest manifest first, a generation counts only when its
        manifest is readable AND its state file matches the digest."""
        m2 = self._after_crash(state.m2_phase, state.m2_synced)
        s2 = self._after_crash(state.s2_phase, state.s2_synced)
        if m2 == "ok" and s2 == "ok":
            return True
        # Torn/absent newest generation: fall back to generation 1.
        return state.g1_present

    def steps(self, state: _CkptState) -> list[Step]:
        if state.crashed:
            return []
        program = self._program()
        out: list[Step] = []
        if state.pc < len(program):
            op = program[state.pc]
            out.append(
                Step(op, self._apply(state, op)._replace(pc=state.pc + 1))
            )
        # A crash candidate at every os.replace/fsync boundary (and
        # everywhere between): the defining feature of the model.
        crashed = state._replace(crashed=True)
        if not self._recoverable(state):
            crashed = crashed._replace(
                crash_msg=(
                    "a crash here leaves NO consistent checkpoint "
                    "generation on disk (newest manifest torn or its "
                    "state file unrecoverable, older generation "
                    "already garbage-collected)"
                )
            )
        out.append(Step("crash", crashed))
        return out

    def invariant(self, state: _CkptState) -> str | None:
        return state.crash_msg or None


# ---------------------------------------------------------------------------
# Model 2: restore/replay exactly-once bookmark arithmetic (JGL202)
# ---------------------------------------------------------------------------


class _ReplayState(NamedTuple):
    next_consume: int
    pending: tuple[int, ...]  # batcher queue (message ids, in order)
    inflight: tuple[int, ...]  # pipeline queue
    counts: tuple[int, ...]  # per-message apply count (the state)
    ckpt: tuple[int, tuple[int, ...]] | None  # (bookmark, counts)
    crashed: bool
    crashes_left: int


_N_MESSAGES = 3


class ReplayModel(ProtocolModel):
    """Consume → batch → apply over three messages, with checkpoint,
    crash and restore+replay transitions. ``_maybe_checkpoint``'s
    quiescence gate (batcher pending == 0, pipeline inflight == 0) is
    the modeled guard: without it a bookmark taken mid-window names an
    offset ahead of the dumped state, and the replay silently skips
    the buffered tail. Invariant (JGL202, second clause): every
    message is applied exactly once by the time the stream drains."""

    NAME = "replay"
    RULE = "JGL202"
    FACTS = ("checkpoint.quiescent_gate",)

    def initial(self) -> _ReplayState:
        return _ReplayState(0, (), (), (0,) * _N_MESSAGES, None, False, 1)

    def steps(self, state: _ReplayState) -> list[Step]:
        out: list[Step] = []
        if state.crashed:
            bookmark, counts = state.ckpt if state.ckpt else (0, (0,) * _N_MESSAGES)
            out.append(
                Step(
                    "restore_and_seek",
                    state._replace(
                        next_consume=bookmark,
                        pending=(),
                        inflight=(),
                        counts=counts,
                        crashed=False,
                    ),
                )
            )
            return out
        if state.next_consume < _N_MESSAGES:
            out.append(
                Step(
                    f"consume_m{state.next_consume}",
                    state._replace(
                        next_consume=state.next_consume + 1,
                        pending=state.pending + (state.next_consume,),
                    ),
                )
            )
        if state.pending:
            out.append(
                Step(
                    f"close_batch_m{state.pending[0]}",
                    state._replace(
                        pending=state.pending[1:],
                        inflight=state.inflight + (state.pending[0],),
                    ),
                )
            )
        if state.inflight:
            msg = state.inflight[0]
            counts = list(state.counts)
            counts[msg] += 1
            out.append(
                Step(
                    f"apply_m{msg}",
                    state._replace(
                        inflight=state.inflight[1:], counts=tuple(counts)
                    ),
                )
            )
        quiescent = not state.pending and not state.inflight
        if (quiescent or not self.fact("checkpoint.quiescent_gate")) and (
            state.ckpt != (state.next_consume, state.counts)
        ):
            out.append(
                Step(
                    "checkpoint",
                    state._replace(
                        ckpt=(state.next_consume, state.counts)
                    ),
                )
            )
        if state.crashes_left > 0:
            out.append(
                Step(
                    "crash",
                    state._replace(
                        crashed=True, crashes_left=state.crashes_left - 1
                    ),
                )
            )
        return out

    def invariant(self, state: _ReplayState) -> str | None:
        for msg, count in enumerate(state.counts):
            if count > 1:
                return (
                    f"message {msg} applied {count} times — replay from "
                    "the bookmark re-delivered data the restored state "
                    "already contains"
                )
        drained = (
            state.next_consume == _N_MESSAGES
            and not state.pending
            and not state.inflight
            and not state.crashed
        )
        if drained:
            lost = [m for m, c in enumerate(state.counts) if c == 0]
            if lost:
                return (
                    f"message(s) {lost} never applied — the checkpoint "
                    "bookmark ran ahead of the dumped state (taken "
                    "while windows were still buffered/in flight), so "
                    "the restart seeked past them"
                )
        return None


# ---------------------------------------------------------------------------
# Model 3: relay resync classification over <boot>:<epoch>:<seq> (JGL203)
# ---------------------------------------------------------------------------


class _RelayState(NamedTuple):
    # upstream hub
    boot: int
    epoch: int
    seq: int
    lineage: int  # accumulation-content identity
    next_lineage: int
    # relay channel
    last_boot: int | None
    last_epoch: int | None
    last_seq: int | None
    generation: int
    dec_lineage: int | None
    dec_epoch: int | None
    dec_seq: int | None
    # downstream subscriber
    down_token: tuple[int, int] | None  # (generation, epoch)
    down_lineage: int | None
    # plumbing + budgets
    connected: bool
    sends_left: int
    restarts_left: int
    violation: str


class RelayModel(ProtocolModel):
    """One upstream hub, one relay channel, one downstream subscriber.
    The hub ticks deltas, loses frames, and restarts — either restoring
    its accumulation (durability) or coming back EMPTY with numbering
    that happens to look contiguous, the case only the boot id can
    catch. The relay runs ``RelayChannel.on_blob``'s classification,
    fact-weakened where the source lost a guard. Invariant (JGL203):
    downstream never receives content from a different upstream
    incarnation under an unchanged ``(generation, epoch)`` token, and a
    fresh keyframe is never discarded as stale (the park)."""

    NAME = "relay"
    RULE = "JGL203"
    FACTS = (
        "on_blob.checks_boot",
        "on_blob.bumps_generation",
        "on_blob.stale_excludes_keyframes",
    )

    def initial(self) -> _RelayState:
        return _RelayState(
            boot=0, epoch=0, seq=0, lineage=0, next_lineage=1,
            last_boot=None, last_epoch=None, last_seq=None,
            generation=0, dec_lineage=None, dec_epoch=None, dec_seq=None,
            down_token=None, down_lineage=None,
            connected=False, sends_left=4, restarts_left=1, violation="",
        )

    # -- RelayChannel.on_blob, fact-parameterized ---------------------------
    def _deliver(
        self, state: _RelayState, *, keyframe: bool, after_reconnect: bool
    ) -> _RelayState:
        epoch, seq, lineage = state.epoch, state.seq, state.lineage
        restarted = (
            self.fact("on_blob.checks_boot")
            and state.last_boot is not None
            and state.boot != state.last_boot
        )
        generation = state.generation
        dec_lineage, dec_epoch, dec_seq = (
            state.dec_lineage, state.dec_epoch, state.dec_seq,
        )
        if after_reconnect and keyframe and (
            restarted
            or (
                state.last_epoch is not None
                and (
                    epoch != state.last_epoch
                    or seq < (state.last_seq or 0)
                )
            )
        ):
            # Hard resync: signal the discontinuity downstream.
            if self.fact("on_blob.bumps_generation"):
                generation += 1
            dec_lineage = dec_epoch = dec_seq = None
        stale = (
            (
                not keyframe
                if self.fact("on_blob.stale_excludes_keyframes")
                else True
            )
            and epoch == state.last_epoch
            and state.last_seq is not None
            and seq <= state.last_seq
        )
        violation = state.violation
        publish = False
        spliced = False
        if keyframe:
            dec_lineage, dec_epoch, dec_seq = lineage, epoch, seq
            publish = True
        else:
            if dec_epoch is None or epoch != dec_epoch:
                # DeltaError on a delta: unrecoverable gap — signal the
                # caller to resubscribe (connection drops, keyframe on
                # reattach). Never reaches publish.
                return state._replace(connected=False)
            if seq <= (dec_seq or 0):
                publish = False  # decoder holds this tick already
            elif seq != (dec_seq or 0) + 1:
                return state._replace(connected=False)
            else:
                spliced = dec_lineage != lineage
                dec_lineage, dec_seq = lineage, seq
                publish = True
        state = state._replace(
            last_boot=state.boot, last_epoch=epoch, last_seq=seq,
            generation=generation,
            dec_lineage=dec_lineage, dec_epoch=dec_epoch, dec_seq=dec_seq,
        )
        if stale:
            if keyframe and not violation:
                violation = (
                    "a fresh keyframe was discarded as stale — the "
                    "relay parks on the restarted hub's pre-restart "
                    "frame and never recovers"
                )
            return state._replace(violation=violation)
        if not publish:
            return state
        token = (generation, epoch)
        if not violation and spliced:
            violation = (
                "a delta from a different upstream incarnation was "
                "spliced onto the held frame — the restarted hub's "
                "numbering looked contiguous and nothing checked the "
                "boot id"
            )
        if (
            not violation
            and state.down_token == token
            and state.down_lineage is not None
            and state.down_lineage != lineage
        ):
            violation = (
                "downstream received a DIFFERENT accumulation under an "
                "UNCHANGED (generation, epoch) token — an unsignaled "
                "reset spliced into the delta stream"
            )
        return state._replace(
            down_token=token, down_lineage=lineage, violation=violation
        )

    def steps(self, state: _RelayState) -> list[Step]:
        if state.violation:
            return []  # absorbing: the counterexample ends here
        out: list[Step] = []
        if state.connected and state.sends_left > 0:
            ticked = state._replace(
                seq=state.seq + 1, sends_left=state.sends_left - 1
            )
            out.append(
                Step(
                    "hub_tick_delta",
                    self._deliver(
                        ticked, keyframe=False, after_reconnect=False
                    ),
                )
            )
            # The frame never arrives (coalesced/lost): the next
            # delivery has a seq gap.
            out.append(Step("hub_tick_lost", ticked))
        if not state.connected:
            out.append(
                Step(
                    "reconnect_keyframe",
                    self._deliver(
                        state._replace(connected=True),
                        keyframe=True,
                        after_reconnect=True,
                    ),
                )
            )
        if state.restarts_left > 0:
            restarted = state._replace(
                boot=state.boot + 1,
                connected=False,
                restarts_left=state.restarts_left - 1,
            )
            # Durability restore: the accumulation genuinely continues.
            out.append(Step("hub_restart_restored", restarted))
            # Fresh process, EMPTY state, plausible numbering: the wire
            # cannot distinguish this from the restore — only the boot
            # id can.
            out.append(
                Step(
                    "hub_restart_empty",
                    restarted._replace(
                        lineage=state.next_lineage,
                        next_lineage=state.next_lineage + 1,
                    ),
                )
            )
        return out

    def invariant(self, state: _RelayState) -> str | None:
        return state.violation or None


# ---------------------------------------------------------------------------
# Model 4: rendezvous fleet ownership under membership churn (JGL201)
# ---------------------------------------------------------------------------


class _FleetState(NamedTuple):
    version: int
    views: tuple[int, ...]  # per-replica membership-view version


class FleetModel(ProtocolModel):
    """Three replicas, a membership history (join then leave), each
    replica applying membership events at its own pace. Ownership per
    group uses the REAL ``rendezvous_owner``. Invariant (JGL201),
    checked at quiescent states (every view converged): each group is
    processed by EXACTLY one live replica — never two (overlapping
    accumulation), never zero (dropped stream) — matching the paper
    system's single-writer-per-source contract."""

    NAME = "fleet"
    RULE = "JGL201"
    FACTS = ("owns.compares_self", "filter.consults_owns")

    #: Membership history: r3 joins, then r2 departs (a departing
    #: replica stops — ``set_replicas`` raises on self-departure, the
    #: structurally-bound guard).
    VERSIONS: tuple[tuple[str, ...], ...] = (
        ("r1", "r2"),
        ("r1", "r2", "r3"),
        ("r1", "r3"),
    )
    REPLICAS: tuple[str, ...] = ("r1", "r2", "r3")
    GROUPS: tuple[str, ...] = ("det0", "mon0", "sans0|('q', 1)")

    def initial(self) -> _FleetState:
        return _FleetState(0, (0,) * len(self.REPLICAS))

    def _processes(self, state: _FleetState, idx: int, group: str) -> bool:
        from ..fleet.assignment import rendezvous_owner

        replica = self.REPLICAS[idx]
        roster = self.VERSIONS[state.views[idx]]
        if replica not in roster:
            return False  # departed replicas stop; they own nothing
        if not self.fact("filter.consults_owns"):
            return True  # the window path lost its ownership filter
        if not self.fact("owns.compares_self"):
            return True  # owns() no longer compares against self_id
        return rendezvous_owner(roster, group) == replica

    def steps(self, state: _FleetState) -> list[Step]:
        out: list[Step] = []
        for idx in range(len(self.REPLICAS)):
            if state.views[idx] < state.version:
                views = list(state.views)
                views[idx] += 1
                out.append(
                    Step(
                        f"{self.REPLICAS[idx]}_applies_v{views[idx]}",
                        state._replace(views=tuple(views)),
                        # Ample-set safe: advances only move this
                        # replica's view toward the current version
                        # (confluent with each other and with later
                        # membership events), and the invariant only
                        # judges quiescent states, which every
                        # reduced path still reaches.
                        invisible=True,
                    )
                )
        if state.version < len(self.VERSIONS) - 1:
            out.append(
                Step(
                    f"membership_event_v{state.version + 1}",
                    state._replace(version=state.version + 1),
                )
            )
        return out

    def invariant(self, state: _FleetState) -> str | None:
        if any(view != state.version for view in state.views):
            return None  # churn in progress: replay covers the overlap
        for group in self.GROUPS:
            owners = [
                self.REPLICAS[idx]
                for idx in range(len(self.REPLICAS))
                if self._processes(state, idx, group)
            ]
            if len(owners) > 1:
                return (
                    f"group {group!r} processed by {owners} after "
                    "quiesce — two replicas accumulate the same "
                    "stream and publish diverging views"
                )
            if not owners:
                return (
                    f"group {group!r} processed by NO replica after "
                    "quiesce — the stream silently stops"
                )
        return None


# ---------------------------------------------------------------------------
# Model 5: epoch-bump ⇒ keyframe discipline (JGL204)
# ---------------------------------------------------------------------------


class _EpochState(NamedTuple):
    lineage: int
    state_epoch: int
    publish_epoch: int
    enc_token: int | None
    down_lineage: int | None
    publishes_left: int
    clear_left: int
    lost_left: int
    swap_left: int
    violation: str


class EpochModel(ProtocolModel):
    """The job's content lineage vs the epoch token the serving tier
    compares: ``clear()``/``note_state_lost()`` bump ``state_epoch``,
    a calibration swap bumps the workflow's ``publish_epoch``,
    ``Job.get()`` folds both into the published token, and the delta
    encoder keyframes whenever the token changes. Invariant (JGL204):
    every state-mutating path publishes an epoch bump before the next
    frame — a delta never bridges two accumulations."""

    NAME = "epoch"
    RULE = "JGL204"
    FACTS = (
        "clear.bumps_epoch",
        "note_state_lost.bumps_epoch",
        "get.folds_publish_epoch",
        "encoder.keyframes_on_epoch_change",
    )

    def initial(self) -> _EpochState:
        return _EpochState(0, 0, 0, None, None, 3, 1, 1, 1, "")

    def steps(self, state: _EpochState) -> list[Step]:
        if state.violation:
            return []
        out: list[Step] = []
        if state.publishes_left > 0:
            token = state.state_epoch + (
                state.publish_epoch
                if self.fact("get.folds_publish_epoch")
                else 0
            )
            keyframe = state.enc_token is None or (
                token != state.enc_token
                and self.fact("encoder.keyframes_on_epoch_change")
            )
            nxt = state._replace(
                enc_token=token, publishes_left=state.publishes_left - 1
            )
            if keyframe:
                nxt = nxt._replace(down_lineage=state.lineage)
            elif (
                state.down_lineage is not None
                and state.down_lineage != state.lineage
            ):
                nxt = nxt._replace(
                    violation=(
                        "a DELTA was published across a state "
                        "discontinuity — the mutation reached the next "
                        "frame without an epoch bump, so the decoder "
                        "splices two unrelated accumulations"
                    )
                )
            else:
                nxt = nxt._replace(down_lineage=state.lineage)
            out.append(
                Step("publish_" + ("keyframe" if keyframe else "delta"), nxt)
            )
        if state.clear_left > 0:
            nxt = state._replace(
                lineage=state.lineage + 1, clear_left=state.clear_left - 1
            )
            if self.fact("clear.bumps_epoch"):
                nxt = nxt._replace(state_epoch=state.state_epoch + 1)
            out.append(Step("job_clear", nxt))
        if state.lost_left > 0:
            nxt = state._replace(
                lineage=state.lineage + 1, lost_left=state.lost_left - 1
            )
            if self.fact("note_state_lost.bumps_epoch"):
                nxt = nxt._replace(state_epoch=state.state_epoch + 1)
            out.append(Step("note_state_lost", nxt))
        if state.swap_left > 0:
            out.append(
                Step(
                    "calibration_swap",
                    state._replace(
                        lineage=state.lineage + 1,
                        publish_epoch=state.publish_epoch + 1,
                        swap_left=state.swap_left - 1,
                    ),
                )
            )
        return out

    def invariant(self, state: _EpochState) -> str | None:
        return state.violation or None


# ---------------------------------------------------------------------------
# Registry
# ---------------------------------------------------------------------------

MODELS: dict[str, type[ProtocolModel]] = {
    cls.NAME: cls
    for cls in (
        CheckpointModel,
        ReplayModel,
        RelayModel,
        FleetModel,
        EpochModel,
    )
}


def build_model(name: str, facts: dict[str, bool] | None = None) -> ProtocolModel:
    """Instantiate one model with source-derived facts (missing keys
    default to True — the guard is assumed present until a binding
    proves otherwise)."""
    return MODELS[name](facts=dict(facts or {}))

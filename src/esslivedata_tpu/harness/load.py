"""Deterministic load harness for the SLO plane (ADR 0120).

Drives the REAL serving path — ``JobManager`` tick programs into a
``ServingPlane`` broadcast hub — under production-shaped load and a
seeded chaos schedule, then hands the scrape to the SLO checker
(``scripts/slo_gate.py``). The pieces:

- **Fake producers**: S distinct streams, K detector-view jobs per
  stream, every window stamped with a REAL wall-clock source timestamp
  (the e2e latency boundaries measure against it; synthetic tiny
  timestamps would land every sample in the +Inf bucket).
- **Simulated SSE subscribers**: N consumers attached through the same
  ``BroadcastServer.subscribe`` the socket handler uses, with
  heavy-tailed consume periods (Pareto-drawn: most drain every window,
  a tail drains rarely) plus a deterministic wedged subset that stops
  consuming entirely and un-wedges late — the coalesce/QoS axes under
  real pressure. Subscribers are driven SYNCHRONOUSLY from the window
  loop: determinism is the point (a chaos run is a test, not a race),
  and the concurrent-consumer paths have their own suites.
- **Verification as metrics**: every checker subscriber byte-compares
  its reconstruction against the sink serializer's exact da00 wire
  (``livedata_slo_parity_*``); every cumulative-counts stream is
  watched for an **unsignaled reset** — decoded counts dropping with
  no epoch bump, the ADR 0117 discipline violation
  (``livedata_slo_gap_violations_total``); every coalesced-then-drained
  subscriber must recover the exact latest frame
  (``livedata_slo_coalesce_recoveries_total``). The SLO rule file
  gates on these counters, which is what makes "the chaos scenario
  passed" a scrapeable fact instead of a log line.

``disable_containment`` exists for the CONTROL run the acceptance
demands — proving the gate goes red when a containment is off:

- ``"state_lost_signal"``: ``Job.note_state_lost`` is patched to a
  no-op for the run, so an injected post-donation failure still resets
  the accumulation but never bumps ``state_epoch`` — subscribers see a
  reset spliced into the delta stream and the gap counter goes
  non-zero.
- ``"bounded_queues"``: the hub is built with an effectively unbounded
  per-subscriber queue, so wedged subscribers grow their backlog
  instead of coalescing — the queue-depth SLO breaches.

Scrapes: :meth:`LoadHarness.run` snapshots the registry AFTER the warm
windows and again at the end; the gate evaluates the DELTA, so warm-up
compiles and whatever ran earlier in the process can never pollute the
gated phase.
"""

from __future__ import annotations

import logging
import time
from dataclasses import dataclass
from random import Random
from typing import Any

import numpy as np

from ..telemetry.e2e import observe_stage
from ..telemetry.health import HEALTH
from ..telemetry.registry import REGISTRY
from .chaos import ChaosSchedule, ChaosSpec

__all__ = ["LoadConfig", "LoadHarness"]

logger = logging.getLogger(__name__)

#: Verification counters the SLO rules gate on (see module docstring).
PARITY_CHECKS = REGISTRY.counter(
    "livedata_slo_parity_checks",
    "Checker-subscriber reconstructions byte-compared against the "
    "sink da00 wire",
)
PARITY_VIOLATIONS = REGISTRY.counter(
    "livedata_slo_parity_violations",
    "Checker reconstructions that did NOT byte-match the sink wire",
)
GAP_VIOLATIONS = REGISTRY.counter(
    "livedata_slo_gap_violations",
    "Unsignaled resets observed by subscribers: decoded cumulative "
    "counts dropped with no epoch bump (ADR 0117 discipline breach)",
)
COALESCE_RECOVERIES = REGISTRY.counter(
    "livedata_slo_coalesce_recoveries",
    "Coalesced (wedged/slow) subscribers that recovered the exact "
    "latest frame from their resync keyframe",
)
WINDOWS_DRIVEN = REGISTRY.counter(
    "livedata_slo_windows",
    "Windows the load harness drove through the serving path",
)
PEAK_QUEUE_DEPTH = REGISTRY.gauge(
    "livedata_slo_peak_queue_depth",
    "Highest per-subscriber send-queue depth observed across the run "
    "— the bounded-queue SLO gates it at the configured queue limit "
    "(a scrape-time gauge can miss the peak; the harness samples "
    "after every publish)",
)
#: Workload-plane rows (ADR 0122): the drill runs a veto-filtered
#: powder-focus stream alongside the detector views, and its parity /
#: freshness gate separately — a new family that silently fell off the
#: serving path would otherwise hide inside the global counters.
WORKLOAD_PARITY_CHECKS = REGISTRY.counter(
    "livedata_slo_workload_parity_checks",
    "Workload-family (powder-focus) checker reconstructions "
    "byte-compared against the sink da00 wire",
)
WORKLOAD_PARITY_VIOLATIONS = REGISTRY.counter(
    "livedata_slo_workload_parity_violations",
    "Workload-family checker reconstructions that did NOT byte-match",
)
WORKLOAD_FRESHNESS = REGISTRY.histogram(
    "livedata_slo_workload_freshness_seconds",
    "Source-timestamp age of workload-family frames at checker "
    "delivery (the per-family freshness SLO)",
)


@dataclass
class LoadConfig:
    """Harness shape; defaults are the bench ``--slo`` scale, shrink
    for smoke (``scripts/slo_gate.py --smoke`` uses ~half)."""

    streams: int = 4
    jobs_per_stream: int = 2
    #: Workload-plane streams (ADR 0122): each runs one veto-filtered
    #: powder-focus job — the new-family presence the SLO rules gate
    #: (parity + freshness rows). 0 = pre-workload drill.
    workload_streams: int = 1
    subscribers: int = 240
    windows: int = 48
    warm_windows: int = 3
    events_per_window: int = 2048
    pixels: int = 1 << 12  # side^2 clamp — sparse frames, delta regime
    queue_limit: int = 8
    seed: int = 7
    #: Pareto tail index for consume periods: ~alpha=1.2 gives mostly
    #: period-1 consumers with a long slow tail.
    heavy_tail_alpha: float = 1.2
    #: Every Nth subscriber wedges (consumes nothing) until 2/3 of the
    #: run, then drains and must recover exactly.
    wedge_every: int = 7
    chaos: ChaosSpec | None = None
    #: Relay hops between the compute hub and the subscribers (ADR
    #: 0121): the default drill runs THROUGH one in-process relay hop
    #: (fleet/relay.py HubRelay, pumped synchronously per window), so
    #: the parity/gap-discipline gates hold ACROSS the hop and the
    #: ``relay_upstream_drop`` chaos site has a live target. 0 = the
    #: pre-fleet direct topology.
    relay_hops: int = 1
    #: None | "state_lost_signal" | "bounded_queues" — the acceptance
    #: control runs (see module docstring). Production containment is
    #: NEVER touched outside this harness.
    disable_containment: str | None = None

    def scaled(self, factor: float) -> "LoadConfig":
        """A smaller copy for smoke budgets (chaos spec untouched —
        explicit ticks must stay inside the window count, so smoke
        specs are built against the scaled count)."""
        cfg = LoadConfig(**{**self.__dict__})
        cfg.subscribers = max(8, int(self.subscribers * factor))
        cfg.windows = max(16, int(self.windows * factor))
        cfg.events_per_window = max(256, int(self.events_per_window * factor))
        return cfg


@dataclass
class _SimSubscriber:
    """One simulated SSE consumer (driven synchronously)."""

    sub: Any  # serving.broadcast.Subscription
    stream: str
    period: int  # drain every Nth window
    wedged_until: int | None  # window index, None = never wedged
    checker: bool  # byte-compares against the sink wire
    decoder: Any = None  # DeltaDecoder, rebased lazily
    frame: bytes | None = None
    last_epoch: int | None = None
    last_counts: float | None = None
    was_coalesced: bool = False
    #: Publishes this consumer slept through while wedged — once it
    #: exceeds the queue limit the hub MUST have coalesced it.
    missed: int = 0
    delivered: int = 0


class LoadHarness:
    """Build once, :meth:`run` once; see module docstring."""

    def __init__(self, config: LoadConfig | None = None) -> None:
        self.config = config or LoadConfig()

    # -- construction helpers ----------------------------------------------
    def _build_manager(self):
        from ..config import JobId, WorkflowConfig, WorkflowSpec
        from ..core.job_manager import JobFactory, JobManager
        from ..workflows import WorkflowFactory
        from ..workflows.detector_view import (
            DetectorViewWorkflow,
            project_logical,
        )

        from ..workloads import (
            CalibrationTable,
            FilterChain,
            PowderFocusParams,
            PowderFocusWorkflow,
            PulseVetoFilter,
        )

        cfg = self.config
        side = int(np.sqrt(min(cfg.pixels, 1 << 14)))
        det = np.arange(side * side).reshape(side, side)
        n_pix = side * side
        reg = WorkflowFactory()
        streams = [f"slo_stream_{i}" for i in range(cfg.streams)]
        for stream in streams:
            spec = WorkflowSpec(
                instrument="slo", name=f"dv_{stream}", source_names=[stream]
            )
            reg.register_spec(spec).attach_factory(
                lambda *, source_name, params: DetectorViewWorkflow(
                    projection=project_logical(det)
                )
            )
            self._specs[stream] = spec
        # Workload plane (ADR 0122): veto-filtered powder-focus streams
        # — a calibration-LUT family with per-event filtering riding the
        # same tick path, gated by its own parity/freshness rows.
        calib = CalibrationTable(
            name="slo_cal",
            version=1,
            columns={
                "difc": np.linspace(2.0e7, 3.0e7, n_pix),
                "tzero": np.zeros(n_pix),
            },
        )
        chain = FilterChain(
            [PulseVetoFilter(windows=((1e6, 4e6),), period_ns=7.0e7)]
        )
        workload_streams = [
            f"slo_powder_{i}" for i in range(max(0, cfg.workload_streams))
        ]
        for stream in workload_streams:
            spec = WorkflowSpec(
                instrument="slo", name=f"pf_{stream}", source_names=[stream]
            )
            reg.register_spec(spec).attach_factory(
                lambda *, source_name, params: PowderFocusWorkflow(
                    calibration=calib,
                    params=PowderFocusParams(d_bins=128),
                    filters=chain,
                )
            )
            self._specs[stream] = spec
        streams = streams + workload_streams
        mgr = JobManager(
            job_factory=JobFactory(reg),
            job_threads=min(4, len(streams) * cfg.jobs_per_stream),
        )
        for stream in streams:
            jobs = (
                cfg.jobs_per_stream
                if stream not in workload_streams
                else 1
            )
            for _ in range(jobs):
                mgr.schedule_job(
                    WorkflowConfig(
                        identifier=self._specs[stream].identifier,
                        job_id=JobId(source_name=stream),
                    )
                )
        return mgr, streams, side

    def _staged(self, rng: np.random.Generator, side: int):
        from ..ops import EventBatch
        from ..preprocessors.event_data import StagedEvents

        cfg = self.config
        n = min(cfg.events_per_window, max(256, (side * side) // 8))
        pid = rng.integers(0, side * side, n, dtype=np.int64).astype(
            np.int32
        )
        toa = rng.uniform(0, 7.0e7, n).astype(np.float32)
        return StagedEvents(
            batch=EventBatch.from_arrays(pid, toa),
            first_timestamp=None,
            last_timestamp=None,
            n_chunks=1,
        )

    def _watch_list(self, streams_cached) -> list[str]:
        """The streams subscribers actually watch: real viewers
        concentrate on a few dashboards, and the harness needs DEPTH
        per stream (wedged + slow + checker on one stream is what
        exercises coalescing), not one viewer per output. Cumulative
        streams come first — they carry the gap-not-reset check."""
        cumulative = [
            s for s in streams_cached if s.endswith("/counts_cumulative")
        ]
        rest = [s for s in streams_cached if s not in set(cumulative)]
        n_watch = max(
            len(cumulative), min(len(streams_cached), self.config.subscribers // 8)
        )
        return (cumulative + rest)[:n_watch]

    def _attach_subscribers(self, hub, streams_cached) -> None:
        cfg = self.config
        rng = Random(cfg.seed ^ 0x5105)
        watch = self._watch_list(streams_cached)
        per_stream_checker: set[str] = set()
        for i in range(cfg.subscribers):
            stream = watch[i % len(watch)]
            checker = stream not in per_stream_checker
            per_stream_checker.add(stream)
            period = (
                1
                if checker
                else max(1, min(16, int(rng.paretovariate(cfg.heavy_tail_alpha))))
            )
            wedged_until = None
            if not checker and cfg.wedge_every and i % cfg.wedge_every == 0:
                wedged_until = (cfg.windows * 2) // 3
            self._subs.append(
                _SimSubscriber(
                    sub=hub.subscribe(stream),
                    stream=stream,
                    period=period,
                    wedged_until=wedged_until,
                    checker=checker,
                )
            )

    # -- subscriber drive ---------------------------------------------------
    def _drain(self, sim: _SimSubscriber, reference: dict[str, bytes]) -> None:
        """Drain everything queued for one subscriber and fold the
        verification counters (parity, gap-not-reset, coalesce
        recovery). Synchronous: publish already happened, so ``depth``
        is exact and an empty queue costs no timeout wait."""
        from ..kafka.wire import decode_da00
        from .. import serving

        got_any = False
        last_frame_ts: int | None = None
        while sim.sub.depth() > 0:
            blob, frame_ts = sim.sub.next_blob_meta(timeout=1.0)
            if blob is None:  # pragma: no cover - depth>0 guarantees one
                break
            if frame_ts is not None:
                last_frame_ts = frame_ts
            got_any = True
            sim.delivered += 1
            header = serving.decode_header(blob)
            if sim.decoder is None:
                sim.decoder = serving.DeltaDecoder()
            try:
                sim.frame = sim.decoder.apply(blob)
            except serving.DeltaError:
                # A gap after coalesce resolves at the resync keyframe;
                # rebase and keep consuming.
                sim.decoder = serving.DeltaDecoder()
                if header.keyframe:
                    sim.frame = sim.decoder.apply(blob)
                else:
                    continue
            # Gap-not-reset (ADR 0117): cumulative counts may only
            # drop when the epoch bumped (signaled reset/state-loss).
            if sim.stream.endswith("/counts_cumulative") and sim.frame:
                msg = decode_da00(sim.frame)
                signal = next(
                    (v for v in msg.variables if v.name == "signal"), None
                )
                if signal is not None:
                    counts = float(np.asarray(signal.data).sum())
                    if (
                        sim.last_counts is not None
                        and counts < sim.last_counts - 1e-9
                        and header.epoch == sim.last_epoch
                    ):
                        GAP_VIOLATIONS.inc()
                    sim.last_counts = counts
                    sim.last_epoch = header.epoch
        if got_any and sim.was_coalesced and sim.stream in reference:
            # A coalesced consumer's first full drain must land on the
            # exact latest frame (resync keyframe + later deltas).
            if sim.frame == reference[sim.stream]:
                COALESCE_RECOVERIES.inc()
            sim.was_coalesced = False
        if got_any and sim.checker and sim.stream in reference:
            PARITY_CHECKS.inc()
            violated = sim.frame != reference[sim.stream]
            if violated:
                PARITY_VIOLATIONS.inc()
            if sim.stream.startswith("slo_powder"):
                # Workload-plane rows (ADR 0122): the new family's
                # parity and freshness gate on their own counters.
                # Freshness against the DELIVERED frame's own source
                # timestamp (the broadcast queue carries it per entry)
                # — measuring against the current window's ts would
                # score a k-window-late frame as fresh.
                WORKLOAD_PARITY_CHECKS.inc()
                if violated:
                    WORKLOAD_PARITY_VIOLATIONS.inc()
                if last_frame_ts is not None:
                    WORKLOAD_FRESHNESS.observe(
                        max(0.0, (time.time_ns() - last_frame_ts) / 1e9)
                    )

    # -- the run -------------------------------------------------------------
    def run(self) -> dict:
        """Drive the configured load + chaos; returns the report dict
        (scrapes included) the SLO gate and ``bench.py --slo`` consume."""
        from ..core.job import Job
        from ..core.timestamp import Timestamp
        from ..kafka.da00_compat import dataarray_to_da00
        from ..kafka.wire import encode_da00
        from ..fleet.relay import RELAY_FRAMES, RELAY_RESYNCS, HubRelay
        from ..serving import ServingPlane, stream_key
        from ..serving.broadcast import SERVING_COALESCE_DROPS
        from ..telemetry.compile import COMPILE_EVENTS
        from ..telemetry.exposition import render_text

        cfg = self.config
        self._specs: dict[str, Any] = {}
        self._subs: list[_SimSubscriber] = []
        chaos = (
            ChaosSchedule(cfg.chaos) if cfg.chaos is not None else None
        )
        queue_limit = cfg.queue_limit
        if cfg.disable_containment == "bounded_queues":
            # CONTROL: wedged consumers buffer instead of coalescing.
            queue_limit = 1 << 17
        mgr, streams, side = self._build_manager()
        plane = ServingPlane(port=None, queue_limit=queue_limit)
        # Relay tree (ADR 0121): the drill's subscribers sit BEHIND
        # ``relay_hops`` in-process relay hops, pumped synchronously
        # after every publish — parity and gap-discipline are therefore
        # gated ACROSS the tree, and the relay_upstream_drop chaos site
        # drills the resync path.
        relays: list[HubRelay] = []
        upstream_hub = plane.server
        for hop in range(max(0, cfg.relay_hops)):
            relay = HubRelay(
                upstream_hub,
                name=f"slo_relay_{hop}",
                queue_limit=queue_limit,
            )
            relays.append(relay)
            upstream_hub = relay.hub
        edge_hub = upstream_hub
        if chaos is not None:
            # Subscriptions capture the schedule at attach, so the hub
            # gets it before subscribers exist; the MANAGER and the
            # relays get it only after the warm windows (a drill starts
            # at steady state — and explicit `at` ticks count steady
            # consultations, not warm-up ones).
            edge_hub.set_chaos(chaos)
        patched_note = None
        if cfg.disable_containment == "state_lost_signal":
            # CONTROL: the containment still resets state, but the
            # epoch signal never fires — downstream MUST catch it.
            patched_note = Job.note_state_lost
            Job.note_state_lost = lambda self: None  # type: ignore[method-assign]
        rng = np.random.default_rng(cfg.seed)
        reference: dict[str, bytes] = {}
        report: dict[str, Any] = {}
        try:
            # Warm phase: programs compile, statics fetch, hub learns
            # the streams; the configured chaos does NOT run here (a
            # drill starts at steady state) — but when chaos is
            # configured, the warm-up ALSO fails each tick group once,
            # one group per window, so the failover path (that group's
            # members re-publishing alone through the combined-publish
            # combiner after note_state_lost — a member-tuple jit key
            # of its own) is compiled before the gated phase. The
            # compiles=0 SLO covers the failure path too: a containment
            # that pays a jit compile mid-incident blows the very p99
            # it exists to protect.
            warm_windows = cfg.warm_windows
            # Tick groups per window: one per detector-view stream
            # (jobs_per_stream jobs fuse) + one singleton per workload
            # (powder-focus) stream — the warm-poison arithmetic below
            # fails each group exactly once.
            n_groups = cfg.streams + max(0, cfg.workload_streams)
            if cfg.chaos is not None:
                warm_windows = max(warm_windows, n_groups + 2)
                # Window 1..n_groups: consultation (w-1)*n_groups + g
                # fires where g == w-1 — exactly one group per window.
                warm_poison = ChaosSchedule(
                    ChaosSpec(
                        at={
                            "tick_dispatch": frozenset(
                                k * (n_groups + 1)
                                for k in range(n_groups)
                            )
                        }
                    )
                )
            for w in range(warm_windows):
                if cfg.chaos is not None:
                    mgr.set_chaos(
                        warm_poison if 1 <= w <= n_groups else None
                    )
                ts = time.time_ns()
                window = {s: self._staged(rng, side) for s in streams}
                mgr.process_jobs(
                    window,
                    start=Timestamp.from_ns(ts),
                    end=Timestamp.from_ns(ts),
                )
            mgr.set_chaos(None)
            ts = time.time_ns()
            out = mgr.process_jobs(
                {s: self._staged(rng, side) for s in streams},
                start=Timestamp.from_ns(ts),
                end=Timestamp.from_ns(ts),
            )
            plane.publish_results(out, Timestamp.from_ns(ts))
            for relay in relays:
                relay.pump()
            streams_cached = sorted(edge_hub.cache.streams())
            if not streams_cached:
                raise RuntimeError("no streams cached after warm windows")
            self._attach_subscribers(edge_hub, streams_cached)
            for sim in self._subs:
                self._drain(sim, reference)  # attach keyframes
            compiles_warm = COMPILE_EVENTS.total()
            drops_before = SERVING_COALESCE_DROPS.total()
            relay_resyncs0 = RELAY_RESYNCS.total()
            relay_frames0 = RELAY_FRAMES.total()
            parity_checks0 = PARITY_CHECKS.total()
            parity_bad0 = PARITY_VIOLATIONS.total()
            gaps0 = GAP_VIOLATIONS.total()
            recov0 = COALESCE_RECOVERIES.total()
            scrape_before = render_text(REGISTRY.collect())
            if chaos is not None:
                mgr.set_chaos(chaos)
                if relays:
                    # Only the FIRST hop consults relay_upstream_drop:
                    # the site's per-consultation counter is shared, so
                    # a second consulting relay would halve the
                    # schedule's window arithmetic (an `at` tick meant
                    # for window N would fire at N/2) and split fires
                    # across hops nondeterministically.
                    relays[0].set_chaos(chaos)
            t_run = time.perf_counter()

            pause = 0
            paused_windows = 0
            peak_depth = 0
            for w in range(cfg.windows):
                if pause > 0:
                    # Consumer restarting: no messages arrive. Data
                    # time keeps advancing; accumulation must resume
                    # with a gap, never a reset.
                    pause -= 1
                    paused_windows += 1
                    continue
                if chaos is not None and chaos.fires("consumer_restart"):
                    pause = cfg.chaos.restart_gap_windows
                # "Consume": the window's source timestamp is born.
                source_ts = time.time_ns()
                observe_stage("consume", source_ts, now_ns=source_ts)
                window = {s: self._staged(rng, side) for s in streams}
                observe_stage("decode", source_ts)
                end = Timestamp.from_ns(source_ts)
                out = mgr.process_jobs(window, start=end, end=end)
                # The sink serializer's exact bytes — the parity oracle
                # (and the "sink publish" the plane mirrors).
                for res in out:
                    job = (
                        f"{res.job_id.source_name}:{res.job_id.job_number}"
                    )
                    for key, da in zip(
                        res.keys(), res.outputs.values(), strict=True
                    ):
                        reference[stream_key(job, key.output_name)] = (
                            encode_da00(
                                key.to_string(),
                                source_ts,
                                dataarray_to_da00(da),
                            )
                        )
                observe_stage("published", source_ts)
                plane.publish_results(out, end)
                for relay in relays:
                    relay.pump()
                WINDOWS_DRIVEN.inc()
                peak_depth = max(
                    peak_depth,
                    max(sim.sub.depth() for sim in self._subs),
                )
                for sim in self._subs:
                    wedged = (
                        sim.wedged_until is not None and w < sim.wedged_until
                    )
                    if wedged:
                        sim.missed += 1
                        if sim.missed > queue_limit:
                            # More publishes than its queue holds: the
                            # hub coalesced this consumer (or, in the
                            # bounded_queues CONTROL, buffered — the
                            # depth rule catches that).
                            sim.was_coalesced = True
                        continue
                    if w % sim.period == 0 or sim.wedged_until == w:
                        sim.missed = 0
                        self._drain(sim, reference)
            wall_s = time.perf_counter() - t_run
            # Final full drain: every consumer ends at the last frame.
            for sim in self._subs:
                self._drain(sim, reference)
            steady_compiles = COMPILE_EVENTS.total() - compiles_warm
            PEAK_QUEUE_DEPTH.set(peak_depth)
            qos = edge_hub.qos()
            report = {
                "streams": cfg.streams,
                "workload_streams": max(0, cfg.workload_streams),
                "jobs": cfg.streams * cfg.jobs_per_stream
                + max(0, cfg.workload_streams),
                "workload_parity_checks": WORKLOAD_PARITY_CHECKS.total(),
                "subscribers": cfg.subscribers,
                "windows": cfg.windows,
                "relay_hops": len(relays),
                "relay_resyncs": RELAY_RESYNCS.total() - relay_resyncs0,
                "relay_frames": RELAY_FRAMES.total() - relay_frames0,
                "paused_windows": paused_windows,
                "events_per_window": cfg.events_per_window,
                "wall_ms_per_window": 1e3 * wall_s / max(1, cfg.windows),
                "chaos_injected": (
                    chaos.injected() if chaos is not None else {}
                ),
                "parity_checks": PARITY_CHECKS.total() - parity_checks0,
                "parity_violations": (
                    PARITY_VIOLATIONS.total() - parity_bad0
                ),
                "gap_violations": GAP_VIOLATIONS.total() - gaps0,
                "coalesce_drops": (
                    SERVING_COALESCE_DROPS.total() - drops_before
                ),
                "coalesce_recoveries": (
                    COALESCE_RECOVERIES.total() - recov0
                ),
                "steady_compiles": steady_compiles,
                "peak_queue_depth": peak_depth,
                "queue_limit": queue_limit,
                "queue_pressure": qos["queue_pressure"],
                "healthz": HEALTH.healthz(),
                "disable_containment": cfg.disable_containment,
                "scrape_before": scrape_before,
                "scrape_after": render_text(REGISTRY.collect()),
            }
        finally:
            if patched_note is not None:
                Job.note_state_lost = patched_note  # type: ignore[method-assign]
            mgr.shutdown()
            for relay in relays:
                relay.close()
            plane.close()
        return report

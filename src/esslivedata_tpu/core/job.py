"""Job = one workflow instance bound to one source, plus wire-status models.

Parity with reference ``core/job.py``: Job:255 (add/process/get with time
coords stamped on outputs :209), JobState:95 phases, JobStatus:59,
ServiceStatus:193, stream-lag model :141-177 with WARN >= 2 s stale /
ERROR > 0.1 s future thresholds (:132-138).
"""

from __future__ import annotations

import time
import uuid
from collections.abc import Mapping
from typing import Any

import numpy as np
from pydantic import BaseModel, Field

from ..config.workflow_spec import JobId, JobSchedule, ResultKey, WorkflowId
from ..telemetry.health import HEALTH
from ..utils.compat import StrEnum
from ..utils.labeled import DataArray, Variable
from ..workflows.workflow_factory import Workflow
from .timestamp import Duration, Timestamp

__all__ = [
    "Job",
    "JobResult",
    "JobState",
    "JobStatus",
    "ServiceStatus",
    "StreamLag",
    "StreamLagReport",
]

STALE_WARN_THRESHOLD = Duration.from_s(2.0)
FUTURE_ERROR_THRESHOLD = Duration.from_s(0.1)


class JobState(StrEnum):
    SCHEDULED = "scheduled"
    PENDING_CONTEXT = "pending_context"
    ACTIVE = "active"
    FINISHING = "finishing"
    WARNING = "warning"
    ERROR = "error"
    STOPPED = "stopped"


class JobStatus(BaseModel):
    """Per-job status as published in heartbeats (x5f2 status_json)."""

    source_name: str
    job_number: uuid.UUID
    workflow_id: str
    state: JobState
    message: str = ""
    has_primary_data: bool = False
    #: The start command's validated params — lets the dashboard offer
    #: "restart with edited params" with the real current values.
    params: dict = {}


class StreamLag(BaseModel):
    """Data-time vs wall-clock skew of one stream at batch close."""

    stream_name: str
    lag_s: float  # positive = stale, negative = from the future
    # Optional window aggregation (filled by kafka.stream_counter on the
    # 30 s metrics rollover; single-sample reports leave them at defaults).
    min_s: float | None = None
    max_s: float | None = None
    count: int = 1

    @property
    def level(self) -> str:
        future = self.min_s if self.min_s is not None else self.lag_s
        if future < -FUTURE_ERROR_THRESHOLD.seconds:
            return "error"
        if self.lag_s > STALE_WARN_THRESHOLD.seconds:
            return "warning"
        return "ok"


class StreamLagReport(BaseModel):
    lags: list[StreamLag] = Field(default_factory=list)

    @property
    def worst_level(self) -> str:
        levels = {lag.level for lag in self.lags}
        for level in ("error", "warning"):
            if level in levels:
                return level
        return "ok"


class ServiceStatus(BaseModel):
    """Service heartbeat payload (2 s cadence)."""

    service_name: str
    instrument: str
    state: str = "running"
    jobs: list[JobStatus] = Field(default_factory=list)
    last_batch_message_count: int = 0
    stream_message_counts: dict[str, int] = Field(default_factory=dict)
    uptime_s: float = 0.0
    #: Worst stream-lag level at the last batch ('ok'/'warning'/'error')
    #: and the worst data-time lag in seconds — the operator's first
    #: clue that a service is falling behind its streams.
    lag_level: str = "ok"
    worst_lag_s: float = 0.0
    #: Per-stream lag detail for the dashboard drill-down (reference
    #: workflow_status_widget surfaces per-source staleness): stream
    #: name -> (lag seconds, level).
    stream_lags: dict[str, tuple[float, str]] = Field(default_factory=dict)
    #: Transport-source health: 'ok' | 'stale' | 'stopped' ('stopped' =
    #: the consume thread's circuit breaker opened — reference
    #: system_status_widget surfaces consumer health per service).
    source_health: str = "ok"
    #: Source counters (queued/dropped batches, consumed messages).
    source_metrics: dict[str, int] = Field(default_factory=dict)


class JobResult:
    """Finalized outputs of one job for one window."""

    __slots__ = (
        "job_id",
        "workflow_id",
        "outputs",
        "start",
        "end",
        "state_epoch",
    )

    def __init__(
        self,
        *,
        job_id: JobId,
        workflow_id: WorkflowId,
        outputs: dict[str, DataArray],
        start: Timestamp | None,
        end: Timestamp | None,
        state_epoch: int = 0,
    ) -> None:
        self.job_id = job_id
        self.workflow_id = workflow_id
        self.outputs = outputs
        self.start = start
        self.end = end
        #: The producing job's state generation at finalize (see
        #: ``Job.state_epoch``) — the fan-out tier's epoch signal.
        self.state_epoch = state_epoch

    @property
    def source_ts_ns(self) -> int | None:
        """The source timestamp this result answers for (ADR 0120):
        the window-end data time — the ev44 reference time / payload
        timestamp of the newest message folded into these outputs.
        Every e2e latency boundary downstream of finalize (publish,
        fan-out encode, subscriber delivery) measures against it; None
        for windows that carried no data time (empty finishing-job
        flushes)."""
        return None if self.end is None else int(self.end.ns)

    def keys(self) -> list[ResultKey]:
        return [
            ResultKey(
                workflow_id=self.workflow_id,
                job_id=self.job_id,
                output_name=name,
            )
            for name in self.outputs
        ]


class Job:
    """Owns a workflow instance; maps window data in, stamped results out."""

    def __init__(
        self,
        *,
        job_id: JobId,
        workflow_id: WorkflowId,
        workflow: Workflow,
        schedule: JobSchedule | None = None,
        primary_streams: set[str] | None = None,
        aux_streams: set[str] | None = None,
        context_keys: set[str] | None = None,
        optional_context_keys: set[str] | None = None,
        reset_on_run_transition: bool = True,
        params: dict | None = None,
    ) -> None:
        self.job_id = job_id
        self.workflow_id = workflow_id
        #: None after release(): a stopped job keeps metadata only.
        self.workflow: Workflow | None = workflow
        self.params = dict(params or {})
        self.schedule = schedule or JobSchedule()
        self.primary_streams = primary_streams or {job_id.source_name}
        self.aux_streams = aux_streams or set()
        self.context_keys = context_keys or set()
        self.optional_context_keys = optional_context_keys or set()
        self.reset_on_run_transition = reset_on_run_transition
        # Generation start: data time of the first message accumulated since
        # job start or last reset. Stamped on outputs as ``start_time``, it
        # is constant for the lifetime of a generation and changes on reset/
        # reconfigure — NICOS uses the jump as a change-detector to tell a
        # post-reset zero from a genuine low reading (reference job.py:111,
        # ADR 0006).
        self._generation_start: Timestamp | None = None
        self._window_end: Timestamp | None = None
        self._start_wall = time.time()
        #: Output names whose last finalize returned None (warning surface).
        self.none_outputs: tuple[str, ...] = ()
        #: State-generation counter for downstream consumers (the result
        #: fan-out tier, ADR 0117): bumped whenever the accumulation
        #: restarts — clear()/reset and ``note_state_lost`` (a donated
        #: dispatch failure rebuilt the buffers mid-generation). A delta
        #: stream must never splice frames across a bump, so the serving
        #: plane folds this into its epoch token.
        self.state_epoch: int = 0

    @property
    def subscribed_streams(self) -> set[str]:
        return self.primary_streams | self.aux_streams

    def add(
        self,
        data: Mapping[str, Any],
        *,
        start: Timestamp | None = None,
        end: Timestamp | None = None,
        skip_accumulate: frozenset[str] | set[str] = frozenset(),
    ) -> bool:
        """Feed one window of stream-keyed data; returns True if any of it
        was for this job.

        ``skip_accumulate`` names streams whose values were already
        accumulated out-of-band by the JobManager's fused stepping layer:
        they still count as delivered data (window stamps, primary-data
        bookkeeping) but must not reach ``workflow.accumulate`` a second
        time."""
        if all(k in self.subscribed_streams for k in data):
            # Common case: the JobManager pre-filters per job — no copy.
            relevant: Mapping[str, Any] = data
        else:
            relevant = {
                k: v for k, v in data.items() if k in self.subscribed_streams
            }
        if not relevant:
            return False
        if self.workflow is None:
            raise RuntimeError(f"Job {self.job_id} is released (stopped)")
        if start is not None and self._generation_start is None:
            self._generation_start = start
        if end is not None:
            self._window_end = end
        if skip_accumulate:
            to_accumulate = {
                k: v for k, v in relevant.items() if k not in skip_accumulate
            }
            if to_accumulate:
                self.workflow.accumulate(to_accumulate)
        else:
            self.workflow.accumulate(relevant)
        return True

    def set_context(self, context: Mapping[str, Any]) -> None:
        deliverable = self.context_keys | self.optional_context_keys
        relevant = {k: v for k, v in context.items() if k in deliverable}
        if relevant and hasattr(self.workflow, "set_context"):
            self.workflow.set_context(relevant)

    def get(self) -> JobResult:
        """Finalize the window into a JobResult, stamping generation-start /
        window-end time coords on every output (reference job.py:209-245).

        Outputs that already carry ``start_time``/``end_time`` (a workflow
        stamping window-local coords on a per-update view) or a ``time``
        coord (timeseries data with its own timestamps) are left alone.
        """
        if self.workflow is None:
            raise RuntimeError(f"Job {self.job_id} is released (stopped)")
        raw = self.workflow.finalize()
        # None-valued outputs degrade to a per-job WARNING, publishing the
        # rest (reference: warning_from_none_values propagates to the job
        # status) — one absent output must not error the whole job.
        outputs = {k: v for k, v in raw.items() if v is not None}
        self.none_outputs = tuple(k for k, v in raw.items() if v is None)
        start, end = self._generation_start, self._window_end
        for da in outputs.values():
            if "time" in da.coords or "end_time" in da.coords:
                continue
            if start is not None:
                da.coords.setdefault(
                    "start_time",
                    Variable(np.asarray(start.ns, dtype=np.int64), (), "ns"),
                )
            if end is not None:
                da.coords["end_time"] = Variable(
                    np.asarray(end.ns, dtype=np.int64), (), "ns"
                )
        # Workflows may carry their own epoch contribution (duck-typed
        # ``publish_epoch``): a calibration swap (ADR 0122) keeps the
        # accumulation — no clear, no state loss — but downstream delta
        # streams must still resync on ONE keyframe at the handover.
        # Summing keeps both counters monotone and independent; the
        # serving tier only compares tokens for equality.
        wf_epoch = int(getattr(self.workflow, "publish_epoch", 0) or 0)
        return JobResult(
            job_id=self.job_id,
            workflow_id=self.workflow_id,
            outputs=outputs,
            start=start,
            end=end,
            state_epoch=self.state_epoch + wf_epoch,
        )

    def process(
        self,
        data: Mapping[str, Any],
        *,
        start: Timestamp | None = None,
        end: Timestamp | None = None,
    ) -> JobResult:
        self.add(data, start=start, end=end)
        return self.get()

    # graft: protocol=epoch (ADR 0124: the state_epoch bumps below must
    # reach every exit path — the modeled epoch-bump⇒keyframe guard)
    def clear(self) -> None:
        """Reset accumulation; starts a new generation (start_time jumps)."""
        if self.workflow is not None:
            self.workflow.clear()
        self._generation_start = None
        self._window_end = None
        self.state_epoch += 1

    def note_state_lost(self) -> None:
        """Record a mid-generation state rebuild (a donated dispatch
        failed after consuming the buffers and the JobManager reset the
        accumulator, ADR 0113/0114): downstream delta streams must
        keyframe — the next published frame does not continue the
        previous one. Also feeds the process health latch (ADR 0120):
        /healthz reports degraded for an interval after a loss, and
        ``livedata_state_lost_total`` counts the rate."""
        self.state_epoch += 1
        HEALTH.note_state_lost()

    @property
    def generation_start_ns(self) -> int | None:
        """The current generation's start time in ns (None before the
        first accumulated message) — checkpointed by the durability
        plane (ADR 0118) so a restored job stamps the same
        ``start_time`` coord an uninterrupted process would have."""
        start = self._generation_start
        return None if start is None else int(start.ns)

    def adopt_checkpoint(
        self,
        *,
        state_epoch: int,
        generation_start_ns: int | None,
    ) -> None:
        """Adopt a restored checkpoint's job-level metadata (ADR 0118):
        the generation start (so ``start_time`` continues rather than
        jumping, which NICOS reads as a reset — ADR 0006) and the
        ``state_epoch`` (so the serving tier's delta/epoch discipline
        continues the restored accumulation's lineage). Only called on
        schedule-time restore, BEFORE any data reaches the job; the
        mid-run ``state_lost`` recovery path must NOT adopt — its epoch
        already bumped past the checkpoint's."""
        self.state_epoch = int(state_epoch)
        self._generation_start = (
            None
            if generation_start_ns is None
            else Timestamp.from_ns(int(generation_start_ns))
        )

    def release(self) -> None:
        """Drop the workflow instance (and with it the device-resident
        accumulator state). Called when the job reaches STOPPED: the
        record stays visible for status/removal, but a stopped
        detector-view job must not pin hundreds of MB of HBM until an
        operator clicks remove — under clear-at-commit every recommit
        retires a predecessor, so leaked predecessors would accumulate
        per recommit."""
        self.workflow = None

"""Pipelined host ingest executor: decode | prestage | step/publish.

Why
---
PERF.md's stage table says steady-state throughput should be
``max(stage)``, but the serial service loop pays ``sum(stages)``: only
the device transfer overlaps compute (``dispatch_safe``'s async
``device_put``), while ev44 accumulate/collect (decode), the host
flatten/partition (~32 ms per 4M events — the measured host bound once
pallas2d beats the 93M ev/s scatter ceiling) and the fused step/publish
all run back to back on the one service-loop thread. This module turns
the loop into a bounded three-stage pipeline (ADR 0111):

- **decode** — ``MessagePreprocessor`` accumulate + collect, then the
  window's staged events are *detached* (owned copies) so the service
  thread can release and refill the staging buffers for the next batch
  while this one is still in flight.
- **stage** — a fresh cache generation is attached
  (``JobManager.open_window``) and every subscribed consumer's wire is
  prestaged (``prestage_window``: host flatten/partition — optionally
  chunked over a thread pool — plus the async device transfer), warming
  the stage-once slots the step stage will hit.
- **step** — ``JobManager.process_jobs(prestaged=True)`` + publish, the
  only stage that touches job state, in submission order. On the
  tick-program fast path (ops/tick.py, ADR 0114) the stage's device
  work collapses to ONE submit: the prestaged wire feeds a single
  jitted step+publish program per group, so a steady-state window costs
  this stage one execute + one fetch — the "publish" timing below is
  sink serialization only, never a second device round trip.

Ordering and parity
-------------------
One worker per stage and FIFO bounded queues give a strict global order:
window i's step always precedes window i+1's step, and publishes leave
in submission order (asserted: a reordering is a bug, not a mode). The
work each stage runs is byte-for-byte the work the serial path runs —
prestaging uses the same keys and staging functions ``step_batch``/
``step_many`` would use, and per-state op order is unchanged — so
outputs are bit-identical to serial ingest (pinned by
tests/workflows/cache_parity_test.py).

Backpressure and shutdown
-------------------------
Queues are bounded and every put/get carries a timeout (graftlint
JGL010: an unbounded hand-off turns a slow stage into unbounded memory;
a timeout-less block turns shutdown into a hang). ``submit`` blocks when
the in-flight window count reaches the pipeline depth — a slow stage
throttles the service thread, which the adaptive batcher then sees as
processing time and answers with bigger windows. ``stop(drain=True)``
refuses new work, drains every queued window through all stages (no
drops, no reorders — pinned by tests/core/ingest_pipeline_test.py), and
joins the workers. A worker failure latches the exception and re-raises
it on the service thread at the next submit, preserving the serial
loop's fail-fast supervisor contract (core/service.py).

The pipeline depth adapts to the link (``core/link_monitor.py``): a
degraded or high-RTT link runs deeper (keep the transfer stage fed), a
healthy one shallower (latency).
"""

from __future__ import annotations

import logging
import queue
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field
from typing import Any, Callable

from ..telemetry.e2e import observe_stage
from ..telemetry.trace import TRACER
from ..utils.profiling import StageTimer
from .link_monitor import LinkMonitor, LinkPolicy

__all__ = ["IngestPipeline", "PipelineWindow"]

logger = logging.getLogger(__name__)

#: Worker poll tick: every blocking queue op times out at this interval
#: to observe shutdown (JGL010 — no timeout-less blocking on threads
#: that also dispatch jitted work).
_TICK_S = 0.1


@dataclass(slots=True)
class PipelineWindow:
    """One window moving through the stages."""

    seq: int
    payload: Any  # decode-stage input (MessageBatch or prebuilt window)
    start: Any = None
    end: Any = None
    data: dict[str, Any] = field(default_factory=dict)
    context: dict[str, Any] = field(default_factory=dict)
    fresh_context: set[str] | None = None
    generation: Any = None  # WindowGeneration, attached by the stage stage
    policy: LinkPolicy | None = None
    results: list = field(default_factory=list)
    #: Wall seconds per stage for THIS window (the completion callback's
    #: load signal: the slowest stage is the pipeline's service time).
    stage_s: dict[str, float] = field(default_factory=dict)
    t_submit: float = 0.0
    #: Telemetry trace id (ADR 0116), allocated at decode: every span
    #: this window records — across all three stage workers and the
    #: device layers — shares it, so a slow tick decomposes by phase.
    trace: int | None = None
    #: Source data timestamp (ns) of the newest message in this window
    #: (ADR 0120): born at consume from ``MessageBatch.end``, it anchors
    #: every ``livedata_e2e_latency_seconds`` boundary the window
    #: crosses (staged/published here; fanout/delivery in the serving
    #: plane via ``JobResult.source_ts_ns``).
    source_ts_ns: int | None = None
    #: Source timestamp (ns) of the OLDEST message in this window: the
    #: ``stage=decode`` observation anchors here (ADR 0125). Decode is
    #: batch-granular — one observation per window, not per message —
    #: and anchoring at the oldest member keeps the histogram an upper
    #: bound on any single message's decode latency instead of
    #: understating it by up to the window span. Falls back to
    #: ``source_ts_ns`` when the batcher provides no per-message view.
    oldest_ts_ns: int | None = None


class IngestPipeline:
    """Bounded multi-stage ingest executor (see module docstring).

    Parameters
    ----------
    job_manager:
        The service's JobManager; supplies ``open_window``,
        ``prestage_window`` and ``process_jobs``.
    decode:
        ``decode(payload) -> (data, context, fresh_context)`` — the
        processor's preprocess+collect+detach step. Receives the
        submitted payload; ``None`` payloads (empty windows flushed for
        finishing jobs) skip decode.
    publish:
        ``publish(results, end)`` — called from the step worker, in
        submission order, only when results are nonempty.
    on_complete:
        Optional ``on_complete(window)`` called after publish with the
        per-stage timings and the applied link policy (the processor
        feeds the batcher and its metrics from this).
    depth:
        Base bound on in-flight windows (the link policy may raise it
        up to ``max_depth``). Depth 1 degenerates to serial-with-threads.
    max_depth:
        Queue capacity and the ceiling for link-adaptive deepening.
    flatten_workers:
        >1 enables the chunked parallel host flatten in prestaging.
    link_monitor:
        Optional LinkMonitor; when present it is attached to the
        JobManager (bandwidth from the stage-once cache's real staging
        timings, publish RTT from the combined publish's execute+fetch
        round trips — ADR 0113) and consulted per window for the
        wire/batch/depth/publish-coalescing policy.
    """

    def __init__(
        self,
        *,
        job_manager,
        decode: Callable[[Any], tuple[dict, dict, set[str] | None]],
        publish: Callable[[list, Any], None],
        on_complete: Callable[[PipelineWindow], None] | None = None,
        depth: int = 2,
        max_depth: int = 4,
        flatten_workers: int = 0,
        link_monitor: LinkMonitor | None = None,
        name: str = "ingest",
    ) -> None:
        if depth < 1:
            raise ValueError("depth must be >= 1")
        self._job_manager = job_manager
        self._decode = decode
        self._publish = publish
        self._on_complete = on_complete
        self._base_depth = depth
        self._max_depth = max(max_depth, depth)
        self._link_monitor = link_monitor
        if link_monitor is not None and hasattr(
            job_manager, "set_link_observer"
        ):
            job_manager.set_link_observer(link_monitor)
        self._flatten_pool = (
            ThreadPoolExecutor(
                max_workers=flatten_workers,
                thread_name_prefix=f"{name}-flatten",
            )
            if flatten_workers > 1
            else None
        )
        # Bounded stage hand-offs (JGL010): capacity = max depth; the
        # real in-flight bound is the submit gate below, which follows
        # the link policy between base and max depth.
        self._decode_q: queue.Queue[PipelineWindow] = queue.Queue(
            maxsize=self._max_depth
        )
        self._stage_q: queue.Queue[PipelineWindow] = queue.Queue(
            maxsize=self._max_depth
        )
        self._step_q: queue.Queue[PipelineWindow] = queue.Queue(
            maxsize=self._max_depth
        )
        self._inflight = 0
        self._state_lock = threading.Condition()
        self._seq = 0
        self._last_completed_seq = -1
        self._completed = 0
        self._published = 0
        self._accepting = True
        self._stopped = threading.Event()
        self._failure: BaseException | None = None
        self._timer = StageTimer()
        self._t_started = time.monotonic()
        #: Fault-injection schedule (harness/chaos.py, ADR 0120): None
        #: in production — every hook is a single attribute check.
        self._chaos = None
        self.name = name
        self._workers = [
            threading.Thread(
                target=self._guarded, args=(fn,), name=f"{name}-{label}",
                daemon=True,
            )
            for label, fn in (
                ("decode", self._decode_loop),
                ("stage", self._stage_loop),
                ("step", self._step_loop),
            )
        ]
        for worker in self._workers:
            worker.start()

    # -- submission --------------------------------------------------------
    @property
    def depth(self) -> int:
        """Current in-flight window bound: the link policy's depth,
        clamped to this pipeline's ceiling. The monitor's neutral depth
        is its ``base_depth`` — construct the two with the same base
        (OrchestratingProcessor does) so a configured ``--pipeline-depth``
        is honored verbatim until the link asks for more."""
        if self._link_monitor is None:
            return self._base_depth
        return min(
            self._max_depth, max(1, self._link_monitor.policy().depth)
        )

    def submit(
        self, payload, *, start=None, end=None, oldest_ts_ns=None
    ) -> int:
        """Enqueue one window; blocks while the pipeline is at depth
        (backpressure — the caller's stall is the load signal). Returns
        the window's sequence number. ``oldest_ts_ns`` anchors the
        batch-granular ``stage=decode`` e2e observation (ADR 0125);
        omitted, it falls back to the window-end timestamp. Raises a
        latched worker failure or RuntimeError after ``stop()``."""
        self._reraise_failure()
        window = PipelineWindow(
            seq=-1, payload=payload, start=start, end=end,
            t_submit=time.monotonic(),
            source_ts_ns=(
                int(end.ns) if hasattr(end, "ns") else None
            ),
            oldest_ts_ns=(
                int(oldest_ts_ns) if oldest_ts_ns is not None else None
            ),
        )
        with self._state_lock:
            while self._accepting and self._inflight >= self.depth:
                self._state_lock.wait(timeout=_TICK_S)
                self._reraise_failure()
            if not self._accepting:
                raise RuntimeError(f"pipeline {self.name} is stopped")
            window.seq = self._seq
            self._seq += 1
            self._inflight += 1
        if not self._put(self._decode_q, window):
            self._reraise_failure()
            raise RuntimeError(f"pipeline {self.name} is stopped")
        return window.seq

    def flush(self, timeout: float | None = None) -> bool:
        """Wait until every submitted window has completed; True on
        drained, False on timeout."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._state_lock:
            while self._inflight > 0:
                self._reraise_failure()
                remaining = (
                    _TICK_S
                    if deadline is None
                    else min(_TICK_S, deadline - time.monotonic())
                )
                if remaining <= 0:
                    return False
                self._state_lock.wait(timeout=remaining)
            return True

    def stop(self, *, drain: bool = True, timeout: float = 30.0) -> bool:
        """Refuse new submits, optionally drain all in-flight windows
        through every stage (no drops, no reorders), stop the workers.
        Returns True when the drain completed. Idempotent."""
        with self._state_lock:
            self._accepting = False
            self._state_lock.notify_all()
        drained = True
        try:
            if drain and self._failure is None:
                drained = self.flush(timeout=timeout)
                if not drained:
                    logger.warning(
                        "pipeline %s: drain timed out with %d windows in "
                        "flight",
                        self.name,
                        self._inflight,
                    )
        finally:
            # A failure latched mid-drain makes flush raise — the
            # workers and the flatten pool must still be torn down, or
            # every in-process restart leaks three polling threads.
            self._stopped.set()
            for worker in self._workers:
                worker.join(timeout=5.0)
            if self._flatten_pool is not None:
                self._flatten_pool.shutdown(wait=False)
        return drained

    def set_chaos(self, chaos) -> None:
        """Install a fault-injection schedule (harness/chaos.py). The
        hooks fire on the worker threads; the schedule's own seeded
        draws keep runs reproducible."""
        self._chaos = chaos

    # -- introspection -----------------------------------------------------
    @property
    def failure(self) -> BaseException | None:
        return self._failure

    def stats(self) -> dict[str, Any]:
        """Per-stage busy time + utilization since the last drain.

        ``utilization`` is stage busy seconds over pipeline wall
        seconds: the slowest stage's utilization approaches 1.0 at
        steady state, and the *sum* exceeding 1.0 is the overlap the
        serial loop forfeits (bench.py --pipeline reports this)."""
        wall = max(time.monotonic() - self._t_started, 1e-9)
        stages = self._timer.drain()
        self._t_started = time.monotonic()
        with self._state_lock:
            completed, published = self._completed, self._published
            inflight = self._inflight
        return {
            "wall_s": wall,
            "completed": completed,
            "published": published,
            "inflight": inflight,
            "depth": self.depth,
            "stages": stages,
            "utilization": {
                stage: entry["total_s"] / wall
                for stage, entry in stages.items()
            },
        }

    def queue_depths(self) -> dict[str, int]:
        """Instantaneous per-stage queue depths (telemetry gauges,
        ADR 0116): a persistently full queue names the bottleneck stage
        the utilization averages can only hint at. ``qsize`` is racy by
        nature — that is fine for a gauge sampled at scrape time."""
        return {
            "decode": self._decode_q.qsize(),
            "stage": self._stage_q.qsize(),
            "step": self._step_q.qsize(),
        }

    def telemetry(self) -> dict[str, Any]:
        """Scrape-time snapshot for the telemetry collector: queue
        depths, in-flight/limit, window counts and CUMULATIVE per-stage
        busy seconds (never drained — ``stats()`` keeps its 30 s
        drain-and-reset semantics for the metrics log)."""
        with self._state_lock:
            completed, published = self._completed, self._published
            inflight = self._inflight
        return {
            "queues": self.queue_depths(),
            "inflight": inflight,
            "depth": self.depth,
            "completed": completed,
            "published": published,
            "stages": self._timer.cumulative(),
        }

    # -- stage workers -----------------------------------------------------
    def _guarded(self, loop: Callable[[], None]) -> None:
        try:
            loop()
        except BaseException as err:  # latch: resurfaced on submit
            logger.exception("pipeline %s worker failed", self.name)
            with self._state_lock:
                self._failure = err
                self._state_lock.notify_all()

    def _reraise_failure(self) -> None:
        if self._failure is not None:
            raise RuntimeError(
                f"pipeline {self.name} worker failed"
            ) from self._failure

    def _put(self, q: queue.Queue, window: PipelineWindow) -> bool:
        """Bounded hand-off to the next stage. False = the pipeline was
        stopped without drain; the caller discards the window."""
        while not self._stopped.is_set():
            try:
                q.put(window, timeout=_TICK_S)
                return True
            except queue.Full:
                if self._failure is not None:
                    break
        self._discard(window)
        return False

    def _discard(self, window: PipelineWindow) -> None:
        """Account for a window abandoned by a no-drain stop."""
        if window.generation is not None:
            window.generation.close()
        with self._state_lock:
            self._inflight -= 1
            self._state_lock.notify_all()

    def _get(self, q: queue.Queue) -> PipelineWindow | None:
        while not self._stopped.is_set():
            try:
                return q.get(timeout=_TICK_S)
            except queue.Empty:
                continue
        return None

    # graft: thread=decode
    def _decode_loop(self) -> None:
        while True:
            window = self._get(self._decode_q)
            if window is None:
                return
            # The trace id is born HERE, with the window's decode
            # (ADR 0116): every later span — prestage on the stage
            # worker, tick-execute/fetch in the device layers, finalize
            # and sink on the step worker — records against it.
            window.trace = TRACER.new_trace()
            t0 = time.perf_counter()
            with self._timer.stage("decode"):
                if window.payload is None:
                    window.data, window.context = {}, {}
                    window.fresh_context = None
                else:
                    (
                        window.data,
                        window.context,
                        window.fresh_context,
                    ) = self._decode(window.payload)
                    window.payload = None  # drop message refs early
            window.stage_s["decode"] = time.perf_counter() - t0
            TRACER.record(
                "decode", t0, window.stage_s["decode"], window.trace
            )
            observe_stage(
                "decode",
                window.oldest_ts_ns
                if window.oldest_ts_ns is not None
                else window.source_ts_ns,
            )
            if self._chaos is not None:
                # Chaos site (ADR 0120): a stalled decode worker — the
                # shape of a slow preprocessor or GC pause — backs the
                # whole pipeline up into the submit gate.
                self._chaos.maybe_delay("decode_stall")
            if not self._put(self._stage_q, window):
                return

    # graft: thread=stage
    def _stage_loop(self) -> None:
        while True:
            window = self._get(self._stage_q)
            if window is None:
                return
            t0 = time.perf_counter()
            with self._timer.stage("stage"):
                window.generation = self._job_manager.open_window(window.data)
                if self._link_monitor is not None:
                    window.policy = self._link_monitor.policy()
                # Wire flips re-key staging — safe against the window
                # currently mid-step because every staging pass
                # snapshots the flag once, key and payload together
                # (EventHistogrammer._staged_partition); the worst case
                # at a flip boundary is one private re-stage, and flips
                # are rare by construction (the policy latch has a
                # hysteresis dead zone).
                self._job_manager.prestage_window(
                    window.data,
                    pool=self._flatten_pool,
                    wire_compact=(
                        None
                        if window.policy is None
                        else window.policy.compact_wire
                    ),
                )
            window.stage_s["stage"] = time.perf_counter() - t0
            TRACER.record(
                "prestage", t0, window.stage_s["stage"], window.trace
            )
            observe_stage("staged", window.source_ts_ns)
            if not self._put(self._step_q, window):
                return

    # graft: thread=step
    def _step_loop(self) -> None:
        while True:
            window = self._get(self._step_q)
            if window is None:
                return
            try:
                t0 = time.perf_counter()
                # Bind the window's trace for everything the step runs:
                # the device layers (tick combiner execute/fetch spans,
                # finalize) read the thread-bound id — they don't know
                # the window.
                with self._timer.stage("step"), TRACER.bind(window.trace):
                    window.results = self._job_manager.process_jobs(
                        window.data,
                        context=window.context,
                        fresh_context=window.fresh_context,
                        start=window.start,
                        end=window.end,
                        prestaged=True,
                    )
                window.stage_s["step"] = time.perf_counter() - t0
                t0 = time.perf_counter()
                with self._timer.stage("publish"):
                    if window.results:
                        with TRACER.span("sink", window.trace):
                            self._publish(window.results, window.end)
                        # "published" means results actually left: an
                        # empty window (no jobs due) records nothing.
                        observe_stage("published", window.source_ts_ns)
                # Publish-stage time here is sink serialization only:
                # the RTT observation moved to the device round trip
                # itself (JobManager times every combined execute+fetch
                # — and every whole-tick program — into the monitor,
                # ADR 0113/0114, compile rounds excluded) — feeding
                # sink time as "RTT" would anchor the
                # publish-coalescing policy on the wrong quantity.
                window.stage_s["publish"] = time.perf_counter() - t0
            finally:
                if window.generation is not None:
                    window.generation.close()
            if window.seq != self._last_completed_seq + 1:
                # Single-worker FIFO stages make this structurally
                # impossible; if it ever fires, ordering — a correctness
                # guarantee consumers rely on — broke. Fail loudly.
                raise RuntimeError(
                    f"pipeline {self.name} reordered windows: completed "
                    f"{window.seq} after {self._last_completed_seq}"
                )
            self._last_completed_seq = window.seq
            if window.trace is not None:
                # Slow-tick watchdog (ADR 0116): submit->published wall
                # time against the latched threshold; a breach logs this
                # window's full span breakdown.
                TRACER.finish_tick(
                    window.trace, time.monotonic() - window.t_submit
                )
            if self._on_complete is not None:
                try:
                    self._on_complete(window)
                except Exception:
                    logger.exception(
                        "pipeline %s completion callback failed", self.name
                    )
            with self._state_lock:
                self._inflight -= 1
                self._completed += 1
                if window.results:
                    self._published += 1
                self._state_lock.notify_all()

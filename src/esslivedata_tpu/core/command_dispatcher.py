"""Command routing with shared-topic ownership semantics.

Parity with reference ``core/job_manager_adapter.py`` (:14, silence-if-not-
owner :26-56) and ``core/command_dispatcher.py`` (:18): all services share
one commands topic; a service acks/errs only commands for workflows *it*
hosts and stays silent otherwise, so each command gets exactly one reply
across the fleet.
"""

from __future__ import annotations

import logging
import uuid
from collections.abc import Sequence

from pydantic import ValidationError

from ..config.acknowledgement import CommandAcknowledgement
from ..config.workflow_spec import WorkflowConfig
from ..workflows.workflow_factory import WorkflowFactory, workflow_registry
from .job_manager import JobCommand, JobManager
from .message import Message

__all__ = ["CommandDispatcher"]

logger = logging.getLogger(__name__)


class CommandDispatcher:
    def __init__(
        self,
        *,
        job_manager: JobManager,
        instrument: str,
        service_name: str = "",
        registry: WorkflowFactory | None = None,
    ) -> None:
        self._job_manager = job_manager
        self._instrument = instrument
        self._service_name = service_name
        self._registry = registry if registry is not None else workflow_registry

    def _owns(self, config: WorkflowConfig) -> bool:
        wid = config.identifier
        if not (
            wid.instrument == self._instrument
            and wid in self._registry
            and self._registry.has_factory(wid)
        ):
            return False
        # All of an instrument's factories load in every service process, so
        # a factory being attached is not ownership — the hosting service is
        # (matching the subscription scoping: a non-hosting service has no
        # data streams for the job and would ack then sit idle forever).
        if self._service_name:
            from ..config.route_derivation import spec_service

            return spec_service(self._registry[wid]) == self._service_name
        return True

    def process_messages(
        self, messages: Sequence[Message]
    ) -> list[CommandAcknowledgement]:
        acks: list[CommandAcknowledgement] = []
        for msg in messages:
            value = msg.value
            if isinstance(value, WorkflowConfig):
                if not self._owns(value):
                    continue  # another service's workflow: stay silent
                acks.append(self._start_job(value))
            elif isinstance(value, dict) and value.get("kind") == "job_command":
                ack = self._job_command(value)
                if ack is not None:
                    acks.append(ack)
            elif isinstance(value, dict) and value.get("kind") == "roi_update":
                ack = self._roi_update(value)
                if ack is not None:
                    acks.append(ack)
            else:
                logger.warning("Unrecognized command payload: %r", type(value))
        return acks

    def _start_job(self, config: WorkflowConfig) -> CommandAcknowledgement:
        try:
            self._job_manager.schedule_job(config)
            return CommandAcknowledgement(
                source_name=config.job_id.source_name,
                job_number=config.job_id.job_number,
                status="ack",
                service=self._service_name,
            )
        except Exception as err:
            logger.exception("Failed to schedule job %s", config.job_id)
            return CommandAcknowledgement(
                source_name=config.job_id.source_name,
                job_number=config.job_id.job_number,
                status="error",
                message=f"{type(err).__name__}: {err}",
                service=self._service_name,
            )

    def _job_command(self, payload: dict) -> CommandAcknowledgement | None:
        try:
            command = JobCommand.model_validate(payload)
        except ValidationError:
            logger.warning("Malformed job command: %r", payload)
            return None
        try:
            acted = self._job_manager.handle_command(command)
            if acted == 0:
                return None  # not our job: silent (another service owns it)
            status, message = "ack", f"acted_on={acted}" if acted > 1 else ""
        except Exception as err:
            status, message = "error", f"{type(err).__name__}: {err}"
        # Scoped/broadcast selectors have no single job identity: the ack
        # echoes the selector with a nil job number (dashboards track
        # per-job commands only and ignore unknown-job acks by contract).
        return CommandAcknowledgement(
            source_name=command.source_name or command.workflow_id or "*",
            job_number=command.job_number or uuid.UUID(int=0),
            status=status,
            message=message,
            service=self._service_name,
        )

    def _roi_update(self, payload: dict) -> CommandAcknowledgement | None:
        """ROI updates route to the job's workflow if it supports set_rois
        (the detector-view round trip, reference roi readbacks)."""
        try:
            command = JobCommand.model_validate({**payload, "action": "reset"})
        except ValidationError:
            logger.warning("Malformed roi update: %r", payload)
            return None
        rois = payload.get("rois", {})
        with self._job_manager._lock:  # noqa: SLF001
            for jid, rec in self._job_manager._records.items():  # noqa: SLF001
                if (
                    jid.source_name == command.source_name
                    and jid.job_number == command.job_number
                ):
                    wf = rec.job.workflow
                    if wf is None:
                        # Released on stop: the job is ours, so stay
                        # audible — an error ack beats a silent timeout
                        # on the dashboard side.
                        return CommandAcknowledgement(
                            source_name=command.source_name,
                            job_number=command.job_number,
                            status="error",
                            message="job is stopped; ROI update ignored",
                            service=self._service_name,
                        )
                    if hasattr(wf, "set_rois"):
                        try:
                            from ..config.models import PolygonROI, RectangleROI

                            parsed = {
                                name: (
                                    RectangleROI.model_validate(r)
                                    if "x_min" in r
                                    else PolygonROI.model_validate(r)
                                )
                                for name, r in rois.items()
                            }
                            wf.set_rois(parsed)
                            status, message = "ack", ""
                        except Exception as err:
                            status, message = "error", str(err)
                        return CommandAcknowledgement(
                            source_name=command.source_name,
                            job_number=command.job_number,
                            status=status,
                            message=message,
                            service=self._service_name,
                        )
        return None

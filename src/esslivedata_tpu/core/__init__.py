"""Core runtime: domain types, batchers, service loop, jobs, control plane.

Mirrors the responsibilities of the reference's ``src/ess/livedata/core/``
(SURVEY.md section 2.1) with the same protocol seams — MessageSource /
MessageSink / Processor / Accumulator / Workflow — so every layer above and
below can be faked in tests exactly like the reference does.
"""

from .message import (
    COMMANDS_STREAM_ID,
    RESPONSES_STREAM_ID,
    RUN_CONTROL_STREAM_ID,
    STATUS_STREAM_ID,
    Message,
    MessageSink,
    MessageSource,
    RunStart,
    RunStop,
    StreamId,
    StreamKind,
)
from .timestamp import Duration, Timestamp

__all__ = [
    "COMMANDS_STREAM_ID",
    "Duration",
    "Message",
    "MessageSink",
    "MessageSource",
    "RESPONSES_STREAM_ID",
    "RUN_CONTROL_STREAM_ID",
    "RunStart",
    "RunStop",
    "STATUS_STREAM_ID",
    "StreamId",
    "StreamKind",
    "Timestamp",
]

"""Stage-once device event cache: one transfer per (stream, layout), not per job.

Before this cache, every job subscribed to a detector stream staged the
window's event batch privately — K jobs on one stream meant K host
flatten/partition passes and K host→device transfers of identical bytes
(``Job.add`` → per-workflow ``accumulate`` → ``dispatch_safe``). The
relay link is the measured bottleneck (PERF.md: 4 B/event of wire
traffic, 6× bandwidth volatility), so per-job staging scaled the binding
constraint by K for no information gain. This module inverts the
ownership: staging belongs to the *stream*, jobs consume device-resident
arrays by reference — the same share-the-staged-input move inference
serving stacks use to amortize transfer cost across consumers (ADR 0110).

Lifecycle (serial path, driven by ``JobManager.process_jobs``):

- ``begin_window()`` opens a new window generation; per-stream
  :class:`StreamStageSlot` handles are attached to the window's
  ``StagedEvents`` values.
- Consumers (workflow kernels) call ``slot.get_or_stage(key, fn)``:
  the first caller under a key runs ``fn`` (host decode→flatten→
  ``dispatch_safe``) and every later caller — any job, any thread —
  gets the same staged object back.
- ``end_window()`` drops every staged reference. Entries never outlive
  a window (each window carries new events), which also makes job
  attach/detach trivially safe: a job added or removed between windows
  can never observe another generation's arrays.

The pipelined ingest (``core/ingest_pipeline.py``, ADR 0111) overlaps
windows — window i+1 prestages while window i still steps — so a single
"current" generation is not enough there. ``new_generation()`` hands out
an independent, caller-owned :class:`WindowGeneration` whose slots and
lifetime the pipeline controls explicitly; the begin/end window pair
above remains a thin wrapper over the cache-owned current generation.

Keys must capture *everything* that changes the staged bytes: the
staging flavor ("raw"/"flat"/"part"/"shard"), a caller-chosen
``batch_tag`` for pre-staging transforms (e.g. the monitor workflow's
pixel-id clamp), and the projection-layout fingerprint
(``EventHistogrammer.stage_key`` — LUT digest, bin edges, block/chunk
shape). A projection-layout change therefore invalidates by *keying*,
not by flushing: the swapped layout simply misses and stages fresh.

Thread-safety: ``process_jobs`` fans consumers over a thread pool, so a
slot serializes staging per key under its lock — the second job *waits*
for the first transfer instead of duplicating it.
"""

from __future__ import annotations

import logging
import threading
import time
from dataclasses import dataclass
from typing import Any, Callable, Hashable

import numpy as np

__all__ = [
    "DecodeArena",
    "DecodeArenaPool",
    "DeviceEventCache",
    "EventIngest",
    "StreamStageSlot",
    "WindowGeneration",
    "default_decode_pool",
]

logger = logging.getLogger(__name__)


# ---------------------------------------------------------------------------
# Decode arenas (ADR 0125): reusable staging landing zones for batch decode
# ---------------------------------------------------------------------------

#: Floor arena capacity: below this, growth churn dominates reuse.
_ARENA_MIN = 1 << 12
#: Free-list depth: the pipelined ingest keeps at most a few windows in
#: flight, so a deeper pool would only pin dead memory.
_ARENA_POOL_DEPTH = 4


def _arena_capacity(n: int) -> int:
    """Power-of-two capacity ≥ max(n, floor) — mirrors the event-batch
    bucketing (ops/event_batch.py) so one steady-state arena per pool
    slot absorbs every poll size without reallocating."""
    cap = _ARENA_MIN
    while cap < n:
        cap <<= 1
    return cap


class DecodeArena:
    """One pinned (page-locked where the allocator provides it; plain
    host-contiguous otherwise) staging landing zone for the batch wire
    decoder: an int32 pixel lane and a float32 time-of-arrival lane that
    grow geometrically and are reused poll after poll.

    Ownership contract: whoever holds the :class:`_ArenaLease` wrapping
    an arena owns BOTH lanes outright — views into them
    (``kafka.wire.Ev44Batch``, the ``EventBatch`` a ref-mode
    ``ToEventBatch`` emits) stay valid exactly as long as the lease is
    referenced, and the arena re-enters its pool only when the lease is
    garbage-collected."""

    __slots__ = ("pixel", "toa", "capacity")

    def __init__(self, capacity: int = _ARENA_MIN) -> None:
        capacity = _arena_capacity(capacity)
        self.capacity = capacity
        self.pixel = np.empty(capacity, dtype=np.int32)
        self.toa = np.empty(capacity, dtype=np.float32)

    def ensure(self, n: int) -> None:
        """Grow (never shrink) to hold at least ``n`` events."""
        if n > self.capacity:
            cap = _arena_capacity(n)
            self.capacity = cap
            self.pixel = np.empty(cap, dtype=np.int32)
            self.toa = np.empty(cap, dtype=np.float32)


class _ArenaLease:
    """Checkout handle for one arena: proxies the lanes, returns the
    arena to its pool on finalization. The return is reference-counted
    by Python itself — a decoded batch keeps its lease alive through
    ``EventBatch.owner``, so an arena can never be handed to the next
    poll while a previous window still reads it."""

    __slots__ = ("_pool", "_arena")

    def __init__(self, pool: DecodeArenaPool, arena: DecodeArena) -> None:
        self._pool = pool
        self._arena = arena

    @property
    def pixel(self) -> np.ndarray:
        return self._arena.pixel

    @property
    def toa(self) -> np.ndarray:
        return self._arena.toa

    @property
    def capacity(self) -> int:
        return self._arena.capacity

    def __del__(self) -> None:
        # A finalizer may run during interpreter shutdown, when the
        # pool's lock/module globals are already torn down — logging
        # here can itself raise, so this swallow stays silent.
        try:
            self._pool._release(self._arena)
        except Exception:  # graftlint: disable=JGL007
            pass  # pragma: no cover - interpreter shutdown


class DecodeArenaPool:
    """Bounded free list of :class:`DecodeArena`.

    ``lease(n)`` hands out an arena sized for ``n`` events (reusing a
    pooled one when available, growing it in place if undersized); the
    lease's finalizer returns it. Keeping the pool bounded means a
    pathological burst allocates transient arenas that simply drop on
    release instead of ratcheting resident memory."""

    def __init__(self, depth: int = _ARENA_POOL_DEPTH) -> None:
        self._lock = threading.Lock()
        self._free: list[DecodeArena] = []
        self._depth = depth

    def lease(self, n: int) -> _ArenaLease:
        with self._lock:
            arena = self._free.pop() if self._free else None
        if arena is None:
            arena = DecodeArena(n)
        else:
            arena.ensure(n)
        return _ArenaLease(self, arena)

    def _release(self, arena: DecodeArena) -> None:
        with self._lock:
            if len(self._free) < self._depth:
                self._free.append(arena)

    def free_count(self) -> int:
        with self._lock:
            return len(self._free)


_DEFAULT_POOL: DecodeArenaPool | None = None
_DEFAULT_POOL_LOCK = threading.Lock()


def default_decode_pool() -> DecodeArenaPool:
    """Process-wide arena pool the batch wire decoder leases from when
    the caller does not bring its own."""
    global _DEFAULT_POOL
    if _DEFAULT_POOL is None:
        with _DEFAULT_POOL_LOCK:
            if _DEFAULT_POOL is None:
                _DEFAULT_POOL = DecodeArenaPool()
    return _DEFAULT_POOL


@dataclass(frozen=True)
class EventIngest:
    """A workflow's offer to have one staged-events value ingested by the
    fused stepping layer instead of its own ``accumulate``.

    Workflows that step a shared :class:`~..ops.histogram.EventHistogrammer`
    state from a ``StagedEvents`` value expose ``event_ingest(stream,
    staged) -> EventIngest | None`` (duck-typed, like ``supports_snapshot``).
    The JobManager groups offers by ``(stream, key)`` and advances every
    group member's state in ONE jitted dispatch (``step_many``) from ONE
    cached staging — then tells the job to skip that stream in
    ``accumulate`` so nothing double-counts.

    ``key`` must be the histogrammer's ``fuse_key`` extended with the
    ``batch_tag``: equal keys promise both identical staged input and an
    identical step program.
    """

    key: tuple
    hist: Any  # EventHistogrammer (duck-typed: step_many)
    batch: Any  # EventBatch, possibly transformed (must match batch_tag)
    batch_tag: str
    get_state: Callable[[], Any]
    set_state: Callable[[Any], None]

    def stage(self, cache, *, pool=None, device=None) -> tuple:
        """The staged device arrays for this offer's wire, handed
        STRAIGHT into a fused/tick program (ops/tick.py, ADR 0114) as a
        flat tuple — no per-job intermediate views are materialized.
        Same keys and staging functions as ``step_many`` would use, so
        the transfer happens once per (stream, layout) however many
        jobs' states the program advances, and a prestaged window
        (ADR 0111) is a guaranteed hit. ``device`` is the group's mesh
        slice (parallel/mesh_tick.py): the wire is committed there and
        the stage-once key carries it, so staging is once per slice.
        The kwarg is forwarded only when set — bespoke duck-typed
        histogrammers predating slice placement keep working."""
        kwargs = {} if device is None else {"device": device}
        return self.hist.tick_staging(
            self.batch, cache, batch_tag=self.batch_tag, pool=pool,
            **kwargs,
        )


def _staged_nbytes(obj: Any) -> int:
    """Approximate wire bytes of a staged object (array or tuple of
    arrays): jax and numpy arrays both expose ``nbytes``."""
    if isinstance(obj, tuple):
        return sum(_staged_nbytes(o) for o in obj)
    return int(getattr(obj, "nbytes", 0))


class _StageEntry:
    """Per-key staging latch: the first claimant stages, later claimants
    wait on the event instead of duplicating the work — while *other*
    keys on the same stream stage concurrently (two projection layouts
    must not serialize each other's host flattens)."""

    __slots__ = ("event", "value", "error")

    def __init__(self) -> None:
        self.event = threading.Event()
        self.value: Any = None
        self.error: BaseException | None = None


class StreamStageSlot:
    """One stream's staging table for the current window."""

    __slots__ = ("_cache", "stream", "_entries", "_lock", "_closed")

    def __init__(self, cache: DeviceEventCache, stream: str) -> None:
        self._cache = cache
        self.stream = stream
        self._entries: dict[Hashable, _StageEntry] = {}
        self._lock = threading.Lock()
        self._closed = False

    def get_or_stage(self, key: Hashable, stage: Callable[[], Any]) -> Any:
        """The staged object for ``key``; runs ``stage`` exactly once per
        window per key (concurrent same-key callers wait; distinct keys
        stage in parallel). After ``end_window`` the slot degrades to a
        passthrough (stage, don't retain) so a late consumer — a
        finishing job flushed on an idle tick — can never pin or read a
        stale generation."""
        with self._lock:
            if self._closed:
                owner, entry = True, None
            else:
                entry = self._entries.get(key)
                owner = entry is None
                if owner:
                    entry = _StageEntry()
                    self._entries[key] = entry
        if entry is None:  # closed slot: pure passthrough
            return stage()
        if owner:
            t0 = time.perf_counter()
            try:
                entry.value = stage()
            except BaseException as err:
                entry.error = err
                # Drop the poisoned entry so a later caller may retry
                # (the private fallback path re-stages after a fused
                # failure, and must not inherit the dead latch).
                with self._lock:
                    if self._entries.get(key) is entry:
                        del self._entries[key]
                raise
            finally:
                entry.event.set()
            # Real staging timings are the link monitor's only probe
            # (ADR 0111): wall time of the flatten+dispatch against the
            # bytes it moved, measured where the work actually happens.
            self._cache._record_miss(
                _staged_nbytes(entry.value), time.perf_counter() - t0
            )
            return entry.value
        entry.event.wait()
        if entry.error is not None:
            raise entry.error
        self._cache._record_hit()
        return entry.value

    def __contains__(self, key: Hashable) -> bool:
        with self._lock:
            entry = self._entries.get(key)
            return entry is not None and entry.event.is_set()

    def _close(self) -> None:
        with self._lock:
            self._closed = True
            self._entries.clear()


class WindowGeneration:
    """One window's staging slots, as an explicit caller-owned handle.

    The serial path never sees this class (the cache keeps a private
    current generation behind ``begin_window``/``end_window``); the
    pipelined ingest opens one generation per in-flight window and
    closes it after that window's publish, so two overlapped windows
    can never alias each other's staged arrays."""

    __slots__ = ("_cache", "_slots", "_lock", "_closed")

    def __init__(self, cache: DeviceEventCache) -> None:
        self._cache = cache
        self._slots: dict[str, StreamStageSlot] = {}
        self._lock = threading.Lock()
        self._closed = False

    def slot(self, stream: str) -> StreamStageSlot:
        with self._lock:
            try:
                return self._slots[stream]
            except KeyError:
                s = StreamStageSlot(self._cache, stream)
                if self._closed:
                    # A slot requested after close degrades to the same
                    # passthrough as a closed slot: never retain.
                    s._close()
                self._slots[stream] = s
                return s

    def close(self) -> None:
        """Drop every staged reference; later consumers pass through."""
        with self._lock:
            self._closed = True
            for slot in self._slots.values():
                slot._close()
            self._slots = {}


class DeviceEventCache:
    """Per-stream stage-once cache for one service's event streams."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._current = WindowGeneration(self)
        # Cumulative stats since construction / last drain: the bench's
        # wire_bytes_per_event and the 30 s metrics line read these.
        # Leaf-level lock: _record_* run while a slot lock is held, so
        # they must never reach back for the generation lock above.
        self._stats_lock = threading.Lock()
        self._hits = 0
        self._misses = 0
        self._bytes_staged = 0
        self._staging_s = 0.0
        # Cumulative twins drain_stats() never resets — the telemetry
        # collector (ADR 0116) needs monotone counters while the 30 s
        # metrics line keeps draining its own interval totals.
        self._cum_hits = 0
        self._cum_misses = 0
        self._cum_bytes_staged = 0
        self._cum_staging_s = 0.0
        #: Optional core.link_monitor.LinkMonitor (duck-typed:
        #: ``observe_staging(nbytes, seconds)``) fed from real staging
        #: timings — the pipelined ingest attaches it (ADR 0111).
        self.link_observer: Any = None

    # -- window lifecycle -------------------------------------------------
    def new_generation(self) -> WindowGeneration:
        """An independent window generation the caller owns and closes —
        the pipelined ingest's per-in-flight-window handle."""
        return WindowGeneration(self)

    def begin_window(self) -> None:
        """Open a new window generation: previous slots close (their
        staged references drop) and fresh slots hand out on demand."""
        with self._lock:
            self._current.close()
            self._current = WindowGeneration(self)

    def slot(self, stream: str) -> StreamStageSlot:
        with self._lock:
            return self._current.slot(stream)

    def end_window(self) -> None:
        """Drop every staged reference. Device memory frees once the last
        in-flight kernel consuming an array completes (JAX refcounts);
        the cache never pins a batch past its window."""
        self.begin_window()

    def invalidate(self) -> None:
        """Flush all slots immediately (job attach/detach hook). With
        window-scoped entries this is belt-and-braces — entries cannot
        cross windows anyway — but it keeps the invalidation rule
        explicit at the call sites that change the consumer set."""
        self.begin_window()

    # -- stats ------------------------------------------------------------
    def _record_miss(self, nbytes: int, seconds: float = 0.0) -> None:
        with self._stats_lock:
            self._misses += 1
            self._bytes_staged += nbytes
            self._staging_s += seconds
            self._cum_misses += 1
            self._cum_bytes_staged += nbytes
            self._cum_staging_s += seconds
        observer = self.link_observer
        if observer is not None:
            try:
                observer.observe_staging(nbytes, seconds)
            except Exception:
                # The estimate is advisory; a broken observer must not
                # take staging down — but it should be visible.
                logger.debug("link observer failed", exc_info=True)

    def _record_hit(self) -> None:
        with self._stats_lock:
            self._hits += 1
            self._cum_hits += 1

    def cumulative_stats(self) -> dict[str, int | float]:
        """Monotone totals since construction (telemetry collector)."""
        with self._stats_lock:
            return {
                "hits": self._cum_hits,
                "misses": self._cum_misses,
                "bytes_staged": self._cum_bytes_staged,
                "staging_s": self._cum_staging_s,
            }

    def stats(self) -> dict[str, int | float]:
        """{hits, misses, bytes_staged, staging_s, hit_rate} since the
        last drain."""
        with self._stats_lock:
            total = self._hits + self._misses
            return {
                "hits": self._hits,
                "misses": self._misses,
                "bytes_staged": self._bytes_staged,
                "staging_s": self._staging_s,
                "hit_rate": (self._hits / total) if total else 0.0,
            }

    def drain_stats(self) -> dict[str, int | float]:
        with self._stats_lock:
            total = self._hits + self._misses
            out = {
                "hits": self._hits,
                "misses": self._misses,
                "bytes_staged": self._bytes_staged,
                "staging_s": self._staging_s,
                "hit_rate": (self._hits / total) if total else 0.0,
            }
            self._hits = 0
            self._misses = 0
            self._bytes_staged = 0
            self._staging_s = 0.0
        return out

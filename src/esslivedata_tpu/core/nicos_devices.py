"""Extraction of contracted workflow outputs onto the NICOS device topic.

Parity with reference ``core/nicos_devices.py`` (ADR 0006): outputs that the
per-instrument :class:`~esslivedata_tpu.config.device_contract.DeviceContract`
designates are republished on a dedicated low-volume stream keyed by a stable
*device name* — free of the job_number carried by the main data path — so
NICOS sees a stable device identity across reconfigurations. The output's
``start_time`` coordinate (stamped by the job layer) rides along as a
generation change-detector: it changes on reset/reconfigure, letting NICOS
distinguish a post-reset zero from a genuine low reading.
"""

from __future__ import annotations

import logging

from ..config.device_contract import DeviceContract
from ..utils.labeled import DataArray
from .job import JobResult
from .message import Message, StreamId, StreamKind
from .timestamp import Timestamp

__all__ = ["DeviceExtractor"]

logger = logging.getLogger(__name__)


class DeviceExtractor:
    """Builds NICOS device messages from finalized job results."""

    def __init__(self, *, device_contract: DeviceContract) -> None:
        self._contract = device_contract
        self._warned_names: set[str] = set()

    def extract(self, results: list[JobResult]) -> list[Message[DataArray]]:
        """One message per contracted output present in ``results``, keyed by
        device name on the ``LIVEDATA_NICOS_DATA`` stream.

        Device names drop the job_number on purpose (stable identity), so two
        concurrent jobs of the same (workflow, source) would write the same
        device. First result wins within a cycle; the collision is logged
        once — running duplicates is an operator error the main data path
        tolerates but the device path cannot express.
        """
        messages: list[Message[DataArray]] = []
        emitted: set[str] = set()
        for result in results:
            entries = self._contract.devices_for(
                result.workflow_id, result.job_id.source_name
            )
            for entry in entries:
                da = result.outputs.get(entry.output_name)
                if da is None:
                    continue
                if entry.device_name in emitted:
                    if entry.device_name not in self._warned_names:
                        self._warned_names.add(entry.device_name)
                        logger.warning(
                            "Multiple jobs write NICOS device %r; "
                            "keeping the first per cycle",
                            entry.device_name,
                        )
                    continue
                emitted.add(entry.device_name)
                # The message timestamp is the RESULT time (window end):
                # it advances every update, so timestamp-keyed NICOS
                # caches see fresh values. The generation marker rides
                # the start_time coord, not the envelope.
                messages.append(
                    Message(
                        timestamp=result.end
                        or result.start
                        or Timestamp.from_ns(0),
                        stream=StreamId(
                            kind=StreamKind.LIVEDATA_NICOS_DATA,
                            name=entry.device_name,
                        ),
                        value=da,
                    )
                )
        return messages

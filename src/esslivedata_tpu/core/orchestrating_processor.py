"""The heart of a backend service: one processor cycle.

Parity with reference ``core/orchestrating_processor.py`` (process:200):
pull -> split commands/run-control/data (:212-218) -> dispatch commands ->
batch -> preprocess per stream (MessagePreprocessor:55) -> context
enrichment -> JobManager.process_jobs (:286) -> publish results -> release
buffers (zero-copy contract :287) -> 2 s status heartbeats (:327) and 30 s
metrics (:364-415) -> idempotent finalize (:417) publishing final stopped
statuses. Per-batch processing time feeds the adaptive batcher — the
implicit load profiler.
"""

from __future__ import annotations

import logging
import threading
import time
from collections.abc import Iterable
from typing import Any

from ..config.acknowledgement import CommandAcknowledgement
from ..core.preprocessor import PreprocessorFactory
from ..telemetry.e2e import observe_stage
from ..telemetry.trace import TRACER
from .command_dispatcher import CommandDispatcher
from .job_manager import JobManager
from .job import JobResult, ServiceStatus, StreamLag, StreamLagReport
from .message import (
    RESPONSE_STREAM,
    STATUS_STREAM,
    Message,
    MessageSink,
    MessageSource,
    RunStart,
    RunStop,
    StreamId,
    StreamKind,
)
from .message_batcher import MessageBatcher
from .timestamp import Duration, Timestamp

__all__ = ["MessagePreprocessor", "OrchestratingProcessor"]

logger = logging.getLogger(__name__)

HEARTBEAT_INTERVAL_S = 2.0
METRICS_INTERVAL_S = 30.0


def _transport_of(source, max_depth: int = 8):
    """Innermost transport exposing health+metrics, or None.

    The processor's source is a decorator chain (AdaptingMessageSource
    holds ``_source``; the synthesizers hold ``_wrapped``); the
    circuit-breaker state lives on the raw transport at the bottom
    (kafka/source.py BackgroundMessageSource.health)."""
    s = source
    for _ in range(max_depth):
        if s is None:
            return None
        if hasattr(s, "health") and hasattr(s, "metrics"):
            return s
        s = getattr(s, "_source", None) or getattr(s, "_wrapped", None)
    return None


def _oldest_ts_ns(batch) -> int | None:
    """Oldest member timestamp (ns) of a closed MessageBatch — the
    batch-granular ``stage=decode`` e2e anchor (ADR 0125). Batches are
    mostly time-ordered but merge multiple streams, so take the true
    minimum; None when the batch carries no timestamped messages."""
    messages = getattr(batch, "messages", None)
    if not messages:
        return None
    try:
        return min(int(m.timestamp.ns) for m in messages)
    except (AttributeError, TypeError, ValueError):
        return None


class MessagePreprocessor:
    """Routes batch messages into per-stream accumulators."""

    def __init__(self, factory: PreprocessorFactory) -> None:
        self._factory = factory
        self._accumulators: dict[StreamId, Any] = {}
        self._touched: set[StreamId] = set()
        self._dropped_streams: set[StreamId] = set()
        self.message_counts: dict[str, int] = {}
        # Pipelined ingest moves preprocess onto the decode worker while
        # the service thread keeps reading the counts for heartbeats —
        # the increment is a read-modify-write and the status snapshot
        # iterates the dict, so both sides take this lock (uncontended
        # acquisition is tens of ns against the >= 71 ms window).
        self._counts_lock = threading.Lock()

    def _get(self, stream: StreamId):
        if stream in self._accumulators:
            return self._accumulators[stream]
        if stream in self._dropped_streams:
            return None
        acc = self._factory.make_preprocessor(stream)
        if acc is None:
            self._dropped_streams.add(stream)
            return None
        self._accumulators[stream] = acc
        return acc

    def preprocess(self, messages: Iterable[Message]) -> None:
        for msg in messages:
            acc = self._get(msg.stream)
            if acc is None:
                continue
            try:
                acc.add(msg.timestamp, msg.value)
            except Exception:
                logger.exception("Accumulator failed for %s", msg.stream)
                continue
            self._touched.add(msg.stream)
            with self._counts_lock:
                self.message_counts[msg.stream.name] = (
                    self.message_counts.get(msg.stream.name, 0) + 1
                )

    def collect_window(self) -> dict[str, Any]:
        """Primary (non-context) data accumulated since last collect."""
        out: dict[str, Any] = {}
        for stream in self._touched:
            acc = self._accumulators[stream]
            if getattr(acc, "is_context", False):
                continue
            try:
                out[stream.name] = acc.get()
            except Exception:
                logger.exception("Accumulator get failed for %s", stream)
        return out

    def collect_context(self) -> dict[str, Any]:
        """Latest value of every context accumulator that has one.

        ``also_context`` marks primary accumulators whose value is
        additionally exposed as context — e.g. timeseries logs that both
        republish as data and gate/parameterize other jobs (the reference
        routes the same f144 stream to republish and to spec-scope context
        bindings)."""
        out: dict[str, Any] = {}
        for stream, acc in self._accumulators.items():
            if not (
                getattr(acc, "is_context", False)
                or getattr(acc, "also_context", False)
            ):
                continue
            if hasattr(acc, "has_value") and not acc.has_value:
                continue
            try:
                out[stream.name] = acc.get()
            except ValueError:
                continue
        return out

    def fresh_context_names(self) -> set[str]:
        """Context streams that received data in this batch.

        The JobManager delivers ``set_context`` to active jobs only for
        these, so an unchanged cached value never re-fires downstream
        recompute. Must be read before :meth:`release` clears the batch's
        touched set.
        """
        out: set[str] = set()
        for stream in self._touched:
            acc = self._accumulators.get(stream)
            if acc is not None and (
                getattr(acc, "is_context", False)
                or getattr(acc, "also_context", False)
            ):
                out.add(stream.name)
        return out

    def snapshot_counts(self) -> dict[str, int]:
        """Copy of the per-stream message counts, safe against the
        decode worker's concurrent increments."""
        with self._counts_lock:
            return dict(self.message_counts)

    def release(self) -> None:
        for stream in self._touched:
            self._accumulators[stream].release_buffers()
        self._touched.clear()


class OrchestratingProcessor:
    """Processor implementation wiring source -> jobs -> sink."""

    def __init__(
        self,
        *,
        source: MessageSource,
        sink: MessageSink,
        preprocessor_factory: PreprocessorFactory,
        job_manager: JobManager,
        batcher: MessageBatcher,
        instrument: str,
        service_name: str,
        registry=None,
        device_extractor=None,
        stream_counter=None,
        clock=time.monotonic,
        heartbeat_interval_s: float = HEARTBEAT_INTERVAL_S,
        pipelined: bool = False,
        pipeline_depth: int = 2,
        flatten_threads: int = 0,
        link_monitor=None,
        result_fanout=None,
        durability=None,
    ) -> None:
        self._source = source
        self._sink = sink
        self._preprocessor = MessagePreprocessor(preprocessor_factory)
        self._job_manager = job_manager
        self._batcher = batcher
        self._dispatcher = CommandDispatcher(
            job_manager=job_manager,
            instrument=instrument,
            service_name=service_name,
            registry=registry,
        )
        self._instrument = instrument
        self._service_name = service_name
        self._device_extractor = device_extractor
        self._stream_counter = stream_counter
        self._clock = clock
        self._heartbeat_interval_s = heartbeat_interval_s
        self._start_wall = clock()
        self._last_heartbeat = -float("inf")
        self._last_metrics = clock()
        self._last_batch_len = 0
        self._finalized = False
        self.last_lag_report = StreamLagReport()
        self._lag_report_wall_ns = time.time_ns()
        from ..utils.profiling import StageTimer

        self.stage_timer = StageTimer()
        # Pipelined ingest (ADR 0111): decode | prestage | step/publish
        # overlap across successive windows instead of summing on this
        # thread. The link policy produced on the step worker is applied
        # to the batcher HERE on the service thread (batcher mutable
        # state is single-thread-owned by contract).
        self._pipeline = None
        self._link_monitor = None
        #: Result fan-out tier (serving/plane.py, ADR 0117), duck-typed:
        #: ``publish_results(results, timestamp)`` mirrors the sink
        #: publish and ``qos()`` feeds the link monitor's demand axis.
        #: None = no serving plane (classic deployments, tests).
        self._result_fanout = result_fanout
        self._last_fanout_qos = -float("inf")
        #: Durability plane (durability/checkpoint.py, ADR 0118):
        #: periodic state + offset checkpoints taken HERE, on the
        #: service thread, only at quiescent window boundaries (no
        #: partial window in the batcher, no in-flight pipeline
        #: window) — the one point where "every delivered offset is in
        #: job state" holds, which is what makes restore + replay
        #: exactly-once instead of double-counting.
        self._durability = durability
        if durability is not None:
            set_durability = getattr(job_manager, "set_durability", None)
            if set_durability is not None:
                set_durability(durability)
        if result_fanout is not None:
            # Removed jobs drop their cached streams: without this the
            # plane would list a dead job in /results and pin a ring of
            # its full frames forever under job churn.
            drop_job = getattr(result_fanout, "drop_job", None)
            set_retire = getattr(job_manager, "set_retire_observer", None)
            if drop_job is not None and set_retire is not None:
                set_retire(drop_job)
        # Step-worker -> service-thread policy mailbox (graftlint JGL012:
        # the step worker posts, the service thread swaps-and-applies;
        # unlocked, the swap's read..None-store window can eat a
        # concurrently posted policy and leave the batcher one decision
        # stale until the next window completes).
        self._policy_lock = threading.Lock()
        self._pending_policy = None
        self._applied_window_scale = 1.0
        self._applied_publish_coalesce = 1
        self._base_window = getattr(batcher, "window", None)
        if pipelined:
            from .ingest_pipeline import IngestPipeline
            from .link_monitor import LinkMonitor

            # The monitor's neutral depth IS the configured pipeline
            # depth — otherwise a --pipeline-depth below the monitor's
            # default would be silently deepened on healthy links.
            self._link_monitor = link_monitor or LinkMonitor(
                base_depth=pipeline_depth,
                max_depth=max(4, pipeline_depth),
            )
            self._pipeline = IngestPipeline(
                job_manager=job_manager,
                decode=self._decode_window,
                publish=self._publish_results,
                on_complete=self._on_window_complete,
                depth=pipeline_depth,
                flatten_workers=flatten_threads,
                link_monitor=self._link_monitor,
                name=f"{service_name}-ingest",
            )
        elif result_fanout is not None:
            # Serial service with a serving plane (ADR 0117): no
            # pipeline means no bandwidth/RTT observations, but the
            # fan-out demand axis still applies — an unwatched service
            # backs its publish cadence off, and the processor applies
            # the (otherwise neutral) policy itself at heartbeat
            # cadence since no step worker posts one.
            from .link_monitor import LinkMonitor

            self._link_monitor = link_monitor or LinkMonitor()
        if self._durability is not None and self._link_monitor is not None:
            # Cadence governance (ADR 0118): the plane stretches its
            # interval while the link is degraded or the publish tick
            # widened — snapshot fetches must never compete with a
            # congested publish path.
            set_monitor = getattr(
                self._durability, "set_link_monitor", None
            )
            if set_monitor is not None:
                set_monitor(self._link_monitor)
        # Unified telemetry (ADR 0116): one keyed collector per
        # processor feeding the process registry at scrape time — link
        # estimates, pipeline depths/utilization, stream/sink/source
        # counters, stage-once cache totals and HBM gauges all ride it.
        # Keyed by service name so a rebuilt processor (tests, restarts)
        # REPLACES its predecessor instead of stacking dead callbacks.
        from ..telemetry.registry import REGISTRY as _registry

        self._telemetry_key = f"processor:{service_name}"
        _registry.register_collector(
            self._telemetry_key, self._telemetry_families
        )

    # -- cycle ------------------------------------------------------------
    def process(self) -> None:
        messages = list(self._source.get_messages())

        commands = [m for m in messages if m.stream.kind.is_command]
        run_control = [m for m in messages if m.stream.kind.is_run_control]
        data = [m for m in messages if m.stream.kind.is_data]

        if commands:
            acks = self._dispatcher.process_messages(commands)
            self._publish_acks(acks)
        for msg in run_control:
            if isinstance(msg.value, (RunStart, RunStop)):
                self._job_manager.handle_run_transition(msg.value)

        batch = self._batcher.batch(data)
        if batch is not None:
            t0 = self._clock()
            if self._pipeline is not None:
                self._submit_batch(batch)
            else:
                self._process_batch(batch)
            # Pipelined: the duration is decode+submit, where submit
            # blocks while the pipeline is at depth — backpressure from
            # a slow stage reaches the adaptive batcher as load through
            # the exact same channel serial processing time does.
            self._batcher.report_processing_time(
                Duration.from_s(self._clock() - t0)
            )
        elif self._job_manager.has_finishing_jobs():
            # A stop must complete even when no beam data flows: run an
            # empty window so finishing jobs flush any pending
            # accumulation and leave the active set (otherwise a job
            # stopped during a beam-off period stays 'finishing'
            # forever and its delisting heartbeat never happens).
            if self._pipeline is not None:
                # Through the pipeline, so the flush cannot overtake an
                # in-flight window and publishes stay ordered. end=None
                # keeps the serial semantics: no data time advances, and
                # the publish (if any) stamps wall time at publish.
                self._pipeline.submit(None)
            else:
                results = self._job_manager.process_jobs({})
                if results:
                    self._publish_results(results, Timestamp.now())
        if self._pipeline is not None:
            self._apply_link_policy()

        now = self._clock()
        if (
            self._result_fanout is not None
            and self._link_monitor is not None
            and now - self._last_fanout_qos >= self._heartbeat_interval_s
        ):
            # Demand axis (ADR 0117): subscriber count + worst queue
            # pressure from the broadcast plane, at heartbeat cadence —
            # a hub-lock probe, far off the per-window hot path.
            self._last_fanout_qos = now
            try:
                qos = self._result_fanout.qos()
                self._link_monitor.observe_fanout(
                    int(qos["subscribers"]), float(qos["queue_pressure"])
                )
            except Exception:
                logger.debug("fan-out qos probe failed", exc_info=True)
            if self._pipeline is None:
                # Serial mode has no step worker posting policies:
                # apply the (fanout-only) decision here.
                with self._policy_lock:
                    self._pending_policy = self._link_monitor.policy()
                self._apply_link_policy()
        if now - self._last_heartbeat >= self._heartbeat_interval_s:
            self._last_heartbeat = now
            self._publish_status()
        if now - self._last_metrics >= METRICS_INTERVAL_S:
            self._last_metrics = now
            self._log_metrics()
        if self._durability is not None:
            self._maybe_checkpoint()

    # -- durability plane (durability/, ADR 0118) --------------------------
    # graft: protocol=replay (ADR 0124: the quiescent gate below is the
    # modeled guard of the exactly-once bookmark arithmetic)
    def _quiescent(self) -> bool:
        """True when every delivered message is in job state: no
        partial window buffered in the batcher, no window in flight in
        the pipeline. Checkpoints only happen here — a bookmark taken
        mid-window would either lose the buffered tail (too new) or
        replay data the dumped states already contain (too old)."""
        # A batcher that does NOT expose the probe is treated as
        # never-quiescent (no checkpoint, no bookmark): a custom
        # batcher with invisible buffering must not get bookmarks that
        # silently skip its buffered tail on restore.
        pending = getattr(self._batcher, "pending_messages", None)
        if pending is None or pending:
            return False
        if self._pipeline is not None:
            try:
                if self._pipeline.telemetry()["inflight"]:
                    return False
            except Exception:  # pragma: no cover - defensive
                return False
        return True

    def _bookmarks(self) -> dict[str, int]:
        """Per-topic next-consume offsets of everything handed to this
        processor, from the raw transport (duck-typed ``positions``;
        in-memory fakes simply have none — the manifest then carries no
        bookmarks and a restart pins to the high watermark, exactly the
        pre-durability behavior)."""
        transport = _transport_of(self._source)
        positions = getattr(transport, "positions", None)
        if positions is None:
            return {}
        try:
            return dict(positions())
        except Exception:  # pragma: no cover - defensive
            logger.debug("bookmark probe failed", exc_info=True)
            return {}

    def _maybe_checkpoint(self, *, force: bool = False) -> None:
        """Take one checkpoint when due AND quiescent (deferred
        otherwise — the next quiescent cycle retries; replay covers
        whatever the deferral leaves out)."""
        plane = self._durability
        try:
            if not force and not plane.due():
                return
            if not self._quiescent():
                return
            entries = self._job_manager.checkpoint_snapshot()
            if not entries:
                return
            plane.checkpoint(
                entries,
                offsets=self._bookmarks(),
                reset_seq=getattr(self._job_manager, "reset_seq", 0),
            )
        except Exception:
            logger.exception("checkpoint failed; will retry next cycle")

    # -- pipelined ingest (ADR 0111) ---------------------------------------
    @property
    def stop_grace_s(self) -> float:
        """How long a stop should wait for finalize (core/service.py
        reads this): pipelined processors drain in-flight windows
        before the stopped statuses go out — worst case the pipeline's
        30 s drain timeout plus three 5 s worker joins, with headroom
        for the status publish."""
        return 50.0 if self._pipeline is not None else 5.0

    def _submit_batch(self, batch) -> None:
        """Hand one closed batch to the pipeline; blocks at depth."""
        self._last_batch_len = len(batch.messages)
        self._record_lag(batch)
        self._pipeline.submit(
            batch,
            start=batch.start,
            end=batch.end,
            oldest_ts_ns=_oldest_ts_ns(batch),
        )

    # graft: thread=decode   (IngestPipeline decode worker callback)
    def _decode_window(self, batch):
        """Decode stage (pipeline decode worker): accumulate + collect,
        then detach the window so the NEXT batch's preprocess — on this
        same worker — reuses the accumulators' buffers while the
        detached window travels on. Staged events copy their arrays
        (``StagedEvents.detach``); DataArray values copy too, because
        some accumulators hand out live views into growable buffers
        (``ToNXlog.get`` sorts its prefix in place on the next collect —
        a window still in flight must not see that mutation)."""
        self._preprocessor.preprocess(batch.messages)
        window = self._preprocessor.collect_window()
        context = self._preprocessor.collect_context()
        fresh_context = self._preprocessor.fresh_context_names()
        from ..preprocessors.event_data import StagedEvents

        def detach(value):
            if isinstance(value, StagedEvents):
                return value.detach()
            copy = getattr(value, "copy", None)
            return copy() if callable(copy) else value

        data = {name: detach(value) for name, value in window.items()}
        context = {name: detach(value) for name, value in context.items()}
        self._preprocessor.release()
        return data, context, fresh_context

    # graft: thread=step   (IngestPipeline step-worker completion callback)
    def _on_window_complete(self, window) -> None:
        """Step-worker callback: fold the window's stage timings into
        the metrics timer and queue the link policy for the service
        thread (batcher state is single-thread-owned by contract, so it
        is never touched from here)."""
        for stage, seconds in window.stage_s.items():
            self.stage_timer.record(stage, seconds)
        if window.policy is not None:
            with self._policy_lock:
                self._pending_policy = window.policy

    def _apply_link_policy(self) -> None:
        """Service thread: retarget the batcher window per link policy.

        Only batchers exposing ``set_window`` (rate-aware) retarget
        explicitly; the adaptive batcher already reacts to the same
        degradation through ``report_processing_time`` backpressure."""
        with self._policy_lock:
            policy, self._pending_policy = self._pending_policy, None
        if policy is None:
            return
        # Publish-coalescing width (ADR 0113): idempotent retarget on
        # the JobManager — applied independently of the batcher axis so
        # a fixed-window batcher still gets the RTT adaptation.
        coalesce = getattr(policy, "publish_coalesce", 1)
        if coalesce != self._applied_publish_coalesce:
            set_coalesce = getattr(
                self._job_manager, "set_publish_coalesce", None
            )
            if set_coalesce is not None:
                set_coalesce(coalesce)
                self._applied_publish_coalesce = coalesce
                logger.info("link policy: publish_coalesce=%d", coalesce)
        if self._base_window is None:
            return
        if policy.window_scale == self._applied_window_scale:
            return
        set_window = getattr(self._batcher, "set_window", None)
        if set_window is None:
            return
        set_window(
            Duration(max(1, round(self._base_window.ns * policy.window_scale)))
        )
        self._applied_window_scale = policy.window_scale
        logger.info(
            "link policy: window_scale=%.2f compact_wire=%s depth=%d "
            "publish_coalesce=%d",
            policy.window_scale,
            policy.compact_wire,
            policy.depth,
            coalesce,
        )

    def _process_batch(self, batch) -> None:
        self._last_batch_len = len(batch.messages)
        # Serial-path tracing (ADR 0116): the trace id is born at
        # decode, exactly like the pipelined decode worker's, so the
        # span names line up across both ingest modes (no prestage
        # span here — the serial loop stages at step time).
        trace_id = TRACER.new_trace()
        # The e2e anchor (ADR 0120): the window-end data time, same
        # birth point as PipelineWindow.source_ts_ns ("staged" is
        # pipelined-only — this loop stages at step time).
        source_ts_ns = (
            int(batch.end.ns) if hasattr(batch.end, "ns") else None
        )
        # Decode is batch-granular (ADR 0125): one observation per
        # window, anchored at the OLDEST member so the histogram upper-
        # bounds any single message's decode latency (same rule as the
        # pipelined decode worker).
        oldest_ts_ns = _oldest_ts_ns(batch)
        decode_ts_ns = (
            oldest_ts_ns if oldest_ts_ns is not None else source_ts_ns
        )
        t_start = time.monotonic()
        with self.stage_timer.stage("preprocess"), TRACER.span(
            "decode", trace_id
        ):
            self._preprocessor.preprocess(batch.messages)
            window = self._preprocessor.collect_window()
            context = self._preprocessor.collect_context()
            fresh_context = self._preprocessor.fresh_context_names()
        observe_stage("decode", decode_ts_ns)
        self._record_lag(batch)
        with self.stage_timer.stage("process_jobs"), TRACER.bind(trace_id):
            results = self._job_manager.process_jobs(
                window,
                context=context,
                fresh_context=fresh_context,
                start=batch.start,
                end=batch.end,
            )
        try:
            with self.stage_timer.stage("publish"), TRACER.span(
                "sink", trace_id
            ):
                self._publish_results(results, batch.end)
            if results:
                # "published" means results actually left: a window
                # with no due jobs records nothing.
                observe_stage("published", source_ts_ns)
        finally:
            self._preprocessor.release()
            TRACER.finish_tick(trace_id, time.monotonic() - t_start)

    def _record_lag(self, batch) -> None:
        now_ns = time.time_ns()
        lags = [
            StreamLag(
                stream_name=name,
                lag_s=(now_ns - batch.end.ns) / 1e9,
            )
            for name in {m.stream.name for m in batch.messages}
        ]
        self.last_lag_report = StreamLagReport(lags=lags)
        self._lag_report_wall_ns = now_ns

    def _current_lag_report(self) -> StreamLagReport:
        """The last report AGED to now: a stream that stopped producing
        has its staleness grow with the silence (a frozen snapshot would
        report 'ok' forever on a fully stalled stream — the worst case),
        and a future-timestamped error relaxes as the wall clock catches
        up with the data."""
        if not self.last_lag_report.lags:
            return self.last_lag_report
        age_s = (time.time_ns() - self._lag_report_wall_ns) / 1e9
        return StreamLagReport(
            lags=[
                StreamLag(
                    stream_name=lag.stream_name,
                    lag_s=lag.lag_s + age_s,
                    min_s=(
                        None if lag.min_s is None else lag.min_s + age_s
                    ),
                    max_s=(
                        None if lag.max_s is None else lag.max_s + age_s
                    ),
                    count=lag.count,
                )
                for lag in self.last_lag_report.lags
            ]
        )

    # -- publishing -------------------------------------------------------
    # Pipelined mode publishes from the step worker; serial mode calls
    # this from the service thread — both roles reach it.
    # graft: thread=step
    def _publish_results(
        self, results: list[JobResult], timestamp: Timestamp | None
    ) -> None:
        if timestamp is None:
            # Empty-window flushes carry no data time (pipelined path).
            timestamp = Timestamp.now()
        messages: list[Message] = []
        for result in results:
            for key, da in zip(result.keys(), result.outputs.values(), strict=True):
                messages.append(
                    Message(
                        timestamp=timestamp,
                        stream=StreamId(
                            kind=StreamKind.LIVEDATA_DATA, name=key.to_string()
                        ),
                        value=da,
                    )
                )
        if self._device_extractor is not None:
            # Contracted outputs additionally ride the stable-identity NICOS
            # device stream (ADR 0006, core/nicos_devices.py).
            messages.extend(self._device_extractor.extract(results))
        if messages:
            self._sink.publish_messages(messages)
        if results and self._result_fanout is not None:
            # Result fan-out tier (ADR 0117): the broadcast plane gets
            # the same finalized results the sink just published —
            # bounded host work (one delta encode per output, one
            # bounded enqueue per subscriber), contained so a fan-out
            # failure can never take the publish path down.
            try:
                self._result_fanout.publish_results(results, timestamp)
            except Exception:
                logger.exception("result fan-out failed")

    def _publish_acks(self, acks: list[CommandAcknowledgement]) -> None:
        if not acks:
            return
        self._sink.publish_messages(
            [
                Message(
                    timestamp=Timestamp.now(),
                    stream=RESPONSE_STREAM,
                    value=ack,
                )
                for ack in acks
            ]
        )

    def _service_status(self, state: str = "running") -> ServiceStatus:
        return ServiceStatus(
            service_name=self._service_name,
            instrument=self._instrument,
            state=state,
            jobs=self._job_manager.job_statuses(),
            last_batch_message_count=self._last_batch_len,
            stream_message_counts=self._preprocessor.snapshot_counts(),
            uptime_s=self._clock() - self._start_wall,
            lag_level=(report := self._current_lag_report()).worst_level,
            # The badge number must describe the lag that SET the level,
            # not an unrelated healthy stream's.
            worst_lag_s=max(
                (
                    abs(lag.lag_s)
                    for lag in report.lags
                    if lag.level != "ok"
                ),
                default=0.0,
            ),
            stream_lags={
                lag.stream_name: (round(lag.lag_s, 3), lag.level)
                for lag in report.lags
            },
            # Duck-typed: Kafka-backed transports expose circuit-breaker
            # health + counters; in-memory fakes simply don't. The
            # transport sits under decorator layers (AdaptingMessageSource,
            # synthesizers), so walk the chain to the innermost source.
            source_health=(
                h.value
                if (t := _transport_of(self._source)) is not None
                and hasattr(h := t.health, "value")
                else "ok"
            ),
            source_metrics=dict(
                t.metrics if t is not None else {}
            ),
        )

    def _publish_status(self, state: str = "running") -> None:
        status = self._service_status(state)
        now = Timestamp.now()
        # One service heartbeat plus one per-job heartbeat: NICOS monitors
        # individual jobs by their source:job_number identity while the
        # dashboard consumes the aggregated service document. On shutdown
        # the per-job heartbeats must report STOPPED — a NICOS cache keyed
        # on the job identity would otherwise latch the last live code
        # (green) for jobs of a dead service.
        jobs = status.jobs
        if state in ("stopping", "stopped"):
            from .job import JobState

            jobs = [
                job.model_copy(update={"state": JobState.STOPPED})
                for job in jobs
            ]
        self._sink.publish_messages(
            [Message(timestamp=now, stream=STATUS_STREAM, value=status)]
            + [
                Message(timestamp=now, stream=STATUS_STREAM, value=job)
                for job in jobs
            ]
        )

    def _telemetry_families(self) -> list:
        """Scrape-time collector (ADR 0116): every per-service metric
        surface this processor owns, rendered as labeled families. The
        hot path pays nothing here — each producer keeps its own
        thread-safe counters and this only snapshots them when
        ``/metrics`` is pulled (or bench embeds the registry)."""
        from ..telemetry.registry import MetricFamily, Sample

        svc = (("service", self._service_name),)

        def family(name, kind, help, rows):
            fam = MetricFamily(name, kind, help)
            suffix = "_total" if kind == "counter" else ""
            fam.samples = [
                Sample(suffix, svc + tuple(labels), float(value))
                for labels, value in rows
            ]
            return fam

        families = [
            family(
                "livedata_stream_messages",
                "counter",
                "Messages mapped per (topic, source) by the adapter layer",
                [
                    ((("topic", t), ("source", s)), n)
                    for (t, s), n in sorted(
                        self._stream_counter.cumulative_counts().items()
                    )
                ]
                if self._stream_counter is not None
                else [],
            ),
            family(
                "livedata_preprocessed_messages",
                "counter",
                "Messages accumulated per stream by the preprocessor",
                [
                    ((("stream", name),), n)
                    for name, n in sorted(
                        self._preprocessor.snapshot_counts().items()
                    )
                ],
            ),
            family(
                "livedata_jobs",
                "gauge",
                "Jobs this service hosts",
                [((), self._job_manager.n_jobs)],
            ),
            family(
                "livedata_processor_stage_seconds",
                "counter",
                "Cumulative wall seconds per processor stage",
                [
                    ((("stage", stage),), entry["total_s"])
                    for stage, entry in sorted(
                        self.stage_timer.cumulative().items()
                    )
                ],
            ),
        ]
        cache_stats = getattr(
            self._job_manager, "event_cache_cumulative_stats", None
        )
        if cache_stats is not None:
            families.append(
                family(
                    "livedata_event_cache_events",
                    "counter",
                    "Stage-once cache totals (ADR 0110): misses ~= one "
                    "per (stream, window) regardless of job count; "
                    "bytes_staged is the actual wire traffic",
                    [
                        ((("kind", kind),), value)
                        for kind, value in sorted(cache_stats().items())
                    ],
                )
            )
        if self._link_monitor is not None:
            link = self._link_monitor.stats()
            families.append(
                family(
                    "livedata_link_bandwidth_bps",
                    "gauge",
                    "EWMA effective staging bandwidth (ADR 0111)",
                    [((), link["bandwidth_bps"] or 0.0)],
                )
            )
            rtt_rows = [((("slice", "all"),), link["rtt_s"] or 0.0)]
            rtt_rows += [
                ((("slice", str(slice_key)),), rtt)
                for slice_key, rtt in sorted(link["rtt_by_slice"].items())
            ]
            families.append(
                family(
                    "livedata_link_rtt_ewma_seconds",
                    "gauge",
                    "EWMA publish RTT, per mesh slice (ADR 0115); the "
                    "policy reacts to the worst slice",
                    rtt_rows,
                )
            )
            families.append(
                family(
                    "livedata_link_policy",
                    "gauge",
                    "Latched link-adaptation decision (ADR 0111): "
                    "window_scale / depth / publish_coalesce / degraded "
                    "(0|1) / compact_wire (0|1, -1 = construction default)",
                    [
                        ((("axis", "window_scale"),), link["window_scale"]),
                        ((("axis", "depth"),), link["depth"]),
                        (
                            (("axis", "publish_coalesce"),),
                            link["publish_coalesce"],
                        ),
                        ((("axis", "degraded"),), int(link["degraded"])),
                        (
                            (("axis", "compact_wire"),),
                            -1
                            if link["compact_wire"] is None
                            else int(link["compact_wire"]),
                        ),
                        (
                            (("axis", "fanout_coalesce"),),
                            link.get("fanout_coalesce", 1),
                        ),
                        (
                            # -1 = no serving plane has reported (axis
                            # neutral), else the attached-viewer count.
                            (("axis", "fanout_subscribers"),),
                            -1
                            if link.get("fanout_subscribers") is None
                            else link["fanout_subscribers"],
                        ),
                    ],
                )
            )
        if self._pipeline is not None:
            pipe = self._pipeline.telemetry()
            families.append(
                family(
                    "livedata_pipeline_queue_depth",
                    "gauge",
                    "Windows queued per pipeline stage (ADR 0111)",
                    [
                        ((("stage", stage),), depth)
                        for stage, depth in sorted(pipe["queues"].items())
                    ],
                )
            )
            families.append(
                family(
                    "livedata_pipeline_inflight",
                    "gauge",
                    "In-flight windows vs the link-adaptive depth bound",
                    [
                        ((("kind", "inflight"),), pipe["inflight"]),
                        ((("kind", "depth"),), pipe["depth"]),
                    ],
                )
            )
            families.append(
                family(
                    "livedata_pipeline_windows",
                    "counter",
                    "Windows completed/published through the pipeline",
                    [
                        ((("kind", "completed"),), pipe["completed"]),
                        ((("kind", "published"),), pipe["published"]),
                    ],
                )
            )
            families.append(
                family(
                    "livedata_pipeline_stage_busy_seconds",
                    "counter",
                    "Cumulative busy seconds per pipeline stage "
                    "(utilization = rate of this over wall time; the "
                    "sum across stages exceeding 1 is the overlap the "
                    "serial loop forfeits)",
                    [
                        ((("stage", stage),), entry["total_s"])
                        for stage, entry in sorted(pipe["stages"].items())
                    ],
                )
            )
        sink_metrics = getattr(self._sink, "metrics", None)
        if callable(sink_metrics):
            try:
                rows = sorted(sink_metrics().items())
            except Exception:
                rows = []
            families.append(
                family(
                    "livedata_kafka_sink_events",
                    "counter",
                    "Sink drop/error counters incl. the per-path "
                    "consecutive-failure streaks behind the breaker",
                    [((("kind", kind),), value) for kind, value in rows],
                )
            )
        transport = _transport_of(self._source)
        if transport is not None:
            families.append(
                family(
                    "livedata_kafka_source_events",
                    "counter",
                    "Raw transport counters (consumed/queued/dropped)",
                    [
                        ((("kind", kind),), value)
                        for kind, value in sorted(transport.metrics.items())
                    ],
                )
            )
            health = transport.health
            families.append(
                family(
                    "livedata_source_up",
                    "gauge",
                    "1 = consume transport healthy, 0 = stale/breaker open",
                    [
                        (
                            (),
                            int(
                                getattr(health, "value", health) == "ok"
                            ),
                        )
                    ],
                )
            )
        hbm = MetricFamily(
            "livedata_hbm_bytes",
            "gauge",
            "Per-device HBM statistics (bytes_in_use / peak_bytes_in_use "
            "/ bytes_limit); empty on backends without memory_stats",
        )
        try:
            from ..utils.profiling import device_memory_stats

            # Service-labeled like every family here: two processors in
            # one process must emit DISTINCT samples, not byte-identical
            # duplicate lines (which real scrapers reject).
            hbm.samples = [
                Sample(
                    "",
                    svc
                    + (
                        ("device", key.partition(":")[0]),
                        ("kind", key.partition(":")[2]),
                    ),
                    float(value),
                )
                for key, value in sorted(device_memory_stats().items())
            ]
        except Exception:  # pragma: no cover - backend without stats
            logger.debug("device_memory_stats unavailable", exc_info=True)
        families.append(hbm)
        return families

    def _log_metrics(self) -> None:
        extra = {
            "service": self._service_name,
            "jobs": self._job_manager.n_jobs,
            "stream_counts": self._preprocessor.snapshot_counts(),
            "lag_level": self._current_lag_report().worst_level,
        }
        # Stage-once cache counters (ADR 0110). The engagement signal is
        # misses ~= one per (stream, window) INDEPENDENT of job count —
        # not hit_rate: a fused group touches the cache exactly once, so
        # hit_rate legitimately reads 0 when sharing works best (hits
        # only appear when jobs stage privately against a warm slot).
        # bytes_staged over the interval is the actual wire traffic.
        cache_stats = getattr(self._job_manager, "event_cache_stats", None)
        if cache_stats is not None:
            extra["event_cache"] = cache_stats()
        try:
            from ..utils.profiling import device_memory_stats

            if memory := device_memory_stats():
                extra["device_memory"] = memory
        except Exception:  # pragma: no cover - backend without stats
            # Memory stats are best-effort, but a permanently failing
            # backend query should at least be visible at debug level
            # (graftlint JGL007: no silent swallows in the service loop).
            logger.debug("device_memory_stats unavailable", exc_info=True)
        if self._stream_counter is not None:
            # Adapter-layer per-(topic,source) counts + producer lag,
            # accumulated since the last rollover (kafka/stream_counter.py).
            stats = self._stream_counter.drain(METRICS_INTERVAL_S)
            extra["input_counts"] = {
                f"{s.topic}/{s.source_name}": s.count for s in stats.streams
            }
            extra["unmapped"] = [s.source_name for s in stats.unmapped]
            lag_report = self._stream_counter.drain_lag()
            if lag_report is not None:
                self.last_lag_report = lag_report
                extra["producer_lag_level"] = lag_report.worst_level
        if stages := self.stage_timer.drain():
            extra["stages"] = stages
        if self._pipeline is not None:
            extra["pipeline"] = self._pipeline.stats()
        if self._link_monitor is not None:
            extra["link"] = self._link_monitor.stats()
        # Device dispatch decomposition (ADR 0113/0114): publish/tick
        # executes+fetches and separate step dispatches since process
        # start. SNAPSHOT, not drain — the counters are process-wide and
        # the bench/tests drain them around their own measured loops; a
        # metrics tick must never zero a loop someone else is timing.
        try:
            from ..ops.publish import METRICS as publish_metrics

            extra["publish"] = publish_metrics.snapshot()
        except Exception:  # pragma: no cover - defensive
            logger.debug("publish metrics unavailable", exc_info=True)
        logger.info("processor_metrics", extra=extra)

    def finalize(self) -> None:
        """Publish final stopped statuses; idempotent (reference :417)."""
        if self._finalized:
            return
        self._finalized = True
        if self._pipeline is not None:
            # Drain first: every accepted window flushes through step and
            # publish before the stopped statuses go out — a service stop
            # must not drop or reorder in-flight batches.
            try:
                self._pipeline.stop(drain=True)
            except Exception:
                logger.exception("Ingest pipeline drain failed")
        try:
            self._publish_status(state="stopped")
        except Exception:
            logger.exception("Failed to publish final status")
        if self._durability is not None:
            # Final checkpoint on graceful stop (the pipeline just
            # drained): the restart resumes from HERE, replaying only
            # what arrived after the stop. Quiescence still gates it —
            # a batcher holding a partial window defers to the last
            # periodic generation, whose bookmark replays that window.
            self._maybe_checkpoint(force=True)
        self._job_manager.shutdown()
        # Drop this processor's scrape collector: the registry is
        # process-wide and a finalized processor must not keep feeding
        # stale families (or pin the whole object graph) forever.
        # Identity-guarded: if a rebuilt processor already REPLACED the
        # key, this late shutdown must not delete the successor's live
        # collector.
        from ..telemetry.registry import REGISTRY as _registry

        _registry.unregister_collector(
            self._telemetry_key, self._telemetry_families
        )

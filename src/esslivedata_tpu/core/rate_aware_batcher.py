"""Rate-aware batcher: per-stream pulse-slot completion instead of fixed windows.

Parity with reference ``core/rate_aware_batcher.py``: a batch closes when every
*gated* stream (detector/monitor/area kinds, reference :22-29) has seen a
message in the last pulse slot its estimated integer-Hz rate predicts for the
window — not when a fixed time has elapsed. A wall-of-data-time timeout
(high-water mark 1.2x the window past the batch start) closes batches when
gating streams stall, and extensive defensive bounds protect against insane
timestamps (reference :56-95): high-water-mark clamping, origin plausibility
checks, and future-message hold-back caps.

Behavioral contract reproduced from the reference's test scenarios:

- Rate estimation (``PeriodEstimator``) is median-of-diffs seeded, with each
  diff snapped to its nearest integer multiple of the seed and divided back,
  robust to missed pulses / split messages / jitter; the final rate snaps to
  integer Hz within max(10% relative, 0.1 Hz absolute) tolerance.
- A stream whose rate is below one pulse per window never gates (delivered
  opportunistically).
- Streams absent for 5 consecutive batches are evicted.
- Messages past the window's last slot overflow; if *only* overflow exists
  the window is lagging live traffic and jumps forward (gap recovery) instead
  of emitting a long run of empty windows.
"""

from __future__ import annotations

import statistics
from collections import defaultdict, deque
from dataclasses import dataclass, field

from .message import Message, StreamId, StreamKind
from .message_batcher import LoadGovernor, MessageBatch
from .timestamp import Duration, Timestamp

__all__ = ["PeriodEstimator", "RateAwareMessageBatcher", "SlotGrid"]

GATED_KINDS = frozenset(
    {
        StreamKind.DETECTOR_EVENTS,
        StreamKind.MONITOR_EVENTS,
        StreamKind.MONITOR_COUNTS,
        StreamKind.AREA_DETECTOR,
    }
)

#: Positive inter-arrival diffs needed before a rate estimate is trusted.
MIN_DIFFS = 4
#: Ring-buffer length of retained diffs.
DIFF_BUFFER = 32
#: Batches a stream may be silent before its state is dropped.
EVICT_AFTER_ABSENT = 5
#: Integer-Hz snap tolerance: relative and absolute-floor. Tight on
#: purpose — a genuinely non-integer rate (e.g. 14.5 Hz) must be REJECTED
#: rather than snapped, because a grid built on the wrong integer rate
#: drifts phase within a batch and turns every close into a timeout.
#: Jittered-but-integer rates land well inside 1% after the median.
_SNAP_REL = 0.01
_SNAP_ABS_HZ = 0.02
#: Allowed integer-Hz rounding drift when mapping timestamps to slots (ns).
_DRIFT_NS = 1_000_000
#: A grid origin further than this many windows from the batch start means the
#: stream's timestamps live in a disjoint epoch — drop the grid, don't gate.
_MAX_ORIGIN_OFFSET_WINDOWS = 1000
#: High-water mark may sit at most this many windows past the active start;
#: bounds the cascade of timeout-closed empty batches after one insane
#: far-future timestamp, and the same cap holds back plausible near-future
#: messages for later windows.
_MAX_HWM_WINDOWS = 3


class PeriodEstimator:
    """Infers a stream's pulse period from message inter-arrival times."""

    __slots__ = ("_diffs", "last_ns")

    def __init__(self) -> None:
        self._diffs: deque[int] = deque(maxlen=DIFF_BUFFER)
        self.last_ns: int | None = None

    def observe(self, ts_ns: int) -> None:
        if self.last_ns is not None and ts_ns > self.last_ns:
            self._diffs.append(ts_ns - self.last_ns)
        if self.last_ns is None or ts_ns > self.last_ns:
            self.last_ns = ts_ns

    @property
    def integer_rate_hz(self) -> int | None:
        """Estimated rate snapped to integer Hz, or None if unconverged."""
        if len(self._diffs) < MIN_DIFFS:
            return None
        seed = statistics.median(self._diffs)
        # Snap each diff to its nearest integer multiple of the seed: a diff
        # spanning k missed pulses contributes diff/k, an unbiased per-pulse
        # sample, instead of acting as an outlier.
        per_pulse = [d / k for d in self._diffs if (k := round(d / seed)) >= 1]
        period_ns = statistics.median(per_pulse) if per_pulse else seed
        raw_hz = 1e9 / period_ns
        rate = round(raw_hz)
        if rate < 1:
            return None
        if abs(raw_hz - rate) > max(_SNAP_REL * rate, _SNAP_ABS_HZ):
            return None
        return rate


@dataclass(frozen=True, slots=True)
class SlotGrid:
    """Fixed per-stream temporal grid mapping timestamps to pulse slots."""

    origin_ns: int
    period_ns: int
    slots_per_batch: int

    def slot(self, ts: Timestamp, window_start: Timestamp) -> int:
        """Slot of ``ts`` relative to the window's first expected pulse.

        The first pulse of a window is found by ceiling division with a small
        tolerance for integer-Hz rounding drift (a few ns/batch); a wide
        tolerance would misclassify genuine phase offsets (reference :162-183).
        """
        index = round((ts.ns - self.origin_ns) / self.period_ns)
        delta = window_start.ns - self.origin_ns
        base, rem = divmod(delta, self.period_ns)
        if rem > min(_DRIFT_NS, self.period_ns // 2):
            base += 1
        return index - base


@dataclass(slots=True)
class _StreamState:
    """Per-gated-stream estimator, grid, and per-window bucket."""

    estimator: PeriodEstimator = field(default_factory=PeriodEstimator)
    grid: SlotGrid | None = None
    absent: int = 0
    bucket: list[Message] = field(default_factory=list)
    max_slot: int = -1

    @property
    def is_gating(self) -> bool:
        return self.grid is not None

    def route(self, msg: Message, window_start: Timestamp) -> Message | None:
        """Bucket the message, or return it if it lies past the last slot.

        Overflow still bumps ``max_slot`` to the final slot so the gate
        observes that the window's last pulse was reached.
        """
        self.estimator.observe(msg.timestamp.ns)
        if self.grid is None:
            self.bucket.append(msg)
            return None
        slot = self.grid.slot(msg.timestamp, window_start)
        if slot >= self.grid.slots_per_batch:
            self.max_slot = max(self.max_slot, self.grid.slots_per_batch - 1)
            return msg
        self.bucket.append(msg)
        self.max_slot = max(self.max_slot, slot)
        return None

    def gate_satisfied(self) -> bool:
        if self.grid is None:
            return True
        return self.max_slot >= self.grid.slots_per_batch - 1

    def drain(self) -> list[Message]:
        out, self.bucket = self.bucket, []
        self.max_slot = -1
        return out

    def refresh_grid(self, window_start: Timestamp, window: Duration) -> None:
        """(Re)build the grid from the estimator; drop it for sub-rate or
        disjoint-epoch streams (they revert to opportunistic delivery)."""
        rate = self.estimator.integer_rate_hz
        if rate is None:
            return
        slots = round(rate * window.seconds)
        if slots < 1:
            self.grid = None
            return
        origin = self._origin_near(window_start, window)
        if origin is None:
            self.grid = None
            return
        self.grid = SlotGrid(
            origin_ns=origin,
            period_ns=round(1e9 / rate),
            slots_per_batch=slots,
        )

    def _origin_near(self, window_start: Timestamp, window: Duration) -> int | None:
        limit = _MAX_ORIGIN_OFFSET_WINDOWS * window.ns

        def plausible(ns: int) -> bool:
            return abs(ns - window_start.ns) <= limit

        if self.grid is not None and plausible(self.grid.origin_ns):
            return self.grid.origin_ns
        for m in self.bucket:
            if m.timestamp >= window_start:
                return m.timestamp.ns if plausible(m.timestamp.ns) else None
        if self.bucket:
            ns = self.bucket[0].timestamp.ns
            return ns if plausible(ns) else None
        last = self.estimator.last_ns
        return last if last is not None and plausible(last) else None


class RateAwareMessageBatcher:
    """Closes a batch when every gated stream's last expected slot is filled.

    Streams of non-gated kinds flow opportunistically into whatever window is
    active; near-future messages (within ``_MAX_HWM_WINDOWS`` windows past the
    active end) are held back for later windows so batch contents stay bounded
    by the batch's time range.
    """

    def __init__(self, window: Duration = Duration.from_s(1.0), *,
                 timeout_factor: float = 1.2) -> None:
        self._window = window
        self._base_window = window
        self.timeout_factor = timeout_factor
        self._streams: defaultdict[StreamId, _StreamState] = defaultdict(_StreamState)
        self._start: Timestamp | None = None
        self._hwm: Timestamp | None = None
        self._non_gated: list[Message] = []
        self._overflow: list[Message] = []
        self._future: list[Message] = []
        self._pending_window: Duration | None = None
        # Load-adaptive windows share the adaptive batcher's governor:
        # overload doubles the gated window (streams regate to the new
        # slot count at the next refresh), underload shrinks it back.
        # The governor locks its own counters; the rest of this batcher's
        # mutable state is deliberately unlocked — it is owned by the one
        # service worker thread that calls batch()/report_processing_time()
        # (unlike the protocol-level guarantee SimpleMessageBatcher makes).
        self._governor = LoadGovernor()
        self._last_emitted_window: Duration = window

    @property
    def window(self) -> Duration:
        return self._window

    @property
    def pending_messages(self) -> int:
        """Messages buffered toward not-yet-closed windows across every
        internal hold (non-gated flow, overflow, near-future, per-stream
        gated slots) — the durability plane's quiescence probe
        (ADR 0118): a checkpoint bookmark must not claim these as
        processed. Read from the owning service thread (like the rest
        of this batcher's unlocked state)."""
        pending = (
            len(self._non_gated) + len(self._overflow) + len(self._future)
        )
        for state in self._streams.values():
            pending += len(state.bucket)
        return pending

    def set_window(self, window: Duration) -> None:
        """Change the window length; takes effect at the next batch start."""
        self._pending_window = window

    def is_gating(self, stream: StreamId) -> bool:
        state = self._streams.get(stream)
        return state.is_gating if state is not None else False

    @property
    def tracked_streams(self) -> set[StreamId]:
        return set(self._streams)

    def report_processing_time(self, duration: Duration) -> None:
        load = duration.ns / max(self._last_emitted_window.ns, 1)
        if self._governor.observe(load):
            self.set_window(
                Duration(
                    max(1, round(self._base_window.ns * self._governor.scale))
                )
            )

    def batch(self, messages: list[Message]) -> MessageBatch | None:
        if messages:
            self._hwm = self._clamped_hwm(max(m.timestamp for m in messages))
        if self._start is None:
            if not messages:
                return None
            return self._bootstrap(messages)
        for msg in messages:
            self._route(msg)
        if self._window_is_lagging():
            self._jump_past_gap()
        if self._complete():
            return self._close()
        return None

    # -- internals ---------------------------------------------------------

    def _clamped_hwm(self, latest: Timestamp) -> Timestamp:
        """Cap HWM advance at a bounded distance past the active window so a
        single far-future timestamp cannot pin the timeout path; floor at the
        current HWM so it never regresses (reference :56-95)."""
        if self._start is None or self._hwm is None:
            return latest
        ceiling = self._start + self._window * _MAX_HWM_WINDOWS
        return max(self._hwm, min(latest, ceiling))

    def _bootstrap(self, messages: list[Message]) -> MessageBatch:
        """Flush the startup backlog as one batch; open the window after it."""
        lo = min(m.timestamp for m in messages)
        hi = max(m.timestamp for m in messages)
        for msg in messages:
            if msg.stream.kind in GATED_KINDS:
                self._streams[msg.stream].estimator.observe(msg.timestamp.ns)
        self._start = hi
        for state in self._streams.values():
            state.refresh_grid(hi, self._window)
        return MessageBatch(start=lo, end=hi, messages=list(messages))

    def _route(self, msg: Message) -> None:
        assert self._start is not None
        gated = msg.stream.kind in GATED_KINDS
        state = self._streams[msg.stream] if gated else None
        if (state is None or not state.is_gating) and self._is_near_future(msg):
            self._future.append(msg)
            return
        if state is None:
            self._non_gated.append(msg)
            return
        overflow = state.route(msg, self._start)
        if overflow is not None:
            self._overflow.append(overflow)

    def _is_near_future(self, msg: Message) -> bool:
        end = self._start + self._window  # type: ignore[operator]
        if not msg.timestamp > end:
            return False
        return (msg.timestamp - end).ns <= _MAX_HWM_WINDOWS * self._window.ns

    def _complete(self) -> bool:
        assert self._start is not None
        if self._hwm is not None:
            if self._hwm >= self._start + self._window * self.timeout_factor:
                return True
        has_gating = False
        for state in self._streams.values():
            if not state.is_gating:
                continue
            has_gating = True
            if not state.gate_satisfied():
                return False
        return has_gating

    def _window_is_lagging(self) -> bool:
        """Only overflow arrived: every gridded stream's traffic lies past the
        window — it is lagging live data and must jump, not crawl."""
        if not self._overflow:
            return False
        return not any(
            s.is_gating and s.bucket for s in self._streams.values()
        )

    def _jump_past_gap(self) -> None:
        assert self._start is not None
        stashed = self._drain_all()
        pending, self._overflow = self._overflow, []
        future, self._future = self._future, []
        earliest = min(m.timestamp for m in pending)
        steps = max((earliest - self._start).ns // self._window.ns, 0)
        if steps > 0:
            self._start = self._start + Duration.from_ns(steps * self._window.ns)
        for msg in stashed + pending + future:
            self._route(msg)

    def _drain_all(self) -> list[Message]:
        out, self._non_gated = self._non_gated, []
        for state in self._streams.values():
            out.extend(state.drain())
        return out

    def _close(self) -> MessageBatch:
        assert self._start is not None
        start = self._start
        # The closing batch's window length: captured before the stream
        # refresh, which may apply a pending set_window() — that takes
        # effect at the *next* batch start, not on this one.
        closing_window = self._window
        self._refresh_streams(start)
        messages = self._drain_all()
        if any(s.is_gating for s in self._streams.values()):
            end = start + closing_window
        else:
            # Timeout-closed with nothing gating: include all held-back
            # traffic and cover its real time range, mirroring
            # SimpleMessageBatcher semantics (reference :593-610).
            messages += self._future + self._overflow
            self._future, self._overflow = [], []
            end = max(
                (m.timestamp for m in messages), default=start + closing_window
            )
            end = max(end, start + closing_window)
        batch = MessageBatch(start=start, end=end, messages=messages)
        # Load feedback divides by the batch's REAL span: timeout-closed
        # batches can cover several windows of drained traffic, and
        # measuring that work against the nominal window would read ~3x
        # the true load and ratchet the governor to max scale.
        self._last_emitted_window = Duration(max(end.ns - start.ns, 1))
        self._start = end
        # Re-route held-back traffic into the new window; anything still past
        # its last slot lands back in overflow and waits for the next close.
        overflow, self._overflow = self._overflow, []
        future, self._future = self._future, []
        for msg in overflow + future:
            self._route(msg)
        return batch

    def _refresh_streams(self, window_start: Timestamp) -> None:
        for sid in list(self._streams):
            state = self._streams[sid]
            if state.bucket:
                state.absent = 0
                state.refresh_grid(window_start, self._window)
            else:
                state.absent += 1
                if state.absent >= EVICT_AFTER_ABSENT:
                    del self._streams[sid]
        if self._pending_window is not None:
            self._window = self._pending_window
            self._pending_window = None
            for state in self._streams.values():
                state.refresh_grid(window_start, self._window)

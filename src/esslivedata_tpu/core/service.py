"""Process lifecycle for backend services.

Parity with reference ``core/service.py`` (ServiceBase:22, Service:100,
_run_loop:156, setup_arg_parser:194, get_env_defaults:236): a worker thread
polls ``processor.process()`` every ``poll_interval``; SIGTERM/SIGINT stop
cleanly; an uncaught worker error stops the service with a nonzero exit code
so a ``restart: on-failure`` supervisor restarts the process. ``step()``
single-steps the loop deterministically for tests.
"""

from __future__ import annotations

import argparse
import logging
import os
import signal
import threading
import time
from typing import Any

from .processor import Processor

__all__ = ["Service", "ServiceBase", "get_env_defaults", "setup_arg_parser"]

logger = logging.getLogger(__name__)

# GC pinning is interpreter-global state: with several Service loops in
# one process (tests, combined deployments) the collector must stay
# disabled until the LAST pinned loop exits, and be restored only if it
# was enabled when the FIRST loop pinned it.
_gc_pin_lock = threading.Lock()
_gc_pin_count = 0
_gc_was_enabled = False


def _gc_pin() -> bool:
    """Pin the cycle collector off (process-wide refcount). Returns True
    iff the caller must balance with ``_gc_unpin``."""
    import gc

    global _gc_pin_count, _gc_was_enabled
    with _gc_pin_lock:
        _gc_pin_count += 1
        if _gc_pin_count == 1:
            _gc_was_enabled = gc.isenabled()
            gc.freeze()  # startup objects: off the collector's plate
            gc.disable()
    return True


def _gc_unpin() -> None:
    import gc

    global _gc_pin_count
    with _gc_pin_lock:
        _gc_pin_count -= 1
        if _gc_pin_count == 0:
            gc.unfreeze()
            if _gc_was_enabled:
                gc.enable()

ENV_PREFIX = "LIVEDATA_"


def get_env_defaults(parser: argparse.ArgumentParser, prefix: str = ENV_PREFIX) -> dict[str, Any]:
    """Defaults for parser args from LIVEDATA_* env vars (reference
    service.py:236): ``--instrument`` <- ``LIVEDATA_INSTRUMENT`` etc."""
    defaults: dict[str, Any] = {}
    for action in parser._actions:  # noqa: SLF001 - argparse has no public iteration
        if not action.option_strings:
            continue
        env_name = prefix + action.dest.upper()
        if env_name not in os.environ:
            continue
        raw = os.environ[env_name]
        if action.const is not None and isinstance(action.const, bool):
            defaults[action.dest] = raw.lower() in ("1", "true", "yes")
        elif action.type is not None:
            defaults[action.dest] = action.type(raw)
        else:
            defaults[action.dest] = raw
    return defaults


#: Metrics servers started by parse_args, keyed by the REQUESTED port
#: (including 0, the ephemeral ask): a process that parses twice (tests
#: driving main() repeatedly) must reuse its endpoint — keying by the
#: resolved port would make every `--metrics-port 0` parse leak another
#: listener, the exact accumulation this table exists to prevent. The
#: bound port is `server.port` on the stored value.
_metrics_servers: dict[int, Any] = {}
_trace_dump_paths: set[str] = set()


def _start_telemetry(parsed: argparse.Namespace) -> None:
    """Telemetry plane wiring shared by every runner (ADR 0116):
    ``--metrics-port``/``LIVEDATA_METRICS_PORT`` starts the /metrics +
    /healthz endpoint; ``--trace-dump PATH`` registers an exit-time
    Chrome trace_event dump of the tick tracer's ring."""
    port = getattr(parsed, "metrics_port", None)
    if port is None and os.environ.get("LIVEDATA_METRICS_PORT"):
        # Belt-and-braces: the env default normally lands via
        # get_env_defaults, but a runner that skips set_defaults still
        # honors the operator's env.
        port = int(os.environ["LIVEDATA_METRICS_PORT"])
    if port is not None and int(port) not in _metrics_servers:
        from ..telemetry.http import start_metrics_server

        server = start_metrics_server(int(port))
        if server is not None:
            _metrics_servers[int(port)] = server
    dump_path = getattr(parsed, "trace_dump", None)
    if dump_path and dump_path not in _trace_dump_paths:
        _trace_dump_paths.add(dump_path)
        import atexit

        from ..telemetry.trace import TRACER

        def _dump() -> None:
            try:
                TRACER.dump(dump_path)
            except Exception:  # pragma: no cover - exit-path best effort
                logger.exception("trace dump to %s failed", dump_path)

        atexit.register(_dump)


class _ServiceArgumentParser(argparse.ArgumentParser):
    """parse_args applies the CPU pin (and starts the telemetry plane)
    BEFORE returning: every service main parses first and builds
    (touching JAX) after, so handling it here covers --cpu /
    LIVEDATA_FORCE_CPU, --metrics-port / LIVEDATA_METRICS_PORT and
    programmatic argv lists alike, for all eight runners.
    """

    def parse_args(self, *args, **kwargs):  # type: ignore[override]
        parsed = super().parse_args(*args, **kwargs)
        force_env = os.environ.get("LIVEDATA_FORCE_CPU", "").lower() in (
            "1",
            "true",
            "yes",
        )
        if getattr(parsed, "cpu", False) or force_env:
            from ..utils.platform_pin import pin_cpu

            pin_cpu()
        _start_telemetry(parsed)
        return parsed


def setup_arg_parser(description: str = "") -> argparse.ArgumentParser:
    """Common CLI surface shared by all services (reference service.py:194).

    ``LIVEDATA_FORCE_CPU`` (1/true/yes) or ``--cpu`` pins JAX to the CPU
    backend before anything initializes one — the dev/demo escape hatch
    for machines where the ambient accelerator platform is configured but
    unreachable (backend init would otherwise hang or fail every job).
    """
    parser = _ServiceArgumentParser(description=description)
    parser.add_argument("--instrument", required=False, default="dummy")
    parser.add_argument("--dev", action="store_true", default=False)
    parser.add_argument(
        "--cpu",
        action="store_true",
        default=False,
        help="pin JAX to the CPU backend (see LIVEDATA_FORCE_CPU)",
    )
    parser.add_argument("--log-level", default="INFO")
    parser.add_argument("--log-json-file", default=None)
    parser.add_argument(
        "--metrics-port",
        type=int,
        default=None,
        metavar="PORT",
        help="serve the process telemetry registry on this port "
        "(GET /metrics: Prometheus text exposition; GET /healthz: "
        "liveness). LIVEDATA_METRICS_PORT equivalently; 0 picks an "
        "ephemeral port (ADR 0116)",
    )
    parser.add_argument(
        "--serve-port",
        type=int,
        default=None,
        metavar="PORT",
        help="serve the result fan-out tier on this port (GET "
        "/results: JSON stream index; GET /streams/<job>/<output>: "
        "SSE keyframe-then-deltas broadcast of the job's da00 "
        "outputs). LIVEDATA_SERVE_PORT equivalently; 0 picks an "
        "ephemeral port (ADR 0117)",
    )
    parser.add_argument(
        "--checkpoint-dir",
        default=None,
        metavar="DIR",
        help="durability plane (ADR 0118): periodically checkpoint "
        "every job's device state + Kafka offset bookmarks into DIR "
        "(atomic manifests); on restart the newest consistent "
        "generation restores and consumers seek to the bookmarks, so "
        "the gap replays instead of the accumulation resetting. "
        "LIVEDATA_CHECKPOINT_DIR equivalently",
    )
    parser.add_argument(
        "--checkpoint-interval",
        type=float,
        default=None,
        metavar="SECONDS",
        help="checkpoint cadence (default 30 s), stretched "
        "automatically while the link is congested "
        "(LIVEDATA_CHECKPOINT_INTERVAL equivalently)",
    )
    parser.add_argument(
        "--warmup",
        action="store_true",
        default=False,
        help="AOT warm-up (ADR 0118): compile tick programs on a "
        "background thread at job-commit/policy-flip time so the hot "
        "path never pays a jit compile at commit; with "
        "--checkpoint-dir also enables JAX's persistent compilation "
        "cache so restarts skip XLA (LIVEDATA_WARMUP equivalently)",
    )
    parser.add_argument(
        "--batch-decode",
        action="store_true",
        default=False,
        help="batch decode plane (ADR 0125): adapt a whole consume "
        "poll per dispatch — ev44 headers walked once, payloads landed "
        "zero-copy into reusable decode arenas, pixel-id sanitize "
        "fused into device staging. Byte-identical da00 output vs the "
        "per-message reference path (LIVEDATA_BATCH_DECODE=1 "
        "equivalently)",
    )
    parser.add_argument(
        "--trace-dump",
        default=None,
        metavar="PATH",
        help="write the per-tick tracer's span ring as Chrome "
        "trace_event JSON (chrome://tracing / Perfetto loadable) to "
        "PATH at exit; span recording itself is on unless "
        "LIVEDATA_TRACE=0 (ADR 0116)",
    )
    return parser


class ServiceBase:
    """Shared start/stop/signal scaffolding."""

    def __init__(self, *, name: str | None = None) -> None:
        self._name = name or self.__class__.__name__
        self._running = threading.Event()
        self._stopped = False
        self.exit_code = 0

    @property
    def name(self) -> str:
        return self._name

    @property
    def is_running(self) -> bool:
        return self._running.is_set()

    def start(self, blocking: bool = True) -> None:
        logger.info("Starting service %s", self._name)
        self._stopped = False
        self._running.set()
        self._start_impl()
        if blocking:
            self.run_forever()

    def _start_impl(self) -> None:  # pragma: no cover - overridden
        pass

    def stop(self) -> None:
        # _running may already be cleared (signal handler, worker failure);
        # _stop_impl must still run exactly once so the worker is joined and
        # finalize() can flush before the interpreter exits.
        if self._stopped:
            return
        self._stopped = True
        logger.info("Stopping service %s", self._name)
        self._running.clear()
        self._stop_impl()

    def _stop_impl(self) -> None:  # pragma: no cover - overridden
        pass

    def _signal_handler(self, signum: int, frame: Any) -> None:  # noqa: ARG002
        logger.info("Service %s received signal %s", self._name, signum)
        self._running.clear()

    def install_signal_handlers(self) -> None:
        signal.signal(signal.SIGTERM, self._signal_handler)
        signal.signal(signal.SIGINT, self._signal_handler)

    def run_forever(self) -> None:
        """Park the main thread until a signal or worker failure stops us."""
        self.install_signal_handlers()
        try:
            while self._running.is_set():
                time.sleep(0.1)
        finally:
            self.stop()


class Service(ServiceBase):
    """Runs a processor in a worker thread at a fixed poll interval."""

    def __init__(
        self,
        *,
        processor: Processor,
        name: str | None = None,
        poll_interval_s: float = 0.01,
    ) -> None:
        super().__init__(name=name)
        self._processor = processor
        self._poll_interval_s = poll_interval_s
        self._thread: threading.Thread | None = None

    @property
    def processor(self) -> Processor:
        return self._processor

    def step(self) -> None:
        """Single-step the loop — the deterministic test entry point
        (reference service.py:150)."""
        self._processor.process()

    #: Worker iterations between explicit cycle collections while the
    #: collector is pinned off (~14 s at the 14 Hz pulse cadence).
    GC_COLLECT_EVERY = 200

    def _run_loop(self) -> None:
        # GC pinning (LIVEDATA_GC_PINNING=0 disables): a gen-2 cycle
        # collection landing inside the ingest->publish window is a
        # multi-ms p99 outlier at LOKI batch sizes. Reference-counting
        # frees the numpy temporaries either way; the cycle collector is
        # only needed for cycles, so run it explicitly BETWEEN process()
        # calls where the 71 ms pulse budget absorbs it.
        pin_gc = os.environ.get("LIVEDATA_GC_PINNING", "1") != "0"
        did_disable = False
        if pin_gc:
            did_disable = _gc_pin()
        iterations = 0
        try:
            while self._running.is_set():
                start = time.monotonic()
                self._processor.process()
                iterations += 1
                if pin_gc and iterations % self.GC_COLLECT_EVERY == 0:
                    import gc

                    gc.collect()
                elapsed = time.monotonic() - start
                sleep = self._poll_interval_s - elapsed
                if sleep > 0:
                    time.sleep(sleep)
        except Exception:
            logger.exception("Service %s worker failed", self._name)
            self.exit_code = 1
            self._running.clear()
            # Wake the parked main thread so the process exits and the
            # supervisor restarts it (reference service.py:166-180).
            try:
                signal.raise_signal(signal.SIGINT)
            # Intentional swallow: the wakeup is best-effort during crash
            # teardown, and any error here (exotic platform, interpreter
            # shutdown) must not mask the worker failure logged above.
            except Exception:  # pragma: no cover  # graftlint: disable=JGL007
                pass
        finally:
            if did_disable:
                _gc_unpin()
            try:
                self._processor.finalize()
            except Exception:
                logger.exception("Service %s finalize failed", self._name)

    def _start_impl(self) -> None:
        self._thread = threading.Thread(
            target=self._run_loop, name=f"{self._name}-worker", daemon=True
        )
        self._thread.start()

    def _stop_impl(self) -> None:
        if self._thread is not None:
            # Processors advertise how long their finalize may take
            # (a pipelined processor drains in-flight windows, ADR 0111:
            # no dropped batches on stop); default to the historical 5 s.
            timeout = float(
                getattr(self._processor, "stop_grace_s", 5.0)
            )
            self._thread.join(timeout=timeout)
            self._thread = None

"""Time-window batching with a data-derived clock.

Parity with reference ``core/message_batcher.py``: batch boundaries come from
*message timestamps*, never wall clock, and are quantized to the 14 Hz pulse
grid. Three batchers:

- ``NaiveMessageBatcher`` (reference :62): emit every poll immediately with
  pulse-quantized bounds — removes batching nondeterminism in tests.
- ``SimpleMessageBatcher`` (reference :93): fixed windows; a window closes
  when the first message of a later window arrives; late messages (older
  than the open window) are folded into the next emitted batch rather than
  dropped (reference :105-113).
- ``AdaptiveMessageBatcher`` (reference :230): window escalates x2 after 2
  consecutive overloaded batches and de-escalates x(1/sqrt 2) after 3
  consecutive underloaded ones, with a dead zone between the thresholds so
  the two rules cannot oscillate (reference :190-207); windows stay
  pulse-quantized (reference :210); a wall-clock idle timeout de-escalates
  when data stops flowing (reference :283-289).

All window arithmetic is exact-integer in pulse indices (see
``core/timestamp.py``), so boundaries are reproducible across hosts.
"""

from __future__ import annotations

import math
import threading
import time
from dataclasses import dataclass, field
from typing import Protocol, runtime_checkable

from .message import Message
from .timestamp import Duration, Timestamp

__all__ = [
    "AdaptiveMessageBatcher",
    "LoadGovernor",
    "MessageBatch",
    "MessageBatcher",
    "NaiveMessageBatcher",
    "SimpleMessageBatcher",
]

from .constants import PULSE_PERIOD_NS_DEN, PULSE_PERIOD_NS_NUM


def _pulses_for(window: Duration) -> int:
    """Window length in whole pulses (>= 1)."""
    return max(1, round(window.ns * PULSE_PERIOD_NS_DEN / PULSE_PERIOD_NS_NUM))


@dataclass(slots=True)
class MessageBatch:
    """Messages plus the data-time window they were batched into."""

    start: Timestamp
    end: Timestamp
    messages: list[Message] = field(default_factory=list)

    def __len__(self) -> int:
        return len(self.messages)

    @property
    def window(self) -> Duration:
        return self.end - self.start


@runtime_checkable
class MessageBatcher(Protocol):
    def batch(self, messages: list[Message]) -> MessageBatch | None: ...

    def report_processing_time(self, duration: Duration) -> None: ...


class NaiveMessageBatcher:
    """Emit every nonempty poll as one batch with pulse-quantized bounds."""

    #: Emits every poll's messages immediately — nothing ever pends
    #: (the durability plane's quiescence probe, ADR 0118).
    pending_messages = 0

    def batch(self, messages: list[Message]) -> MessageBatch | None:
        if not messages:
            return None
        lo = min(m.timestamp for m in messages).quantize()
        hi = max(m.timestamp for m in messages)
        end = hi.quantize_up()
        if end == hi:  # message exactly on grid: window must contain it
            end = Timestamp.from_pulse_index(hi.pulse_index() + 1)
        return MessageBatch(start=lo, end=end, messages=list(messages))

    def report_processing_time(self, duration: Duration) -> None:
        pass


class SimpleMessageBatcher:
    """Fixed data-time windows closed by the first message of a later window."""

    def __init__(self, window: Duration = Duration.from_s(1.0)) -> None:
        self._window_pulses = _pulses_for(window)
        self._buffer: list[Message] = []
        self._start_pulse: int | None = None
        # Width of the most recently *emitted* batch: load feedback must be
        # measured against the window the work actually covered, not a
        # freshly escalated width.
        self._last_emitted_pulses: int = self._window_pulses
        # Reentrant: the adaptive subclass wraps batch() and re-enters the
        # base implementation under the same lock. Today's in-repo callers
        # drive batch()/report_processing_time() from the one service
        # worker thread, so this is a defensive guarantee, not a fix for
        # an observed race: batchers are protocol objects handed to
        # multi-threaded transports, and an unguarded cross-thread
        # ``window`` read could observe a half-advanced (start_pulse,
        # window_pulses) pair mid-update. Uncontended RLock acquisition
        # is tens of ns against a >=71 ms batch window.
        self._lock = threading.RLock()

    @property
    def window(self) -> Duration:
        with self._lock:
            return Duration(
                self._window_pulses * PULSE_PERIOD_NS_NUM // PULSE_PERIOD_NS_DEN
            )

    def _window_pulses_next(self) -> int:
        """Hook for adaptive subclass: pulses for the next opened window."""
        return self._window_pulses

    @property
    def pending_messages(self) -> int:
        """Messages buffered toward a not-yet-closed window. The
        durability plane (ADR 0118) checkpoints only when this reads 0:
        a bookmark taken while a partial window sits here would claim
        data as processed that no job state yet contains — replay
        would then skip it."""
        with self._lock:
            return len(self._buffer)

    def batch(self, messages: list[Message]) -> MessageBatch | None:
        with self._lock:
            return self._batch_locked(messages)

    def _batch_locked(self, messages: list[Message]) -> MessageBatch | None:
        self._buffer.extend(messages)
        if not self._buffer:
            return None
        if self._start_pulse is None:
            first = min(m.timestamp for m in self._buffer)
            self._start_pulse = first.pulse_index()
        end_pulse = self._start_pulse + self._window_pulses
        end_ts = Timestamp.from_pulse_index(end_pulse)
        # The window closes only once data time has moved past it.
        if not any(m.timestamp >= end_ts for m in self._buffer):
            return None
        emitted = [m for m in self._buffer if m.timestamp < end_ts]
        self._buffer = [m for m in self._buffer if m.timestamp >= end_ts]
        self._last_emitted_pulses = self._window_pulses
        batch = MessageBatch(
            start=Timestamp.from_pulse_index(self._start_pulse),
            end=end_ts,
            messages=emitted,
        )
        # Advance to the aligned window containing the earliest remaining
        # message (skipping empty windows), using the possibly-updated width.
        self._window_pulses = self._window_pulses_next()
        next_pulse = min(m.timestamp for m in self._buffer).pulse_index()
        skipped = (next_pulse - end_pulse) // self._window_pulses
        self._start_pulse = end_pulse + max(0, skipped) * self._window_pulses
        return batch

    def report_processing_time(self, duration: Duration) -> None:
        pass


class LoadGovernor:
    """The load->window-scale state machine shared by the adaptive and
    rate-aware batchers: above ``high_load`` for ``escalate_after``
    consecutive batches the scale doubles (cap ``max_scale``); below
    ``high_load / (2*sqrt 2)`` for ``deescalate_after`` batches it
    shrinks by 1/sqrt 2 (floor 1). The gap between thresholds is the
    dead zone preventing oscillation after a doubling halves the load.
    """

    def __init__(
        self,
        *,
        max_scale: float = 8.0,
        high_load: float = 0.8,
        escalate_after: int = 2,
        deescalate_after: int = 3,
    ) -> None:
        self.scale = 1.0
        self._max_scale = max_scale
        self._high = high_load
        self._low = high_load / (2.0 * math.sqrt(2.0))
        self._escalate_after = escalate_after
        self._deescalate_after = deescalate_after
        self._over = 0
        self._under = 0
        # The consecutive-batch counters are read-modify-write sequences.
        # The governor is shared infrastructure (adaptive AND rate-aware
        # batchers); in-repo callers feed it from one worker thread, so —
        # as with the batcher lock above — this makes the class safe to
        # drive from any thread rather than fixing an observed race: a
        # lost increment would silently defer an escalation. RLock:
        # observe() re-enters escalate()/relax().
        self._lock = threading.RLock()

    def observe(self, load: float) -> bool:
        """Feed one batch's load; returns True when the scale changed."""
        with self._lock:
            if load > self._high:
                self._over += 1
                self._under = 0
            elif load < self._low:
                self._under += 1
                self._over = 0
            else:
                self._over = 0
                self._under = 0
            if self._over >= self._escalate_after:
                self._over = 0
                return self.escalate()
            if self._under >= self._deescalate_after:
                self._under = 0
                return self.relax()
            return False

    def escalate(self) -> bool:
        with self._lock:
            new = min(self._max_scale, self.scale * 2.0)
            changed = new != self.scale
            self.scale = new
            return changed

    def relax(self) -> bool:
        with self._lock:
            new = max(1.0, self.scale / math.sqrt(2.0))
            changed = new != self.scale
            self.scale = new
            return changed


class AdaptiveMessageBatcher(SimpleMessageBatcher):
    """Load-adaptive windows.

    ``report_processing_time`` feeds back the wall time the service spent on
    the last emitted batch. Load = processing_time / window. Above
    ``high_load`` for ``escalate_after`` consecutive batches the window
    doubles (cap ``max_scale`` x base); below ``high_load / (2*sqrt 2)`` for
    ``deescalate_after`` consecutive batches it shrinks by 1/sqrt 2 (floor at
    base). The gap between thresholds is the dead zone: after one doubling,
    load halves, landing between the thresholds — no oscillation.
    """

    def __init__(
        self,
        window: Duration = Duration.from_s(1.0),
        *,
        max_scale: float = 8.0,
        high_load: float = 0.8,
        escalate_after: int = 2,
        deescalate_after: int = 3,
        idle_timeout_s: float = 5.0,
        clock=time.monotonic,
    ) -> None:
        super().__init__(window)
        self._base_pulses = self._window_pulses
        self._governor = LoadGovernor(
            max_scale=max_scale,
            high_load=high_load,
            escalate_after=escalate_after,
            deescalate_after=deescalate_after,
        )
        self._pending_pulses = self._window_pulses
        self._idle_timeout_s = idle_timeout_s
        self._clock = clock
        self._last_activity = clock()

    @property
    def scale(self) -> float:
        with self._lock:
            return self._pending_pulses / self._base_pulses

    def _window_pulses_next(self) -> int:
        return self._pending_pulses

    def batch(self, messages: list[Message]) -> MessageBatch | None:
        with self._lock:
            now = self._clock()
            if messages:
                self._last_activity = now
            elif (
                now - self._last_activity > self._idle_timeout_s
                and self._pending_pulses > self._base_pulses
            ):
                # Data stopped: relax toward the base window so the next
                # burst is not stuck behind a huge escalated window.
                self._deescalate()
                self._last_activity = now
            return self._batch_locked(messages)

    def report_processing_time(self, duration: Duration) -> None:
        with self._lock:
            window_ns = (
                self._last_emitted_pulses
                * PULSE_PERIOD_NS_NUM
                / PULSE_PERIOD_NS_DEN
            )
            if self._governor.observe(duration.ns / window_ns):
                self._apply_scale()

    def _deescalate(self) -> None:
        """Idle relaxation path (wall-clock driven); caller holds the lock."""
        self._governor.relax()
        self._apply_scale()

    def _apply_scale(self) -> None:
        self._pending_pulses = max(
            1, round(self._base_pulses * self._governor.scale)
        )

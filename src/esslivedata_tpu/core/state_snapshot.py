"""Device-state snapshots at run boundaries (SURVEY §5 checkpoint note).

The reference accepts that accumulated histograms die with the process;
this build's HBM-resident :class:`~esslivedata_tpu.ops.histogram
.HistogramState` makes a cheap dump/restore worth having: on RunStop
(and on graceful service shutdown) each job's device state is fetched to
host and written as an ``.npz``; a restarted service restores it when a
job with the SAME configuration is scheduled again.

Safety model — a snapshot is only ever restored when:

- the workflow's **fingerprint** matches (a hash over everything that
  gives bins physical meaning: projection LUT bytes, TOA edges, decay,
  screen geometry). A changed geometry or binning invalidates the
  snapshot rather than blending counts with different meaning.
- it is **one-shot**: the file is deleted on successful restore, so a
  stale snapshot cannot resurrect twice.

Workflows opt in structurally (duck-typed): ``state_fingerprint()``,
``dump_state() -> dict[str, np.ndarray]``, ``restore_state(dict) ->
bool``.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import re
import time
from pathlib import Path

import numpy as np

__all__ = ["SnapshotStore", "supports_snapshot"]

logger = logging.getLogger(__name__)


def supports_snapshot(workflow) -> bool:
    return (
        hasattr(workflow, "state_fingerprint")
        and hasattr(workflow, "dump_state")
        and hasattr(workflow, "restore_state")
    )


def _slug(text: str) -> str:
    return re.sub(r"[^A-Za-z0-9._-]+", "_", text)


class SnapshotStore:
    """npz-per-job snapshot directory with atomic writes."""

    def __init__(self, directory: str | os.PathLike) -> None:
        self._dir = Path(directory)
        self._dir.mkdir(parents=True, exist_ok=True)

    def _path(
        self, workflow_id: str, source_name: str, archive: bool
    ) -> Path:
        suffix = ".runfinal.npz" if archive else ".npz"
        # _slug output may itself contain '_', so the '__' join alone is
        # ambiguous ('a' + 'b__c' vs 'a__b' + 'c'): a short digest of the
        # unambiguous pair keeps distinct jobs on distinct files (the
        # fingerprint check would refuse a wrong restore, but last-dump-
        # wins on one shared file would silently destroy the other
        # job's snapshot).
        pair = hashlib.sha256(
            f"{workflow_id}\x00{source_name}".encode()
        ).hexdigest()[:8]
        return self._dir / (
            f"{_slug(workflow_id)}__{_slug(source_name)}__{pair}{suffix}"
        )

    def _legacy_path(
        self, workflow_id: str, source_name: str, archive: bool
    ) -> Path:
        """Pre-digest filename (no pair hash): snapshots written by an
        older service must survive the upgrade, so load() falls back to
        this name and migrates on hit."""
        suffix = ".runfinal.npz" if archive else ".npz"
        return self._dir / (
            f"{_slug(workflow_id)}__{_slug(source_name)}{suffix}"
        )

    def save(
        self,
        *,
        workflow_id: str,
        source_name: str,
        fingerprint: str,
        arrays: dict[str, np.ndarray],
        reason: str = "",
        archive: bool = False,
    ) -> Path:
        """``archive=True`` writes to a separate ``.runfinal`` key that
        :meth:`load` never reads: a finished run's final accumulation is
        preserved for inspection/explicit recovery WITHOUT ever being
        resurrected into a later job (which would mix runs). The main
        key is the crash/shutdown-recovery channel only."""
        path = self._path(workflow_id, source_name, archive)
        tmp = path.with_suffix(".tmp")
        meta = json.dumps(
            {
                "fingerprint": fingerprint,
                "workflow_id": workflow_id,
                "source_name": source_name,
                "saved_at": time.time(),
                "reason": reason,
            }
        )
        # Uncompressed: this may run at a run boundary in the processing
        # path; the state is the projected screen (a few MB), and raw
        # write speed beats compression there.
        with open(tmp, "wb") as fh:
            np.savez(
                fh, __meta__=np.frombuffer(meta.encode(), np.uint8), **arrays
            )
            fh.flush()
            # fsync BEFORE the rename (graftlint JGL020): without it
            # the rename can become durable before the data it names,
            # and a crash leaves the final path pointing at garbage a
            # restart would trust.
            os.fsync(fh.fileno())
        os.replace(tmp, path)  # atomic: a reader never sees a torn file
        logger.info(
            "Snapshot saved for %s/%s (%s)", workflow_id, source_name, reason
        )
        return path

    def load(
        self,
        *,
        workflow_id: str,
        source_name: str,
        fingerprint: str,
        consume: bool = True,
    ) -> dict[str, np.ndarray] | None:
        """Arrays if a snapshot exists AND its fingerprint matches; with
        ``consume`` the file is deleted on a hit (kept on a mismatch — a
        rollback to the old configuration can still use it). Callers
        that might REFUSE the arrays after loading (a workflow whose
        device state is not built yet) pass ``consume=False`` and call
        :meth:`discard` only once the restore actually succeeded."""
        path = self._path(workflow_id, source_name, archive=False)
        if not path.exists():
            # Upgrade path: adopt a snapshot written under the pre-digest
            # filename so a restart across the version change still
            # restores (the fingerprint check below stays the gate).
            legacy = self._legacy_path(workflow_id, source_name, archive=False)
            if legacy.exists():
                try:
                    legacy.rename(path)
                except OSError:
                    path = legacy
        try:
            with np.load(path) as archive:
                meta = json.loads(bytes(archive["__meta__"]).decode())
                if meta.get("fingerprint") != fingerprint:
                    logger.info(
                        "Snapshot for %s/%s ignored: fingerprint mismatch",
                        workflow_id,
                        source_name,
                    )
                    return None
                arrays = {
                    k: archive[k] for k in archive.files if k != "__meta__"
                }
        except FileNotFoundError:
            return None
        except Exception:
            logger.exception("Snapshot for %s/%s unreadable", workflow_id, source_name)
            return None
        if consume:
            self.discard(workflow_id=workflow_id, source_name=source_name)
        return arrays

    def discard(self, *, workflow_id: str, source_name: str) -> None:
        try:
            self._path(workflow_id, source_name, archive=False).unlink()
        except OSError:
            pass

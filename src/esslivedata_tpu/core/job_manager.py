"""Job lifecycle and scheduling.

Parity with reference ``core/job_manager.py``: JobFactory.create:140 (eager
workflow build at schedule time — startup cost paid at the command, not in
the hot loop), phase machine scheduled -> pending_context -> active with a
finishing overlay (:223), data-time-driven activation (_advance_to_time:357),
context gating per ADR 0002 (_open_context_gates:599), run-transition resets
(:486-501), thread-pool fan-out of per-job work (:560,690) and per-job
error/warning containment instead of service death (:640-682).

TPU note on the fan-out: device kernels serialize on the chip anyway, so
threads only overlap the *host-side* staging/finalize portions — the
default thread count stays modest (reference default 5).
"""

from __future__ import annotations

import bisect
import logging
import threading
import time
import uuid
from collections.abc import Mapping
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field
from typing import Any, Literal

from pydantic import BaseModel, model_validator

from ..config.workflow_spec import JobId, WorkflowConfig
from ..preprocessors.event_data import StagedEvents
from ..telemetry.trace import TRACER
from ..utils.compat import StrEnum
from ..workflows.workflow_factory import WorkflowFactory, workflow_registry
from .device_event_cache import DeviceEventCache
from .job import Job, JobResult, JobState, JobStatus
from .message import RunStart, RunStop
from .state_snapshot import supports_snapshot
from .timestamp import Timestamp

__all__ = ["JobCommand", "JobFactory", "JobManager"]

logger = logging.getLogger(__name__)


class JobCommand(BaseModel):
    """stop/remove/reset command from the dashboard (reference :67).

    Selector forms (reference job_manager broadcast/by-workflow actions):

    - exact: ``source_name`` + ``job_number`` — one job;
    - by source: ``source_name`` alone — every job on that source;
    - by workflow: ``workflow_id`` (optionally + ``source_name``) —
      every job of that workflow;
    - broadcast: no selector — every job this service hosts.
    """

    action: Literal["stop", "remove", "reset"]
    source_name: str | None = None
    job_number: uuid.UUID | None = None
    workflow_id: str | None = None

    @model_validator(mode="after")
    def _job_number_needs_source(self):
        if self.job_number is not None and self.source_name is None:
            raise ValueError("job_number requires source_name")
        return self

    def matches(self, job_id: JobId, workflow_id) -> bool:
        if self.job_number is not None:
            return (
                job_id.source_name == self.source_name
                and job_id.job_number == self.job_number
            )
        if self.source_name is not None and job_id.source_name != self.source_name:
            return False
        if self.workflow_id is not None and str(workflow_id) != self.workflow_id:
            return False
        return True


class JobFactory:
    """Builds Jobs from start commands via the workflow registry."""

    def __init__(self, registry: WorkflowFactory | None = None) -> None:
        self._registry = registry if registry is not None else workflow_registry

    def create(self, config: WorkflowConfig) -> Job:
        spec = self._registry[config.identifier]
        workflow = self._registry.create(config)
        aux = set(config.aux_source_names.values())
        return Job(
            job_id=config.job_id,
            workflow_id=config.identifier,
            workflow=workflow,
            schedule=config.schedule,
            primary_streams={config.job_id.source_name},
            aux_streams=aux,
            context_keys=set(spec.context_keys),
            optional_context_keys=set(spec.optional_context_keys),
            reset_on_run_transition=spec.reset_on_run_transition,
            params=dict(config.params),
        )


class _Phase(StrEnum):
    SCHEDULED = "scheduled"
    PENDING_CONTEXT = "pending_context"
    ACTIVE = "active"
    STOPPED = "stopped"


@dataclass
class _JobRecord:
    job: Job
    phase: _Phase = _Phase.SCHEDULED
    finishing: bool = False
    error: str = ""
    warning: str = ""
    has_primary_data: bool = False
    # A run-transition reset whose workflow.clear() failed; retried before
    # the job may accumulate again, so data from the old and new run can
    # never mix in a wedged workflow.
    needs_reset: bool = False
    # True once this record completed a finalize that was timed (or
    # could have been): the FIRST offer-less finalize may compile its
    # publish program, so its wall time must not feed the RTT estimate.
    publish_timed: bool = False
    # Context streams whose latest cached value this job has not received
    # yet. Persisted across windows so an update arriving while the job is
    # idle (no data, nothing pending) is delivered before its next add —
    # a fresh value is queued once and stays queued until a successful
    # set_context.
    stale_context: set[str] = field(default_factory=set)

    @property
    def state(self) -> JobState:
        if self.error:
            return JobState.ERROR
        if self.phase == _Phase.STOPPED:
            return JobState.STOPPED
        if self.finishing:
            return JobState.FINISHING
        if self.phase == _Phase.PENDING_CONTEXT:
            # More informative than WARNING; the missing-context warning
            # still rides the status message field.
            return JobState.PENDING_CONTEXT
        if self.warning:
            return JobState.WARNING
        return JobState(self.phase.value)


class JobManager:
    """Keeps the job table; drives activation, gating, processing, resets."""

    def __init__(
        self,
        *,
        job_factory: JobFactory | None = None,
        job_threads: int = 5,
        snapshot_store=None,
        combine_publish: bool = True,
        tick_program: bool = True,
        placement=None,
        durability=None,
    ) -> None:
        self._factory = job_factory or JobFactory()
        #: Cross-job publish combiner (ADR 0113): every job due in a
        #: publish tick is served from ONE device execute + ONE packed
        #: fetch per device. ``combine_publish=False`` keeps the per-job
        #: path (the parity tests' reference).
        from ..ops.publish import PublishCombiner
        from ..ops.tick import TickCombiner

        self._publish_combiner = (
            PublishCombiner() if combine_publish else None
        )
        #: Whole-tick program (ADR 0114): a (stream, fuse-key) group
        #: whose every member is due a publish steps AND publishes in
        #: ONE jitted dispatch + ONE fetch, replacing the worst-case
        #: stage/step/publish triple. ``tick_program=False`` keeps the
        #: separate fused-step + combined-publish path (the parity
        #: tests' reference); it requires combining — without the
        #: combiner's offer plumbing there is nothing to fuse into.
        self._tick_combiner = (
            TickCombiner() if (combine_publish and tick_program) else None
        )
        #: Mesh-slice placement policy (parallel/mesh_tick.py,
        #: ADR 0115): assigns every (stream, fuse-key) tick/fused group
        #: a sticky mesh slice — a single device round-robin for
        #: single-device histogrammers, the whole mesh for bank-sharded
        #: ones. Staging keys carry the slice (one transfer per slice),
        #: member states are committed to it once at assignment, mesh
        #: groups run through the slice's MeshTickCombiner, and each
        #: slice's publish RTT reports separately to the link monitor.
        #: None = classic single-placement behavior, byte-identical.
        self._placement = placement
        #: Publish-coalescing window (link policy, ADR 0113): finalize
        #: only every Nth data window — accumulation continues every
        #: window, so a degraded relay pays the publish round trip less
        #: often. 1 = publish every window; finishing jobs and idle
        #: flushes always publish.
        self._publish_coalesce = 1
        self._window_seq = 0
        #: LinkMonitor (duck-typed ``observe_publish``), attached via
        #: ``set_link_observer``: combined publishes time the real
        #: device round trip into it.
        self._link_observer = None
        #: Job-retirement observer (``set_retire_observer``): called
        #: with each removed JobId so downstream caches — the result
        #: fan-out tier's ResultCache (ADR 0117) — drop the job's
        #: streams instead of serving stale keyframes forever.
        self._retire_observer = None
        #: Optional core.state_snapshot.SnapshotStore: device-resident
        #: accumulation is dumped at run boundaries + shutdown and
        #: restored when an identically-configured job is scheduled
        #: (SURVEY §5 checkpoint note).
        self._snapshot_store = snapshot_store
        #: Optional durability plane (durability/checkpoint.py,
        #: ADR 0118): the periodic checkpoint channel. Consulted FIRST
        #: at schedule-time restore (fresher than the shutdown-only
        #: store), re-seeds fresh states at the state-loss containment
        #: sites, and receives the run-boundary reset sequence so stale
        #: manifests can never resurrect old-run data.
        self._durability = durability
        #: Run-boundary reset sequence — persisted by the durability
        #: plane as the manifest staleness gate. Seeded from the
        #: plane's persisted marker: a process that restarts AFTER a
        #: reset must stamp new manifests at (or past) the marker, or
        #: every post-restart checkpoint would be rejected as stale
        #: forever (pinned in tests/durability).
        self._reset_seq = self._seed_reset_seq(durability)
        #: Optional AOT warm-up service (durability/warmup.py): job
        #: commits/removals and wire flips plan the next tick's program
        #: keys and compile them off the hot path before the change
        #: goes live.
        self._warmup = None
        #: Fault-injection schedule (harness/chaos.py, ADR 0120);
        #: None in production.
        self._chaos = None
        #: Fleet assignment (fleet/assignment.py, ADR 0121): when set,
        #: each window processes only the (stream, fuse-key) groups
        #: this replica owns; None = single-replica (everything local).
        self._fleet = None
        #: Last seen padded batch size per stream — the staged-signature
        #: memory warm-up plans against (a tick program's key includes
        #: the staged wire's shape, and commit-time warm-up must
        #: compile against the shape the stream actually carries).
        self._stream_batch_shapes: dict[str, int] = {}
        self._records: dict[JobId, _JobRecord] = {}
        #: Stage-once staging per stream (ADR 0110): every window's event
        #: batches decode/flatten/transfer ONCE per (stream, layout) no
        #: matter how many jobs subscribe; slots are attached to the
        #: window's StagedEvents values in process_jobs.
        self._event_cache = DeviceEventCache()
        self._lock = threading.RLock()
        # Reset times scheduled by run transitions, sorted; each fires when
        # DATA time reaches it (reference :486-501) — never on arrival
        # order, so a run-start announced ahead of the data stream resets
        # exactly at the boundary even if messages straddle it.
        self._pending_reset_times: list[Timestamp] = []
        self._executor = (
            ThreadPoolExecutor(max_workers=job_threads, thread_name_prefix="job")
            if job_threads > 1
            else None
        )

    # -- scheduling --------------------------------------------------------
    def schedule_job(self, config: WorkflowConfig) -> JobId:
        """Create + register a job. The workflow builds eagerly here so
        compile/LUT cost lands at command time, not in the data path."""
        with self._lock:
            if config.job_id in self._records:
                raise ValueError(f"Job {config.job_id} already exists")
            job = self._factory.create(config)
            self._records[config.job_id] = _JobRecord(job=job)
            logger.info("Scheduled job %s (%s)", config.job_id, config.identifier)
            # Consumer-set change: flush staged slots (ADR 0110). Entries
            # are window-scoped anyway; this keeps the rule explicit.
            self._event_cache.invalidate()
            self._maybe_restore(job)
        # Outside the lock: warm-up planning calls workflow offer code.
        # The commit re-keys every tick group the new job joins (member
        # tuple change), so the programs its FIRST live window needs
        # compile on the warm-up thread now instead of stalling that
        # window (ADR 0118).
        self._queue_warmup("commit")
        return config.job_id

    def _maybe_restore(self, job: Job) -> None:
        """Adopt a prior process's accumulation for this configuration.

        The durability plane's periodic checkpoint (ADR 0118) is
        consulted first — it is at most one checkpoint interval stale,
        against the shutdown-only store's crash-loses-everything — and
        carries job-level meta (state_epoch, generation start) the old
        channel never had. The ADR 0107 store stays as the fallback so
        a deployment with only LIVEDATA_SNAPSHOT_DIR keeps its exact
        pre-durability behavior.
        """
        if self._durability is not None:
            try:
                if self._durability.restore_job(job):
                    return
            except Exception:
                logger.exception(
                    "checkpoint restore failed for %s; trying the "
                    "snapshot store",
                    job.job_id,
                )
        store, wf = self._snapshot_store, job.workflow
        if store is None or not supports_snapshot(wf):
            return
        try:
            # Non-consuming load: a workflow that refuses the arrays
            # (device state not built yet) keeps the file for a later
            # schedule instead of losing it.
            arrays = store.load(
                workflow_id=str(job.workflow_id),
                source_name=job.job_id.source_name,
                fingerprint=wf.state_fingerprint(),
                consume=False,
            )
            if arrays is not None and wf.restore_state(arrays):
                store.discard(
                    workflow_id=str(job.workflow_id),
                    source_name=job.job_id.source_name,
                )
                logger.info(
                    "Restored snapshot state for %s/%s",
                    job.workflow_id,
                    job.job_id.source_name,
                )
        except Exception:
            logger.exception(
                "Snapshot restore failed for %s; starting fresh", job.job_id
            )

    def _dump_snapshot(
        self, rec: _JobRecord, reason: str, archive: bool = False
    ) -> None:
        store, wf = self._snapshot_store, rec.job.workflow
        if store is None or not supports_snapshot(wf):
            return
        try:
            arrays = wf.dump_state()
            if not arrays:
                # Nothing accumulated yet (context-gated workflow before
                # its first table): don't overwrite a prior snapshot.
                return
            store.save(
                workflow_id=str(rec.job.workflow_id),
                source_name=rec.job.job_id.source_name,
                fingerprint=wf.state_fingerprint(),
                arrays=arrays,
                reason=reason,
                archive=archive,
            )
        except Exception:
            logger.exception("Snapshot dump failed for %s", rec.job.job_id)

    def dump_snapshots(self, reason: str = "shutdown") -> None:
        # Every non-stopped job, INCLUDING still-scheduled ones: a job
        # that restored a snapshot but never activated holds that
        # accumulation only in its workflow — skipping it here would
        # destroy it (the restore consumed the file).
        with self._lock:
            for rec in self._records.values():
                if rec.phase != _Phase.STOPPED:
                    self._dump_snapshot(rec, reason)

    # -- durability plane (durability/, ADR 0118) --------------------------
    @staticmethod
    def _seed_reset_seq(plane) -> int:
        """The persisted reset marker (0 without a plane/marker)."""
        marker = getattr(plane, "reset_marker", None)
        if marker is None:
            return 0
        try:
            return int(marker())
        except Exception:
            logger.exception("reset-marker read failed; seeding 0")
            return 0

    def set_durability(self, plane) -> None:
        """Attach the periodic checkpoint plane (duck-typed:
        ``restore_job``/``note_reset``/``reset_marker``)
        post-construction; the reset sequence re-seeds from the
        plane's persisted marker (never backward)."""
        self._durability = plane
        with self._lock:
            self._reset_seq = max(
                self._reset_seq, self._seed_reset_seq(plane)
            )

    def set_warmup(self, service) -> None:
        """Attach the AOT warm-up service (durability/warmup.py):
        commits, removals and wire flips submit tick-program warm-up
        requests through it."""
        self._warmup = service

    def set_chaos(self, chaos) -> None:
        """Install a fault-injection schedule (harness/chaos.py,
        ADR 0120). Two sites: ``slow_tick`` delays a window before any
        lock is taken (a slow-tick storm, the watchdog's prey), and
        ``tick_dispatch`` raises AFTER a tick program's dispatch ran —
        the post-donation failure mode, exercising the exact
        ``note_state_lost`` containment the live failure would. None
        (production) costs one attribute check per window."""
        self._chaos = chaos

    def set_fleet(self, assignment) -> None:
        """Partition this manager across a replica fleet (duck-typed:
        ``owns(stream, fuse_tag)`` — fleet/assignment.py, ADR 0121).
        Each window then processes only the (stream, fuse-key) groups
        rendezvous-hashed to THIS replica: fresh data for unowned
        groups is dropped (a peer replica is accumulating it), while
        state already accumulated here still flushes — so a rebalance
        drains cleanly and the new owner's checkpoint-restore + replay
        (ADR 0118) carries the group forward as a gap, not a reset."""
        with self._lock:
            self._fleet = assignment

    @property
    def reset_seq(self) -> int:
        """Run-boundary resets fired since construction — rides every
        checkpoint manifest as its staleness tag."""
        with self._lock:
            return self._reset_seq

    def checkpoint_snapshot(self) -> list[dict]:
        """Per-job state-dump entries for the CheckpointPlane: every
        non-stopped snapshot-capable job's host arrays plus the meta a
        restart needs (fingerprint gate, state_epoch, generation
        start). The record list is captured under the lock; the
        device→host fetches run outside it with per-job containment —
        the plane's caller (the processor) only checkpoints at
        quiescent window boundaries, so nothing steps these states
        concurrently, and a job that still fails to dump is skipped
        this generation rather than wedging the checkpoint."""
        with self._lock:
            records = [
                rec
                for rec in self._records.values()
                if rec.phase != _Phase.STOPPED
            ]
        entries: list[dict] = []
        for rec in records:
            wf = rec.job.workflow
            if wf is None or not supports_snapshot(wf):
                continue
            try:
                arrays = wf.dump_state()
                if not arrays:
                    # Nothing accumulated yet (context-gated workflow
                    # before its first table): no entry beats an empty
                    # state resurrecting over a later restore.
                    continue
                entries.append(
                    {
                        "workflow_id": str(rec.job.workflow_id),
                        "source_name": rec.job.job_id.source_name,
                        "job_number": str(rec.job.job_id.job_number),
                        "fingerprint": wf.state_fingerprint(),
                        "state_epoch": rec.job.state_epoch,
                        "generation_start_ns": rec.job.generation_start_ns,
                        "arrays": arrays,
                    }
                )
            except Exception:
                logger.exception(
                    "checkpoint dump failed for %s; skipped this "
                    "generation",
                    rec.job.job_id,
                )
        return entries

    def _after_state_loss(self, rec: _JobRecord) -> None:
        """Durability hook at every ``note_state_lost`` containment
        site (ADR 0118): the fresh zeroed state just installed is
        re-seeded from the newest checkpoint, so a donated-dispatch
        failure costs the gap since the last checkpoint instead of the
        whole accumulated run. ``adopt_meta=False`` — the epoch already
        bumped, and regressing it would let a delta stream splice
        across the rebuild (the next publish must keyframe)."""
        plane = self._durability
        if plane is None:
            return
        try:
            if plane.restore_job(
                rec.job, adopt_meta=False, reason="state_lost"
            ):
                rec.warning += "; re-seeded from last checkpoint"
        except Exception:
            logger.exception(
                "state-loss checkpoint restore failed for %s",
                rec.job.job_id,
            )

    def request_warmup(self, trigger: str) -> None:
        """Plan + submit tick-program warm-up for the current job set
        (ADR 0118). Called internally on commits/removals/wire flips;
        public so the processor (policy changes) and layout-swap
        appliers can pre-compile before a change goes live."""
        self._queue_warmup(trigger)

    def _queue_warmup(self, trigger: str) -> None:
        warmup = self._warmup
        if warmup is None or self._tick_combiner is None:
            return
        try:
            requests = self.plan_warmup(trigger)
        except Exception:
            logger.exception("warm-up planning failed (%s)", trigger)
            return
        if requests:
            warmup.submit(requests)

    def plan_warmup(self, trigger: str = "commit") -> list:
        """Plan one WarmupRequest per tick-eligible (stream, fuse-key)
        group, against the batch shape each stream has actually been
        carrying (``_stream_batch_shapes``) — the staged signature in a
        tick program's key. Mirrors the live planners: the record
        predicate is ``prestage_window``'s (active, or scheduled with
        no gate — those activate on their first window), grouping is
        ``_plan_fused_steps``'s (event_ingest offers keyed by (stream,
        offer key)), and eligibility is ``_split_tick_groups``'s
        (publish offer present, args[0] IS the ingest state). Offers
        are side-effect free by contract, and member args travel as
        ``jax.ShapeDtypeStruct`` trees — planning never touches (or
        pins) a live device buffer. Streams with no remembered shape
        (nothing consumed yet) are skipped: there is no signature to
        warm against, and their first window compiles as a startup
        ``new_group`` exactly as before.
        """
        import jax as _jax
        import numpy as np

        from ..durability.warmup import WarmupRequest
        from ..ops.event_batch import EventBatch

        with self._lock:
            if self._tick_combiner is None:
                return []
            records = [
                rec
                for rec in self._records.values()
                if not rec.needs_reset
                and (
                    rec.phase == _Phase.ACTIVE
                    or (
                        rec.phase == _Phase.SCHEDULED
                        and rec.job.schedule.start is None
                        and not rec.job.context_keys
                    )
                )
            ]
            shapes = dict(self._stream_batch_shapes)
        groups: dict[tuple, list] = {}
        for stream, padded in shapes.items():
            value = StagedEvents(
                batch=EventBatch(
                    pixel_id=np.full(padded, -1, dtype=np.int32),
                    toa=np.zeros(padded, dtype=np.float32),
                    n_valid=0,
                ),
                first_timestamp=None,
                last_timestamp=None,
                n_chunks=1,
            )
            for rec in records:
                if stream not in rec.job.subscribed_streams:
                    continue
                ingest_fn = getattr(rec.job.workflow, "event_ingest", None)
                if ingest_fn is None:
                    continue
                try:
                    offer = ingest_fn(stream, value)
                except Exception:
                    logger.exception(
                        "event_ingest failed during warm-up planning "
                        "for %s",
                        rec.job.job_id,
                    )
                    continue
                if offer is not None:
                    groups.setdefault((stream, offer.key), []).append(
                        (rec, offer)
                    )
        requests = []
        for (stream, key), members in groups.items():
            ingest0 = members[0][1]
            device = combiner = None
            if self._placement is not None:
                # Sticky-assignment PROBE only: state moves stay on the
                # step thread (``_group_placement``'s ensure_state_on),
                # exactly like the prestage path's probe.
                try:
                    plc = self._placement.assign(stream, key, ingest0.hist)
                    device, combiner = plc.device, plc.combiner
                except Exception:
                    logger.debug(
                        "warm-up placement probe failed", exc_info=True
                    )
            member_specs = []
            for rec, ingest in members:
                offer_fn = getattr(rec.job.workflow, "publish_offer", None)
                if offer_fn is None:
                    member_specs = None
                    break
                try:
                    offer = offer_fn()
                    if (
                        offer is None
                        or not offer.args
                        or offer.args[0] is not ingest.get_state()
                    ):
                        member_specs = None
                        break
                    sharding = (
                        None
                        if device is None
                        else _jax.sharding.SingleDeviceSharding(device)
                    )
                    args = _jax.tree_util.tree_map(
                        lambda a: _jax.ShapeDtypeStruct(
                            tuple(a.shape),
                            a.dtype,
                            **(
                                {}
                                if sharding is None
                                else {"sharding": sharding}
                            ),
                        ),
                        offer.args,
                    )
                except Exception:
                    logger.debug(
                        "warm-up offer capture failed for %s",
                        rec.job.job_id,
                        exc_info=True,
                    )
                    member_specs = None
                    break
                member_specs.append(
                    (offer.publisher, args, offer.static_token)
                )
            if not member_specs:
                # Not tick-eligible: this group dispatches separately on
                # the live path, where the fused-step/publish jits have
                # their own (per-K) caches — nothing to warm here.
                continue
            requests.append(
                WarmupRequest(
                    combiner=(
                        combiner
                        if combiner is not None
                        else self._tick_combiner
                    ),
                    hist=ingest0.hist,
                    group_key=key,
                    batch=ingest0.batch,
                    batch_tag=ingest0.batch_tag,
                    device=device,
                    members=member_specs,
                    trigger=trigger,
                )
            )
        return requests

    def handle_command(self, command: JobCommand) -> int:
        """Apply ``command``; return how many jobs it acted on.

        Zero for an unknown job is routine, not exceptional: every service
        sees the shared commands topic but owns a disjoint job set, and a
        non-owner must stay silent (the dispatcher acks only on count > 0).
        """
        removed: list[JobId] = []
        with self._lock:
            matched = [
                (jid, rec)
                for jid, rec in self._records.items()
                if command.matches(jid, rec.job.workflow_id)
            ]
            for jid, rec in matched:
                if command.action == "stop":
                    # Graceful: the job processes one more window and
                    # flushes a final result before leaving the active set.
                    rec.finishing = True
                elif command.action == "remove":
                    rec.phase = _Phase.STOPPED
                    del self._records[jid]
                    # Consumer detach: flush staged slots (ADR 0110).
                    self._event_cache.invalidate()
                    removed.append(jid)
                elif command.action == "reset":
                    self._reset_record(rec)
        # Outside the lock: observers reach foreign subsystems (the
        # fan-out tier's own hub lock) — never from inside ours.
        observer = self._retire_observer
        if observer is not None:
            for jid in removed:
                try:
                    observer(jid)
                except Exception:
                    logger.exception("retire observer failed for %s", jid)
        if removed:
            # A removal re-keys every group the job belonged to (member
            # tuple shrinks): warm the survivors' programs off the hot
            # path (ADR 0118).
            self._queue_warmup("regroup")
        return len(matched)

    def set_retire_observer(self, observer) -> None:
        """Attach a ``fn(job_id)`` called after each job removal — the
        serving plane drops the job's cached streams through this
        (ADR 0117)."""
        self._retire_observer = observer

    # -- run transitions ---------------------------------------------------
    def handle_run_transition(self, event: RunStart | RunStop) -> None:
        """Schedule deferred resets at the run boundary's data time."""
        with self._lock:
            if isinstance(event, RunStart):
                bisect.insort(self._pending_reset_times, event.start_time)
                if event.stop_time is not None:
                    bisect.insort(self._pending_reset_times, event.stop_time)
                logger.info(
                    "Run start %r: reset scheduled at %s",
                    event.run_name,
                    event.start_time,
                )
            else:
                bisect.insort(self._pending_reset_times, event.stop_time)
                logger.info(
                    "Run stop %r: reset scheduled at %s",
                    event.run_name,
                    event.stop_time,
                )

    def _fire_pending_resets(self, data_time: Timestamp) -> None:
        """Fire every scheduled reset that data time has now reached."""
        due = bisect.bisect_right(self._pending_reset_times, data_time)
        if not due:
            return
        del self._pending_reset_times[:due]
        if any(
            rec.job.reset_on_run_transition
            for rec in self._records.values()
        ):
            # Run-boundary staleness gate (ADR 0118): once any job's
            # accumulation resets at this boundary, every checkpoint
            # written before it must never restore — the marker is
            # persisted BEFORE the resets run, so a crash anywhere
            # after this line cannot resurrect old-run state.
            # graftlint: disable=JGL004 caller (process_jobs) holds self._lock
            self._reset_seq += 1
            if self._durability is not None:
                try:
                    self._durability.note_reset(self._reset_seq)
                except Exception:
                    logger.exception("reset-marker persist failed")
        for rec in self._records.values():
            if rec.job.reset_on_run_transition:
                # The run's final accumulation, captured before the reset
                # wipes it (SURVEY §5: snapshot at run boundaries). Goes
                # to the ARCHIVE key — restore never reads it, so a
                # finished run can't be resurrected into a later job.
                if rec.phase in (_Phase.ACTIVE, _Phase.PENDING_CONTEXT):
                    self._dump_snapshot(
                        rec, reason="run_boundary", archive=True
                    )
                self._reset_record(rec)

    def _reset_record(self, rec: _JobRecord) -> None:
        """Clear accumulation and retry/error state; phase is unchanged
        (context is sticky across run boundaries, so a gated job stays
        gated). A workflow whose clear() raises keeps its error recorded
        and does not take the other jobs' resets down with it; the record
        is flagged ``needs_reset`` and excluded from processing until a
        retry succeeds, so old-run and new-run data cannot mix."""
        try:
            rec.job.clear()
        except Exception as err:
            rec.needs_reset = True
            rec.error = f"Reset failed: {type(err).__name__}: {err}"
            logger.exception("Job %s failed clearing on reset", rec.job.job_id)
            return
        rec.needs_reset = False
        rec.has_primary_data = False
        rec.error = ""
        rec.warning = ""

    # -- phase machine -----------------------------------------------------
    def _advance_to_time(self, data_time: Timestamp) -> None:
        for rec in self._records.values():
            job = rec.job
            if rec.phase == _Phase.SCHEDULED:
                start = job.schedule.start
                if start is None or data_time >= start:
                    rec.phase = (
                        _Phase.PENDING_CONTEXT
                        if job.context_keys
                        else _Phase.ACTIVE
                    )
            if rec.phase in (_Phase.ACTIVE, _Phase.PENDING_CONTEXT):
                # A job still gated on context can also reach its end time
                # and must finish (reference :375-377).
                end = job.schedule.end
                if end is not None and data_time >= end:
                    rec.finishing = True

    def _open_context_gates(
        self, context: Mapping[str, Any]
    ) -> set[JobId]:
        """pending_context -> active once every needed context stream has a
        value (ADR 0002); still-gated jobs carry a warning naming what is
        missing, so the dashboard shows why nothing is produced.

        Returns the ids of jobs that graduated in this pass — they received
        the full cached context here and must not get a second (partial)
        delivery from the processing fan-out.
        """
        graduated: set[JobId] = set()
        for job_id, rec in self._records.items():
            if rec.phase != _Phase.PENDING_CONTEXT:
                continue
            missing = {k for k in rec.job.context_keys if k not in context}
            if missing:
                rec.warning = (
                    "Waiting for context streams: "
                    + ", ".join(sorted(missing))
                )
            else:
                # Contained per job: one workflow rejecting its context
                # must not abort the batch for every other job.
                try:
                    rec.job.set_context(context)
                except Exception as err:
                    rec.warning = (
                        f"Applying context failed: {type(err).__name__}: {err}"
                    )
                    logger.exception(
                        "Job %s failed applying gate context", job_id
                    )
                    continue
                rec.phase = _Phase.ACTIVE
                rec.warning = ""
                rec.stale_context.clear()
                graduated.add(job_id)
        return graduated

    # -- publish combining / coalescing (ADR 0113) -------------------------
    def set_publish_coalesce(self, n: int) -> None:
        """Retarget the publish-coalescing window (link policy): finalize
        runs only every ``n``th data window, so K windows' accumulation
        publishes in one device round trip on degraded-relay days.
        Finishing jobs and idle flushes always publish immediately."""
        with self._lock:
            self._publish_coalesce = max(1, int(n))

    def _run_combined_publish(self, due: list[_JobRecord]) -> set[int]:
        """Serve every due job's publish from one execute + one packed
        fetch per device (ADR 0113).

        Jobs whose workflows offer ``publish_offer`` are grouped by the
        device their state lives on; each group runs through the
        :class:`~..ops.publish.PublishCombiner` and the unpacked per-job
        trees are handed back via ``offer.consume`` — the subsequent
        ``job.get()`` then consumes the prefetched outputs instead of
        dispatching privately. Singletons ride the combiner too: in the
        manager-driven flow the workflow's private publish jit never
        compiles, so a K=1 program is the only compile either way, and
        routing it here gives every publish the same timing probe. Each
        group's execute+fetch wall time feeds the link monitor — the
        EWMA RTT behind the publish-coalescing policy is measured on
        the real device round trip, never on sink serialization.

        Containment mirrors the fused stepping layer: a member whose
        unpack failed still adopts its (valid) folded carry and
        republishes privately; a dispatch failure that consumed the
        donated buffers resets that member's state with a visible
        warning; everyone else is unaffected.

        Returns the ``id()`` set of the records served here (offer
        collected): their device round trip is already timed into the
        link monitor, so the finalize phase must not time them again —
        and conversely, records NOT in the set publish inside their
        finalize, which is where their round trip gets timed instead
        (sharded collective reads, ``combine_publish=False``)."""
        if self._publish_combiner is None:
            return set()
        from ..ops.publish import (
            PublishRequest,
            publish_args_consumed,
            publish_device,
        )

        offers = []
        for rec in due:
            offer_fn = getattr(rec.job.workflow, "publish_offer", None)
            if offer_fn is None:
                continue
            try:
                offer = offer_fn()
            except Exception:
                logger.exception(
                    "publish_offer failed for %s", rec.job.job_id
                )
                continue
            if offer is not None:
                offers.append((rec, offer))
        groups: dict[Any, list] = {}
        for rec, offer in offers:
            groups.setdefault(publish_device(offer.args), []).append(
                (rec, offer)
            )
        for members in groups.values():
            requests = [
                PublishRequest(o.publisher, o.args, o.static_token)
                for _, o in members
            ]
            t0 = time.perf_counter()
            try:
                results = self._publish_combiner.publish(requests)
            except Exception:
                # The combiner contains plan/dispatch/unpack failures
                # per member; anything escaping is a combiner bug — it
                # must degrade this group to private publishes, never
                # take the window (or the pipeline's step worker) down.
                logger.exception(
                    "combined publish failed (%d jobs); falling back to "
                    "per-job publishes",
                    len(members),
                )
                for rec, offer in members:
                    if publish_args_consumed(offer.args):
                        if offer.reset is not None:
                            offer.reset()
                        rec.job.note_state_lost()
                        rec.warning = (
                            "combined publish failed after buffer "
                            "donation; accumulation reset (see service "
                            "log)"
                        )
                        self._after_state_loss(rec)
                continue
            observer = self._link_observer
            # Compile rounds are one-off XLA work, not round trips —
            # feeding them would latch coalescing on every startup.
            if (
                observer is not None
                and not self._publish_combiner.last_compiled
                and any(res.error is None for res in results)
            ):
                try:
                    observer.observe_publish(time.perf_counter() - t0)
                except Exception:
                    logger.debug("link observer failed", exc_info=True)
            for (rec, offer), res in zip(members, results, strict=True):
                if res.error is not None:
                    if res.state_lost:
                        # Donation already invalidated the buffers: the
                        # pre-publish accumulation is unrecoverable in
                        # place. Rebuild a fresh state and surface the
                        # loss instead of erroring on a deleted array
                        # every publish from here on.
                        if offer.reset is not None:
                            offer.reset()
                        rec.job.note_state_lost()
                        rec.warning = (
                            "combined publish failed after buffer "
                            "donation; accumulation reset (see service "
                            "log)"
                        )
                        self._after_state_loss(rec)
                    elif res.carry:
                        # The fold already ran on device: adopt the new
                        # state so the job keeps a live buffer, and let
                        # finalize republish privately (this tick's
                        # window summaries read zero; the cumulative is
                        # intact).
                        try:
                            offer.consume(None, res.carry)
                        except Exception:
                            logger.exception(
                                "publish carry adoption failed for %s",
                                rec.job.job_id,
                            )
                    continue
                try:
                    offer.consume(res.outputs, res.carry)
                except Exception:
                    logger.exception(
                        "publish consume failed for %s", rec.job.job_id
                    )
        return {id(rec) for rec, _offer in offers}

    # -- one-dispatch tick programs (ops/tick.py, ADR 0114) ----------------
    def _split_tick_groups(
        self, work: list[tuple[_JobRecord, dict[str, Any]]], fuse_groups
    ) -> tuple[dict[tuple, list], list[tuple[tuple, Any, list]]]:
        """Partition the fused-step groups into tick-program groups —
        stepped AND published in one dispatch — and plain fused groups.

        A group rides the tick fast path only when EVERY member can:
        the member's window data is exactly the fused stream (any other
        stream would accumulate into the state AFTER the tick published
        it), the stream is primary (so the publish bookkeeping marks the
        record due and finalize consumes the prefetched tree — an
        aux-only window must never leave a stale prefetch behind), and
        the workflow's ``publish_offer`` names the SAME state object the
        ingest offer steps (the ``make_publish_offer`` args[0]/carry
        contract — verified by identity, so a bespoke offer that breaks
        it degrades to the separate-dispatch path instead of publishing
        the wrong buffers). Mixed groups stay whole on the fused path —
        splitting one would pay two dispatches for one group.

        Context ordering is inherited, not re-checked: ``fuse_groups``
        comes from ``_plan_fused_steps``, which already excludes any
        record with queued context (``rec.stale_context``) — so a
        window that carries a fresh geometry/position update never
        ticks, and the set_context-before-accumulate-before-publish
        contract holds on this path exactly as on the private one
        (pinned in tick_program_test.py).

        Unlike fused stepping, singleton groups DO tick: K=1 still
        collapses step + publish from two dispatches to one.
        """
        if self._tick_combiner is None:
            return fuse_groups, []
        data_keys = {id(rec): frozenset(jd) for rec, jd in work}
        rest: dict[tuple, list] = {}
        ticks: list[tuple[tuple, Any, list]] = []
        for group_key, members in fuse_groups.items():
            # Slice assignment happens BEFORE offers are collected: a
            # member whose state must move to its slice gets the moved
            # state captured in offer.args[0], keeping the identity
            # check below (and the tick program's donation layout)
            # honest. Assignment is sticky, so this is a metadata probe
            # on every tick after a group's first.
            plc = self._group_placement(group_key, members)
            enriched: list | None = []
            for rec, stream, value, ingest in members:
                if (
                    data_keys.get(id(rec)) != frozenset((stream,))
                    or stream not in rec.job.primary_streams
                ):
                    enriched = None
                    break
                offer_fn = getattr(rec.job.workflow, "publish_offer", None)
                if offer_fn is None:
                    enriched = None
                    break
                try:
                    offer = offer_fn()
                except Exception:
                    logger.exception(
                        "publish_offer failed for %s", rec.job.job_id
                    )
                    enriched = None
                    break
                if (
                    offer is None
                    or not offer.args
                    or offer.args[0] is not ingest.get_state()
                ):
                    enriched = None
                    break
                enriched.append((rec, stream, value, ingest, offer))
            if enriched:
                ticks.append((group_key, plc, enriched))
            else:
                rest[group_key] = members
        return rest, ticks

    def _group_placement(self, group_key: tuple, members: list):
        """The (sticky) mesh slice for one (stream, fuse-key) group —
        None without a placement policy. Member states are committed to
        a single-device slice here, before state identity is captured
        anywhere (publish offers, fused-step tuples); a move failure
        degrades the group to its current placement rather than taking
        the window down."""
        if self._placement is None:
            return None
        stream, key = group_key
        ingest0 = members[0][3]
        try:
            plc = self._placement.assign(stream, key, ingest0.hist)
            if plc.device is not None:
                for _rec, _strm, _value, ingest in members:
                    self._placement.ensure_state_on(ingest, plc.device)
            return plc
        except Exception:
            logger.exception(
                "slice placement failed for group %r", group_key
            )
            return None

    def _run_tick_programs(
        self, tick_groups: list[tuple[tuple, Any, list]]
    ) -> tuple[set[int], dict[JobId, set[str]]]:
        """Execute every ((stream, key), slice, members) tick group as
        ONE device dispatch + ONE fetch.

        Returns (served record ids, job_id -> streams accumulated
        out-of-band). Served records' publishes are complete — the
        combined-publish pass must skip them and finalize consumes their
        prefetched trees; the stream map feeds ``Job.add``'s
        ``skip_accumulate`` exactly like the fused-step map.

        Containment (mirrors ``_run_combined_publish`` +
        ``_run_fused_steps``): a staging failure drops the whole group
        to the separate-dispatch path (nothing was touched); a plan
        failure drops only that member; an unpack failure adopts the
        member's folded carry — the fold already ran on device, so the
        stream is still marked accumulated and finalize republishes
        privately; a dispatch failure after donation resets exactly the
        members whose buffers were consumed (``state_lost``), with a
        visible warning, and the private path re-adds THIS window's
        batch into the fresh state.

        Each group's execute+fetch wall time — the whole tick's device
        round trip — feeds the link monitor, with compile rounds
        excluded via ``TickCombiner.last_compiled`` (ADR 0113's
        mechanism, threaded through this path too so a first-tick
        compile cannot latch ``publish_coalesce`` spuriously).
        """
        served: set[int] = set()
        streams_done: dict[JobId, set[str]] = {}
        if not tick_groups:
            return served, streams_done
        from ..ops.publish import PublishRequest, publish_args_consumed

        for (stream, key), plc, members in tick_groups:
            _rec0, _stream0, value0, ingest0, _offer0 = members[0]
            try:
                staged = ingest0.stage(
                    value0.cache,
                    device=None if plc is None else plc.device,
                )
            except Exception:
                logger.exception(
                    "tick staging failed for stream %r (%d jobs); "
                    "falling back to separate dispatches",
                    stream,
                    len(members),
                )
                continue
            requests = [
                PublishRequest(o.publisher, o.args, o.static_token)
                for _rec, _strm, _value, _ingest, o in members
            ]
            # Mesh-spanning groups run through their slice's
            # MeshTickCombiner (replicated outputs, one fetch for the
            # whole mesh); single-device slices share the manager's
            # combiner — programs are keyed per (hist, group) anyway.
            combiner = self._tick_combiner
            slice_key = None
            if plc is not None:
                slice_key = plc.label
                if plc.combiner is not None:
                    combiner = plc.combiner
            t0 = time.perf_counter()
            try:
                results = combiner.publish(
                    ingest0.hist, key, staged, requests,
                    slice_key=slice_key,
                )
                if self._chaos is not None:
                    # Chaos site (ADR 0120): the dispatch RAN — donated
                    # member buffers are consumed — and then "fails".
                    # The containment below sees exactly what a real
                    # post-donation XLA failure produces: consumed args,
                    # no adoptable results, note_state_lost + re-seed.
                    self._chaos.check("tick_dispatch")
            except Exception:
                # The combiner contains plan/dispatch/unpack failures
                # per member; anything escaping is a combiner bug — it
                # must degrade this group to the separate path, never
                # take the window down. States a partial dispatch
                # already consumed are rebuilt with a visible warning.
                logger.exception(
                    "tick program failed (%d jobs); falling back to "
                    "separate dispatches",
                    len(members),
                )
                for rec, _strm, _value, _ingest, offer in members:
                    if publish_args_consumed(offer.args):
                        if offer.reset is not None:
                            offer.reset()
                        rec.job.note_state_lost()
                        rec.warning = (
                            "tick program failed after buffer donation; "
                            "accumulation reset (see service log)"
                        )
                        self._after_state_loss(rec)
                continue
            observer = self._link_observer
            # Compile rounds are one-off XLA work, not round trips —
            # feeding them would latch coalescing on every startup,
            # layout swap or wire flip (the combiner-path rule, threaded
            # through the tick path too). Slice-placed groups report
            # under their slice label so the policy reacts to the WORST
            # slice (ADR 0115).
            if (
                observer is not None
                and not combiner.last_compiled
                and any(res.error is None for res in results)
            ):
                self._observe_publish(
                    observer, time.perf_counter() - t0, slice_key
                )
            for (rec, strm, _value, _ingest, offer), res in zip(
                members, results, strict=True
            ):
                if res.error is not None:
                    if res.state_lost:
                        # Donation already invalidated the buffers: the
                        # pre-tick accumulation is unrecoverable in
                        # place. Rebuild a fresh state (the private
                        # fallback re-adds THIS window's batch) and
                        # surface the loss instead of stepping a
                        # deleted array forever.
                        if offer.reset is not None:
                            offer.reset()
                        rec.job.note_state_lost()
                        rec.warning = (
                            "tick program failed after buffer donation; "
                            "accumulation reset (see service log)"
                        )
                        self._after_state_loss(rec)
                    elif res.carry:
                        # The step+fold already ran on device: adopt the
                        # new state, mark the stream accumulated (a
                        # private re-add would double-count), and let
                        # finalize republish privately — this tick's
                        # window summaries read zero; the cumulative is
                        # intact.
                        try:
                            offer.consume(None, res.carry)
                            streams_done.setdefault(
                                rec.job.job_id, set()
                            ).add(strm)
                        except Exception:
                            logger.exception(
                                "tick carry adoption failed for %s",
                                rec.job.job_id,
                            )
                    # Plan-time error (no carry): state untouched — the
                    # member takes the full private accumulate + publish
                    # path this window.
                    continue
                try:
                    offer.consume(res.outputs, res.carry)
                except Exception:
                    logger.exception(
                        "tick consume failed for %s", rec.job.job_id
                    )
                    continue
                served.add(id(rec))
                streams_done.setdefault(rec.job.job_id, set()).add(strm)
        return served, streams_done

    @staticmethod
    def _observe_publish(observer, seconds: float, slice_key) -> None:
        """Feed one publish RTT sample, with the per-slice label when a
        placement is active. The observer slot is duck-typed (stub
        observers in tests take only ``seconds``), so the slice kwarg
        degrades to the sliceless call instead of losing the sample."""
        try:
            if slice_key is None:
                observer.observe_publish(seconds)
            else:
                try:
                    observer.observe_publish(seconds, slice_key=slice_key)
                except TypeError:
                    observer.observe_publish(seconds)
        except Exception:
            logger.debug("link observer failed", exc_info=True)

    # -- pipelined ingest (core/ingest_pipeline.py, ADR 0111) --------------
    def set_link_observer(self, observer) -> None:
        """Attach a LinkMonitor: every staging miss reports (bytes,
        wall seconds) through the stage-once cache, and every combined
        publish reports its execute+fetch round trip (ADR 0113) — both
        estimates come from real work, never probes."""
        self._event_cache.link_observer = observer
        self._link_observer = observer

    def open_window(self, data: Mapping[str, Any]):
        """Attach a fresh, caller-owned cache generation to this window's
        staged event values and return it.

        The pipelined ingest overlaps windows, so each in-flight window
        gets its own generation (window i+1 prestages while window i
        steps); the caller closes it after the window's publish. The
        serial path never calls this — ``process_jobs`` manages the
        cache-owned current generation itself.
        """
        generation = self._event_cache.new_generation()
        for name, value in data.items():
            if isinstance(value, StagedEvents):
                value.cache = generation.slot(name)
        return generation

    def prestage_window(
        self,
        data: Mapping[str, Any],
        *,
        pool=None,
        wire_compact: bool | None = None,
    ) -> None:
        """Warm the window's stream slots ahead of the job fan-out.

        Runs on the pipeline's stage worker: for every event stream, ask
        each subscribed active job's workflow for its ingest offer (the
        same duck-typed ``event_ingest`` the fused-stepping planner uses
        — offers are side-effect free) and run the offered histogrammer's
        staging into the window's slot. When the step stage later runs
        ``process_jobs``, workflows hit the warm slot and the host
        flatten/partition + transfer cost has already overlapped the
        previous window's step. Offers sharing a key stage once; streams
        without offers (workflows with no ``event_ingest``) simply stage
        at step time — prestaging is an overlap optimization, never a
        correctness dependency. Failures are contained per offer: the
        slot drops a poisoned entry, so the step stage retries privately.

        ``wire_compact`` (link policy, ADR 0108) applies the int32 vs
        uint16 partitioned-wire selection to each offered histogrammer
        before staging, so the whole window stages in one format.
        """
        with self._lock:
            # ACTIVE jobs, plus SCHEDULED ones with no start gate: the
            # phase machine activates those on this very window (data
            # time always reaches a None start), so their staging is
            # needed — skipping them would cold-start every first
            # window. Time- or context-gated jobs stay out: their
            # activation depends on data the stage worker doesn't have,
            # and a wrong guess is a wasted transfer.
            records = [
                rec
                for rec in self._records.values()
                if not rec.needs_reset
                and (
                    rec.phase == _Phase.ACTIVE
                    or (
                        rec.phase == _Phase.SCHEDULED
                        and rec.job.schedule.start is None
                        and not rec.job.context_keys
                    )
                )
            ]
        staged_keys: set[tuple] = set()
        wire_flipped = False
        for name, value in data.items():
            if not isinstance(value, StagedEvents) or value.cache is None:
                continue
            for rec in records:
                if name not in rec.job.subscribed_streams:
                    continue
                ingest_fn = getattr(rec.job.workflow, "event_ingest", None)
                if ingest_fn is None:
                    continue
                try:
                    offer = ingest_fn(name, value)
                except Exception:
                    logger.exception(
                        "event_ingest failed during prestage for %s",
                        rec.job.job_id,
                    )
                    continue
                if offer is None:
                    continue
                stage = getattr(offer.hist, "stage_events", None)
                if stage is None:
                    continue
                if wire_compact is not None:
                    set_wire = getattr(offer.hist, "set_wire_format", None)
                    if set_wire is not None and set_wire(wire_compact):
                        wire_flipped = True
                key = (name, offer.key)
                if key in staged_keys:
                    continue
                staged_keys.add(key)
                # Warm the SLICE's key when a placement is active: the
                # step path stages per-slice, so a default-device
                # prestage would miss. Assignment is sticky and pure
                # table lookup — state moves stay on the step thread
                # (the stage worker must never mutate workflow state).
                stage_kwargs = {}
                if self._placement is not None:
                    try:
                        plc = self._placement.assign(
                            name, offer.key, offer.hist
                        )
                        if plc.device is not None:
                            stage_kwargs["device"] = plc.device
                    except Exception:
                        logger.debug(
                            "prestage placement probe failed",
                            exc_info=True,
                        )
                try:
                    stage(
                        offer.batch,
                        value.cache,
                        batch_tag=offer.batch_tag,
                        pool=pool,
                        **stage_kwargs,
                    )
                except Exception:
                    logger.exception(
                        "Prestage failed for stream %r (job %s); "
                        "step-time staging will retry",
                        name,
                        rec.job.job_id,
                    )
        if wire_flipped:
            # The link policy just flipped the partitioned wire: every
            # pallas2d tick program re-keys on its next publish. Warm
            # the new-wire programs off the hot path (ADR 0118); the
            # race with the very next window is best-effort — losing it
            # costs exactly the compile the instrument reports today.
            self._queue_warmup("wire_flip")

    def peek_pending_streams(self) -> set[str]:
        """Context streams still gating some job (the processor uses this
        to know which context to enrich; reference :503)."""
        with self._lock:
            out: set[str] = set()
            for rec in self._records.values():
                if rec.phase in (_Phase.SCHEDULED, _Phase.PENDING_CONTEXT):
                    out |= rec.job.context_keys
                    out |= rec.job.optional_context_keys
            return out

    # -- processing --------------------------------------------------------
    def process_jobs(
        self,
        data: Mapping[str, Any],
        *,
        context: Mapping[str, Any] | None = None,
        fresh_context: set[str] | None = None,
        start: Timestamp | None = None,
        end: Timestamp | None = None,
        prestaged: bool = False,
    ) -> list[JobResult]:
        """One window: fire due resets, advance phases, open gates, fan
        per-job add over the thread pool, then serve every due job's
        publish from one combined device round trip per device and fan
        the finalize/serialization back out — per-job errors contained
        at every phase (ADR 0113). The publish-coalescing window
        (``set_publish_coalesce``) may skip the finalize phase entirely
        on intermediate windows; accumulation persists and flushes on
        the next publish tick.

        On publish ticks, fused-step groups whose every member is due
        take the tick-program fast path (ops/tick.py, ADR 0114): step
        AND publish ride one jitted dispatch + one fetch, so a
        steady-state tick is a single device round trip instead of the
        stage/step/publish triple. Groups that can't (extra streams in
        the window, no publish offer, ``tick_program=False``) keep the
        separate fused-step + combined-publish dispatches.

        ``prestaged`` marks a window whose staged-events values already
        carry slots from a caller-owned cache generation (the pipelined
        ingest: ``open_window`` + ``prestage_window`` ran on a stage
        worker). The cache-owned window lifecycle is skipped — the
        pipeline closes its generation after the window's publish, so an
        overlapped next window can never drop this one's staged arrays.

        ``fresh_context`` names the context streams that received data in
        THIS batch; active jobs get ``set_context`` only for those, so an
        unchanged cached motor position does not re-fire downstream
        recompute every window (reference avoids steady-state context
        refill for the same reason, :596-618). ``None`` means unknown —
        deliver everything (test shims).

        Per-job data is filtered to the streams the job subscribes to
        (reference ``_filter_data_for_job:726``): a job never sees — and
        never pays staging time for — another job's streams.
        """
        context = context or {}
        if self._chaos is not None:
            # Chaos site (ADR 0120): a slow-tick storm. BEFORE the
            # manager lock — the injected stall models slow device/host
            # work, not a lock convoy (and a sleep under the lock would
            # stall scrape-time collectors, the JGL023 class).
            self._chaos.maybe_delay("slow_tick")
        with self._lock:
            # Warm-up shape memory (ADR 0118): the padded batch size
            # each stream carries is the staged-signature dimension of
            # every tick-program key — commit-time warm-up compiles
            # against the shape the stream is actually running at.
            for name, value in data.items():
                if isinstance(value, StagedEvents):
                    self._stream_batch_shapes[name] = (
                        value.batch.padded_size
                    )
            if not prestaged:
                # New window generation: previous staged slots drop, and
                # this window's event batches get stream slots so every
                # consumer — workflow-private stepping and the fused
                # layer alike — stages each batch once per (stream,
                # layout).
                self._event_cache.begin_window()
                for name, value in data.items():
                    if isinstance(value, StagedEvents):
                        value.cache = self._event_cache.slot(name)
            if end is not None:
                self._fire_pending_resets(end)
                self._advance_to_time(end)
            graduated = self._open_context_gates(context)
            # Queue fresh context for later delivery. None = unknown
            # freshness (test shims): queue everything, restoring
            # every-window delivery.
            queued = set(context) if fresh_context is None else fresh_context
            if queued:
                for job_id, rec in self._records.items():
                    if rec.phase == _Phase.ACTIVE and job_id not in graduated:
                        rec.stale_context |= queued & (
                            rec.job.context_keys
                            | rec.job.optional_context_keys
                        )
            work: list[tuple[_JobRecord, dict[str, Any]]] = []
            for rec in self._records.values():
                if rec.phase != _Phase.ACTIVE:
                    continue
                if rec.needs_reset:
                    # Retry the failed run-transition reset; until it
                    # succeeds the job must not accumulate (old-run data
                    # is still in the workflow).
                    self._reset_record(rec)
                    if rec.needs_reset:
                        continue
                job_data = {
                    k: v
                    for k, v in data.items()
                    if k in rec.job.subscribed_streams
                }
                # Skip jobs with nothing to do: no fresh data and nothing
                # pending finalize. A finishing job is still ACTIVE here —
                # it leaves only after this pass — so the window that
                # carried it past its end time is flushed before stopping.
                # (Queued context survives the skip and is delivered before
                # the job's next add.)
                if job_data or rec.has_primary_data:
                    work.append((rec, job_data))
            fuse_groups = self._plan_fused_steps(work)
            if self._fleet is not None:
                work, fuse_groups = self._apply_fleet_filter(
                    work, fuse_groups
                )
            # Publish-coalescing gate (ADR 0113): on a widened tick,
            # accumulation still runs every window but finalize (the
            # device round trip) only fires every Nth — idle flushes
            # (no data: a stop must complete) always publish, and a
            # finishing job forces the tick below.
            self._window_seq += 1
            coalesce = max(1, self._publish_coalesce)
            publish_now = (
                coalesce <= 1
                or not data
                or self._window_seq % coalesce == 0
            )

        # Tick fast path (outside the lock, same as the fan-out): on a
        # publish tick, groups whose every member is due step AND
        # publish in ONE dispatch (ops/tick.py, ADR 0114). Remaining
        # groups of >= 2 jobs sharing a (stream, fuse-key) advance all
        # their states in ONE fused dispatch from ONE cached staging.
        tick_served: set[int] = set()
        tick_streams: dict[JobId, set[str]] = {}
        if publish_now and self._tick_combiner is not None:
            fuse_groups, tick_groups = self._split_tick_groups(
                work, fuse_groups
            )
            tick_served, tick_streams = self._run_tick_programs(tick_groups)
        fused_streams = self._run_fused_steps(fuse_groups)
        for job_id, streams in tick_streams.items():
            fused_streams.setdefault(job_id, set()).update(streams)

        def run_accumulate(item: tuple[_JobRecord, dict[str, Any]]) -> None:
            rec, job_data = item
            skip_streams = fused_streams.get(rec.job.job_id, frozenset())
            job = rec.job
            # Deliver pending context in its own try: a failure keeps the
            # names queued (retried next window) and does not block this
            # window's accumulation.
            context_warning = ""
            if rec.stale_context:
                # Only the names actually present in this window's context
                # are delivered (and de-queued on success); the rest stay
                # queued for a later window rather than being dropped.
                deliverable = {
                    k for k in rec.stale_context if k in context
                }
                try:
                    if deliverable:
                        job.set_context(
                            {k: context[k] for k in deliverable}
                        )
                    rec.stale_context -= deliverable
                except Exception as err:
                    context_warning = f"{type(err).__name__}: {err}"
                    logger.exception(
                        "Job %s failed applying context", job.job_id
                    )
            # Accumulate: a failure here is a warning — the job may still
            # be able to finalize previously accumulated data. A successful
            # add must not mask an unresolved context failure.
            try:
                touched = job.add(
                    job_data,
                    start=start,
                    end=end,
                    skip_accumulate=skip_streams,
                )
                if touched and any(k in job_data for k in job.primary_streams):
                    rec.has_primary_data = True
                rec.warning = context_warning
            except Exception as err:
                rec.warning = f"{type(err).__name__}: {err}"
                logger.exception("Job %s failed accumulating", job.job_id)

        if self._executor is not None and len(work) > 1:
            list(self._executor.map(run_accumulate, work))
        else:
            for item in work:
                run_accumulate(item)

        # Every accumulated state is final for this window: jobs due a
        # publish (fresh or coalesced-over primary data) finalize below,
        # prefetched through ONE combined device round trip per device.
        due = [rec for rec, _ in work if rec.has_primary_data]
        if due and not publish_now and any(rec.finishing for rec in due):
            # A stop's final flush must not wait out the coalescing
            # window (beam-off could stall it indefinitely).
            publish_now = True

        def run_finalize(rec: _JobRecord) -> JobResult | None:
            # Finalize: a failure here is an error; has_primary_data stays
            # set so the next window retries.
            try:
                t0 = time.perf_counter()
                result = rec.job.get()
                if id(rec) not in served:
                    # Offer-less publish (sharded collective reads,
                    # combining disabled): the device fetch happens
                    # inside finalize, so time it here — the RTT axes
                    # must never go dark for these deployments. The
                    # record's FIRST offer-less finalize is skipped: it
                    # may compile the private publish program (also
                    # after ticks of combined serving — the private jit
                    # never compiled there), and a compile sample would
                    # latch coalescing on a healthy link.
                    observer = self._link_observer
                    if rec.publish_timed and observer is not None:
                        try:
                            observer.observe_publish(
                                time.perf_counter() - t0
                            )
                        except Exception:
                            logger.debug(
                                "link observer failed", exc_info=True
                            )
                    rec.publish_timed = True
                rec.error = ""
                rec.has_primary_data = False
                if rec.job.none_outputs:
                    rec.warning = (
                        "outputs returned None: "
                        + ", ".join(rec.job.none_outputs)
                    )
                return result
            except Exception as err:
                rec.error = f"{type(err).__name__}: {err}"
                logger.exception("Job %s failed finalizing", rec.job.job_id)
                return None

        results: list[JobResult | None] = []
        if due and publish_now:
            # Tick-served records already published inside their tick
            # program; combining them again would dispatch a second
            # publish over the already-folded state.
            served = tick_served | self._run_combined_publish(
                [rec for rec in due if id(rec) not in tick_served]
            )
            # One finalize span per window (ADR 0116), recorded from
            # THIS thread (the step worker carries the window's bound
            # trace id; the pool threads inside wouldn't).
            with TRACER.span("finalize"):
                if self._executor is not None and len(due) > 1:
                    results = list(self._executor.map(run_finalize, due))
                else:
                    results = [run_finalize(rec) for rec in due]

        with self._lock:
            for rec in list(self._records.values()):
                if rec.finishing and rec.phase in (
                    _Phase.ACTIVE,
                    _Phase.PENDING_CONTEXT,
                    # A job stopped before it ever activated (beam-off:
                    # nothing advanced it out of SCHEDULED) has nothing
                    # to flush — it must still complete its stop.
                    _Phase.SCHEDULED,
                ):
                    rec.phase = _Phase.STOPPED
                    # The final window just flushed above: free the
                    # device-resident accumulator now instead of pinning
                    # it until an operator removes the stopped record.
                    rec.job.release()
        if not prestaged:
            # Drop this window's staged references: device memory frees
            # once the last in-flight kernel completes, and next window's
            # batches can never alias a stale generation. (Pipelined
            # windows: the pipeline closes its own generation after the
            # publish instead.)
            self._event_cache.end_window()
        return [r for r in results if r is not None]

    # graft: protocol=fleet (ADR 0124: the per-group owns() consult
    # below is the modeled filter of the single-owner invariant)
    def _apply_fleet_filter(
        self,
        work: list[tuple["_JobRecord", dict[str, Any]]],
        fuse_groups: dict[tuple, list],
    ) -> tuple[list, dict[tuple, list]]:
        """Drop the groups a peer replica owns (ADR 0121; caller holds
        the manager lock).

        Ownership is decided at GROUP granularity: a job riding a fused
        group follows its ``(stream, fuse-key)`` rendezvous hash — the
        exact key ADR 0115 places on mesh slices — and an ungrouped job
        follows its primary stream with a None fuse tag. A filtered job
        keeps an EMPTY work entry when it has accumulation pending
        (``has_primary_data``): a group that just moved away must still
        flush what this replica already folded in, which is what makes
        a rebalance a drain + replay instead of data loss."""
        fleet = self._fleet
        member_owned: dict[tuple[int, str], bool] = {}
        kept_groups: dict[tuple, list] = {}
        for (stream, fkey), members in fuse_groups.items():
            owned = fleet.owns(stream, fkey)
            if owned:
                kept_groups[(stream, fkey)] = members
            for rec, member_stream, _value, _offer in members:
                member_owned[(id(rec), member_stream)] = owned
        new_work: list[tuple[_JobRecord, dict[str, Any]]] = []
        for rec, job_data in work:
            grouped = [
                s for s in job_data if (id(rec), s) in member_owned
            ]
            if grouped:
                owned = any(
                    member_owned[(id(rec), s)] for s in grouped
                )
            elif job_data:
                # Ungrouped work keys by the job's FIXED anchor stream
                # — its first declared primary (or, for primary-less
                # jobs, its first subscribed stream) — NOT whichever
                # streams happened to arrive this window: a window
                # carrying only auxiliary data must land on the same
                # replica as every other window of the job, or the
                # partition stops being sticky and aux updates
                # accumulate on an orphan copy.
                anchor = sorted(
                    rec.job.primary_streams
                    or rec.job.subscribed_streams
                )
                owned = (
                    fleet.owns(anchor[0], None) if anchor else True
                )
            else:
                owned = True  # pure flush entry: always local
            if owned:
                new_work.append((rec, job_data))
            elif rec.has_primary_data:
                new_work.append((rec, {}))
        return new_work, kept_groups

    def _plan_fused_steps(
        self, work: list[tuple[_JobRecord, dict[str, Any]]]
    ) -> dict[tuple, list]:
        """Group fusable (job, stream, staged) offers by (stream, fuse key).

        A job is eligible when it has no queued context (fused stepping
        runs before the per-job context delivery in ``run_one``, so a
        pending position/geometry update must keep the job on the private
        path this window to preserve context-before-accumulate ordering)
        and its workflow offers an ``event_ingest`` for the value. At most
        one stream fuses per job per window — a second StagedEvents value
        on the same workflow would race its own state capture.
        """
        groups: dict[tuple, list] = {}
        for rec, job_data in work:
            if rec.stale_context:
                continue
            ingest_fn = getattr(rec.job.workflow, "event_ingest", None)
            if ingest_fn is None:
                continue
            for stream, value in job_data.items():
                if not isinstance(value, StagedEvents):
                    continue
                try:
                    offer = ingest_fn(stream, value)
                except Exception:
                    logger.exception(
                        "event_ingest failed for %s", rec.job.job_id
                    )
                    offer = None
                if offer is None:
                    continue
                groups.setdefault((stream, offer.key), []).append(
                    (rec, stream, value, offer)
                )
                break
        return groups

    def _run_fused_steps(
        self, groups: dict[tuple, list]
    ) -> dict[JobId, set[str]]:
        """Execute every group of >= 2 offers with one fused dispatch.

        Returns job_id -> streams accumulated out-of-band (``Job.add``
        skips them). Failure containment: a group whose fused step raises
        at TRACE time (buffers untouched) is logged and left to the
        private per-job path — state setters only run after a successful
        dispatch, so nothing half-applies and the fallback cannot
        double-count. A RUNTIME failure (e.g. HBM OOM allocating the K
        fused outputs) is harder: ``step_many`` donates every state, so
        the old buffers may already be invalidated — each member whose
        state was consumed gets a fresh zeroed state and a visible
        warning instead of stepping a deleted array forever. Singleton
        groups stay private: a K=1 fused program would compile a second
        identical kernel for no dispatch saving.
        """
        from ..ops.publish import METRICS

        fused: dict[JobId, set[str]] = {}
        for (stream, _key), members in groups.items():
            if len(members) < 2:
                continue
            rec0, _stream0, value0, offer0 = members[0]
            # Same sticky slice as the tick path (coalesced windows run
            # here; a group must not alternate devices between publish
            # and non-publish windows — that would re-stage the wire
            # and re-commit every state per window).
            plc = self._group_placement((stream, _key), members)
            device = None if plc is None else plc.device
            # device is None for un-placed groups AND for bespoke
            # histogrammers the placement pinned to the default slice
            # (DevicePlacement probes for device-aware staging), so the
            # kwarg is only ever forwarded to implementations that
            # accept it.
            step_kwargs = {} if device is None else {"device": device}
            states = tuple(m[3].get_state() for m in members)
            try:
                new_states = offer0.hist.step_many(
                    states,
                    offer0.batch,
                    cache=value0.cache,
                    batch_tag=offer0.batch_tag,
                    **step_kwargs,
                )
                # One separate step dispatch (the tick program folds
                # this into the publish execute instead): the bench
                # ``--tick`` dispatch-count decomposition reads it.
                METRICS.record(step_executes=1)
            except Exception:
                logger.exception(
                    "Fused step failed for stream %r (%d jobs); "
                    "falling back to per-job accumulation",
                    stream,
                    len(members),
                )
                for (rec, _strm, _value, offer), state in zip(
                    members, states, strict=True
                ):
                    if self._state_consumed(state):
                        # Donation already invalidated the buffers: the
                        # pre-step accumulation is unrecoverable in
                        # place. Reset to a fresh state (the private
                        # fallback then re-adds THIS window's batch) and
                        # surface the loss instead of erroring on a
                        # deleted array every window from here on.
                        offer.set_state(offer.hist.init_state())
                        rec.job.note_state_lost()
                        rec.warning = (
                            "fused step failed after buffer donation; "
                            "accumulation reset (see service log)"
                        )
                        self._after_state_loss(rec)
                continue
            for (rec, strm, _value, offer), new_state in zip(
                members, new_states, strict=True
            ):
                offer.set_state(new_state)
                fused.setdefault(rec.job.job_id, set()).add(strm)
        return fused

    @staticmethod
    def _state_consumed(state) -> bool:
        """True when any leaf buffer of a (donated) state pytree has been
        invalidated by a dispatch that subsequently failed."""
        for leaf in state:
            deleted = getattr(leaf, "is_deleted", None)
            try:
                if deleted is not None and deleted():
                    return True
            except Exception:  # pragma: no cover - defensive
                return True
        return False

    def event_cache_stats(self) -> dict[str, int | float]:
        """Stage-once cache counters since the last metrics drain
        (hits/misses/bytes_staged/hit_rate) — the 30 s metrics line and
        the multi-job bench read these."""
        return self._event_cache.drain_stats()

    def event_cache_cumulative_stats(self) -> dict[str, int | float]:
        """Monotone stage-once cache totals since construction — the
        telemetry collector's read (ADR 0116), independent of the 30 s
        drain above."""
        return self._event_cache.cumulative_stats()

    # -- introspection -----------------------------------------------------
    def has_finishing_jobs(self) -> bool:
        """True while any job awaits its final flush — the processor runs
        an empty window on idle ticks so stops complete without beam.
        Already-stopped records keep their ``finishing`` flag but need
        nothing further."""
        with self._lock:
            return any(
                rec.finishing and rec.phase is not _Phase.STOPPED
                for rec in self._records.values()
            )

    def job_statuses(self) -> list[JobStatus]:
        with self._lock:
            return [
                JobStatus(
                    source_name=jid.source_name,
                    job_number=jid.job_number,
                    workflow_id=str(rec.job.workflow_id),
                    state=rec.state,
                    message=rec.error or rec.warning,
                    has_primary_data=rec.has_primary_data,
                    params=rec.job.params,
                )
                for jid, rec in self._records.items()
            ]

    @property
    def n_jobs(self) -> int:
        with self._lock:
            return len(self._records)

    def subscribed_streams(self) -> set[str]:
        with self._lock:
            out: set[str] = set()
            for rec in self._records.values():
                out |= rec.job.subscribed_streams
            return out

    def shutdown(self) -> None:
        # Crash-recovery dump: a restarted service restores mid-run
        # accumulation instead of starting from zero.
        self.dump_snapshots(reason="shutdown")
        if self._executor is not None:
            self._executor.shutdown(wait=False)

"""Job lifecycle and scheduling.

Parity with reference ``core/job_manager.py``: JobFactory.create:140 (eager
workflow build at schedule time — startup cost paid at the command, not in
the hot loop), phase machine scheduled -> pending_context -> active with a
finishing overlay (:223), data-time-driven activation (_advance_to_time:357),
context gating per ADR 0002 (_open_context_gates:599), run-transition resets
(:486-501), thread-pool fan-out of per-job work (:560,690) and per-job
error/warning containment instead of service death (:640-682).

TPU note on the fan-out: device kernels serialize on the chip anyway, so
threads only overlap the *host-side* staging/finalize portions — the
default thread count stays modest (reference default 5).
"""

from __future__ import annotations

import logging
import threading
import uuid
from collections.abc import Mapping
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field
from enum import StrEnum
from typing import Any, Literal

from pydantic import BaseModel

from ..config.workflow_spec import JobId, WorkflowConfig
from ..workflows.workflow_factory import WorkflowFactory, workflow_registry
from .job import Job, JobResult, JobState, JobStatus
from .message import RunStart, RunStop
from .timestamp import Timestamp

__all__ = ["JobCommand", "JobFactory", "JobManager"]

logger = logging.getLogger(__name__)


class JobCommand(BaseModel):
    """stop/remove/reset command from the dashboard (reference :67)."""

    action: Literal["stop", "remove", "reset"]
    source_name: str
    job_number: uuid.UUID


class JobFactory:
    """Builds Jobs from start commands via the workflow registry."""

    def __init__(self, registry: WorkflowFactory | None = None) -> None:
        self._registry = registry if registry is not None else workflow_registry

    def create(self, config: WorkflowConfig) -> Job:
        spec = self._registry[config.identifier]
        workflow = self._registry.create(config)
        aux = set(config.aux_source_names.values())
        return Job(
            job_id=config.job_id,
            workflow_id=config.identifier,
            workflow=workflow,
            schedule=config.schedule,
            primary_streams={config.job_id.source_name},
            aux_streams=aux,
            context_keys=set(spec.context_keys),
            reset_on_run_transition=spec.reset_on_run_transition,
        )


class _Phase(StrEnum):
    SCHEDULED = "scheduled"
    PENDING_CONTEXT = "pending_context"
    ACTIVE = "active"
    STOPPED = "stopped"


@dataclass
class _JobRecord:
    job: Job
    phase: _Phase = _Phase.SCHEDULED
    finishing: bool = False
    error: str = ""
    warning: str = ""
    has_primary_data: bool = False
    pending_reset: bool = False

    @property
    def state(self) -> JobState:
        if self.error:
            return JobState.ERROR
        if self.phase == _Phase.STOPPED:
            return JobState.STOPPED
        if self.finishing:
            return JobState.FINISHING
        if self.warning:
            return JobState.WARNING
        return JobState(self.phase.value)


class JobManager:
    """Keeps the job table; drives activation, gating, processing, resets."""

    def __init__(
        self,
        *,
        job_factory: JobFactory | None = None,
        job_threads: int = 5,
    ) -> None:
        self._factory = job_factory or JobFactory()
        self._records: dict[JobId, _JobRecord] = {}
        self._lock = threading.RLock()
        self._executor = (
            ThreadPoolExecutor(max_workers=job_threads, thread_name_prefix="job")
            if job_threads > 1
            else None
        )

    # -- scheduling --------------------------------------------------------
    def schedule_job(self, config: WorkflowConfig) -> JobId:
        """Create + register a job. The workflow builds eagerly here so
        compile/LUT cost lands at command time, not in the data path."""
        with self._lock:
            if config.job_id in self._records:
                raise ValueError(f"Job {config.job_id} already exists")
            job = self._factory.create(config)
            self._records[config.job_id] = _JobRecord(job=job)
            logger.info("Scheduled job %s (%s)", config.job_id, config.identifier)
            return config.job_id

    def handle_command(self, command: JobCommand) -> None:
        job_id = JobId(
            source_name=command.source_name, job_number=command.job_number
        )
        with self._lock:
            rec = self._records.get(job_id)
            if rec is None:
                raise KeyError(f"Unknown job {job_id}")
            if command.action == "stop":
                rec.finishing = True
            elif command.action == "remove":
                rec.phase = _Phase.STOPPED
                del self._records[job_id]
            elif command.action == "reset":
                rec.job.clear()
                rec.has_primary_data = False
                rec.error = ""

    # -- run transitions ---------------------------------------------------
    def handle_run_transition(self, event: RunStart | RunStop) -> None:
        """RunStart resets accumulated state of opted-in jobs (reference
        deferred reset semantics :486-501 — here applied at the next batch
        boundary via pending_reset, preserving the data-time ordering)."""
        if isinstance(event, RunStart):
            with self._lock:
                for rec in self._records.values():
                    if rec.job.reset_on_run_transition:
                        rec.pending_reset = True
            logger.info("Run start %r: queued resets", event.run_name)

    # -- phase machine -----------------------------------------------------
    def _advance_to_time(self, data_time: Timestamp) -> None:
        for rec in self._records.values():
            job = rec.job
            if rec.phase == _Phase.SCHEDULED:
                start = job.schedule.start
                if start is None or data_time >= start:
                    rec.phase = (
                        _Phase.PENDING_CONTEXT
                        if job.context_keys
                        else _Phase.ACTIVE
                    )
            if rec.phase == _Phase.ACTIVE:
                end = job.schedule.end
                if end is not None and data_time >= end:
                    rec.finishing = True

    def _open_context_gates(self, context: Mapping[str, Any]) -> None:
        """pending_context -> active once every needed context stream has a
        value (ADR 0002)."""
        for rec in self._records.values():
            if rec.phase != _Phase.PENDING_CONTEXT:
                continue
            if all(k in context for k in rec.job.context_keys):
                rec.job.set_context(context)
                rec.phase = _Phase.ACTIVE

    def peek_pending_streams(self) -> set[str]:
        """Context streams still gating some job (the processor uses this
        to know which context to enrich; reference :503)."""
        with self._lock:
            out: set[str] = set()
            for rec in self._records.values():
                if rec.phase in (_Phase.SCHEDULED, _Phase.PENDING_CONTEXT):
                    out |= rec.job.context_keys
            return out

    # -- processing --------------------------------------------------------
    def process_jobs(
        self,
        data: Mapping[str, Any],
        *,
        context: Mapping[str, Any] | None = None,
        start: Timestamp | None = None,
        end: Timestamp | None = None,
    ) -> list[JobResult]:
        """One window: advance phases, open gates, fan per-job add+finalize
        over the thread pool, contain per-job errors."""
        context = context or {}
        with self._lock:
            if end is not None:
                self._advance_to_time(end)
            self._open_context_gates(context)
            active = [
                rec
                for rec in self._records.values()
                if rec.phase == _Phase.ACTIVE
            ]

        def run_one(rec: _JobRecord) -> JobResult | None:
            job = rec.job
            try:
                if rec.pending_reset:
                    job.clear()
                    rec.pending_reset = False
                    rec.has_primary_data = False
                job.set_context(context)
                touched = job.add(data, start=start, end=end)
                if touched and any(
                    k in data for k in job.primary_streams
                ):
                    rec.has_primary_data = True
                if not rec.has_primary_data:
                    return None
                result = job.get()
                rec.warning = ""
                return result
            except Exception as err:
                rec.error = f"{type(err).__name__}: {err}"
                logger.exception("Job %s failed", job.job_id)
                return None

        if self._executor is not None and len(active) > 1:
            results = list(self._executor.map(run_one, active))
        else:
            results = [run_one(rec) for rec in active]

        with self._lock:
            for rec in list(self._records.values()):
                if rec.finishing and rec.phase == _Phase.ACTIVE:
                    rec.phase = _Phase.STOPPED
        return [r for r in results if r is not None]

    # -- introspection -----------------------------------------------------
    def job_statuses(self) -> list[JobStatus]:
        with self._lock:
            return [
                JobStatus(
                    source_name=jid.source_name,
                    job_number=jid.job_number,
                    workflow_id=str(rec.job.workflow_id),
                    state=rec.state,
                    message=rec.error or rec.warning,
                    has_primary_data=rec.has_primary_data,
                )
                for jid, rec in self._records.items()
            ]

    @property
    def n_jobs(self) -> int:
        with self._lock:
            return len(self._records)

    def subscribed_streams(self) -> set[str]:
        with self._lock:
            out: set[str] = set()
            for rec in self._records.values():
                out |= rec.job.subscribed_streams
            return out

    def shutdown(self) -> None:
        if self._executor is not None:
            self._executor.shutdown(wait=False)

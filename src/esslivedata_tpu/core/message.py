"""Domain message model: stream identities, messages, source/sink protocols.

Parity with reference ``core/message.py`` (StreamKind:17, StreamId:35,
Message:70, RunStart:47/RunStop:59, MessageSource:95/MessageSink:100).
"""

from __future__ import annotations

from collections.abc import Sequence
from dataclasses import dataclass, field
from enum import StrEnum
from typing import Generic, Protocol, TypeVar, runtime_checkable

from .timestamp import Timestamp

T = TypeVar("T")
Tin = TypeVar("Tin")
Tout = TypeVar("Tout")

__all__ = [
    "Message",
    "MessageSink",
    "MessageSource",
    "RunStart",
    "RunStop",
    "StreamId",
    "StreamKind",
]


class StreamKind(StrEnum):
    """Kinds of streams flowing through a service (13 kinds, matching the
    reference so stream routing tables translate one-to-one)."""

    UNKNOWN = "unknown"
    MONITOR_COUNTS = "monitor_counts"
    MONITOR_EVENTS = "monitor_events"
    DETECTOR_EVENTS = "detector_events"
    AREA_DETECTOR = "area_detector"
    LOG = "log"
    DEVICE = "device"
    LIVEDATA_COMMANDS = "livedata_commands"
    LIVEDATA_RESPONSES = "livedata_responses"
    LIVEDATA_DATA = "livedata_data"
    LIVEDATA_NICOS_DATA = "livedata_nicos_data"
    LIVEDATA_ROI = "livedata_roi"
    LIVEDATA_STATUS = "livedata_status"
    RUN_CONTROL = "run_control"


@dataclass(frozen=True, slots=True, kw_only=True)
class StreamId:
    kind: StreamKind = StreamKind.UNKNOWN
    name: str


COMMANDS_STREAM_ID = StreamId(kind=StreamKind.LIVEDATA_COMMANDS, name="")
RESPONSES_STREAM_ID = StreamId(kind=StreamKind.LIVEDATA_RESPONSES, name="")
STATUS_STREAM_ID = StreamId(kind=StreamKind.LIVEDATA_STATUS, name="")
RUN_CONTROL_STREAM_ID = StreamId(kind=StreamKind.RUN_CONTROL, name="")


@dataclass(frozen=True, slots=True)
class RunStart:
    """Run start event from the facility control system (pl72 wire schema)."""

    run_name: str
    start_time: Timestamp
    stop_time: Timestamp | None = None


@dataclass(frozen=True, slots=True)
class RunStop:
    """Run stop event from the facility control system (6s4t wire schema)."""

    run_name: str
    stop_time: Timestamp


@dataclass(frozen=True, slots=True, kw_only=True)
class Message(Generic[T]):
    """A timestamped value on a stream. For data-plane messages ``timestamp``
    is data time (when the data was produced at the source) and must be set
    explicitly from the wire payload; the wall-clock default exists for
    control-plane messages (commands, acks, statuses) created in-process,
    matching the reference (core/message.py:70)."""

    timestamp: Timestamp = field(default_factory=Timestamp.now)
    stream: StreamId
    value: T

    def __lt__(self, other: "Message[T]") -> bool:
        return self.timestamp < other.timestamp


@runtime_checkable
class MessageSource(Protocol, Generic[Tin]):
    def get_messages(self) -> Sequence[Tin]: ...


@runtime_checkable
class MessageSink(Protocol, Generic[Tout]):
    def publish_messages(self, messages: Sequence[Message[Tout]]) -> None: ...

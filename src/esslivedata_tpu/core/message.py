"""Stream identities and the timestamped message envelope.

Everything that flows through a service — raw facility data, synthesized
streams, commands, acks, statuses, results — is a ``Message`` carrying a
``StreamId``. The envelope is deliberately tiny: routing decisions read
only ``stream``, batching decisions read only ``timestamp``, and the
payload type is opaque to both.

Behavioral parity with reference ``core/message.py`` (the 13 wire stream
kinds, nameless control-plane stream ids, data-time message ordering);
expression is this codebase's own.
"""

from __future__ import annotations

from collections.abc import Sequence
from dataclasses import dataclass, field
from typing import Generic, Protocol, TypeVar, runtime_checkable

from ..utils.compat import StrEnum
from .timestamp import Timestamp

PayloadT = TypeVar("PayloadT")
ItemT = TypeVar("ItemT")
OutT = TypeVar("OutT")

__all__ = [
    "COMMAND_STREAM",
    "Message",
    "MessageSink",
    "MessageSource",
    "RESPONSE_STREAM",
    "RUN_CONTROL_STREAM",
    "RunStart",
    "RunStop",
    "STATUS_STREAM",
    "StreamId",
    "StreamKind",
]


class StreamKind(StrEnum):
    """The kinds of streams a service consumes or produces.

    The string values are wire-contract: they appear in routing tables and
    serialized stream names, and match the reference's vocabulary so that
    deployments can mix both implementations on the same topics.
    """

    UNKNOWN = "unknown"

    # Raw facility streams (consumed).
    MONITOR_COUNTS = "monitor_counts"
    MONITOR_EVENTS = "monitor_events"
    DETECTOR_EVENTS = "detector_events"
    AREA_DETECTOR = "area_detector"
    LOG = "log"
    RUN_CONTROL = "run_control"

    # Synthesized in-process (ADR 0001).
    DEVICE = "device"

    # Livedata control plane and outputs (produced, and consumed by the
    # dashboard).
    LIVEDATA_COMMANDS = "livedata_commands"
    LIVEDATA_RESPONSES = "livedata_responses"
    LIVEDATA_DATA = "livedata_data"
    LIVEDATA_NICOS_DATA = "livedata_nicos_data"
    LIVEDATA_ROI = "livedata_roi"
    LIVEDATA_STATUS = "livedata_status"

    @property
    def is_command(self) -> bool:
        """Dispatched to the command handler, never batched as data."""
        return self is StreamKind.LIVEDATA_COMMANDS

    @property
    def is_run_control(self) -> bool:
        """Run start/stop transitions; handled before data batching."""
        return self is StreamKind.RUN_CONTROL

    @property
    def is_data(self) -> bool:
        """Everything the batcher and preprocessors may see."""
        return not (self.is_command or self.is_run_control)


@dataclass(frozen=True, slots=True, kw_only=True)
class StreamId:
    """Identity of one stream: its kind plus a source name.

    Control-plane streams are singletons per kind and carry no name; use
    :meth:`nameless` (or the module-level constants) for those.
    """

    kind: StreamKind = StreamKind.UNKNOWN
    name: str

    @classmethod
    def nameless(cls, kind: StreamKind) -> StreamId:
        return cls(kind=kind, name="")


COMMAND_STREAM = StreamId.nameless(StreamKind.LIVEDATA_COMMANDS)
RESPONSE_STREAM = StreamId.nameless(StreamKind.LIVEDATA_RESPONSES)
STATUS_STREAM = StreamId.nameless(StreamKind.LIVEDATA_STATUS)
RUN_CONTROL_STREAM = StreamId.nameless(StreamKind.RUN_CONTROL)


@dataclass(frozen=True, slots=True, kw_only=True)
class Message(Generic[PayloadT]):
    """A payload on a stream, stamped with data time.

    ``timestamp`` is the *data clock*: for data-plane messages it is when
    the payload was produced at its source (decoded from the wire), and all
    batching/windowing math runs on it — never on wall clock. The wall-clock
    default exists only for control-plane messages created in-process.

    Messages order by timestamp so heterogeneous streams can be merged with
    a plain sort.
    """

    stream: StreamId
    value: PayloadT
    timestamp: Timestamp = field(default_factory=Timestamp.now)

    def __lt__(self, other: Message[PayloadT]) -> bool:
        return self.timestamp < other.timestamp


@dataclass(frozen=True, slots=True)
class RunStart:
    """Run start announced by the facility control system (pl72 schema)."""

    run_name: str
    start_time: Timestamp
    stop_time: Timestamp | None = None


@dataclass(frozen=True, slots=True)
class RunStop:
    """Run stop announced by the facility control system (6s4t schema)."""

    run_name: str
    stop_time: Timestamp


@runtime_checkable
class MessageSource(Protocol, Generic[ItemT]):
    """Anything messages can be pulled from (Kafka, fakes, adapters)."""

    def get_messages(self) -> Sequence[ItemT]: ...


@runtime_checkable
class MessageSink(Protocol, Generic[OutT]):
    """Anything finished messages can be pushed into (Kafka, fakes)."""

    def publish_messages(self, messages: Sequence[Message[OutT]]) -> None: ...

"""Link monitor: EWMA bandwidth/RTT estimates driving ingest adaptation.

The host→device link behind the network relay is the measured, binding
and *volatile* constraint of the whole ingest tier: PERF.md records an
~8× bandwidth swing between hours (2.36e8 ev/s on a healthy relay vs
2.0–3.0e7 link-bound) with identical kernels and batch sizes. A fixed
batch size and wire format are therefore tuned for exactly one of those
regimes and wrong in the other. This module closes the loop (ADR 0111):

- **Estimation costs nothing on the hot path.** There are no probes.
  Bandwidth observations are the wall time of real staging work
  (``DeviceEventCache`` times each stage-once miss and reports the bytes
  it moved); RTT observations are the wall time of real publishes (one
  execute + one fetch = one device round trip, ``ops/publish.py``) —
  or, on the tick-program fast path (``ops/tick.py``, ADR 0114), of the
  whole step+publish tick, which IS the round trip a steady-state
  window pays. Compile rounds are excluded on both paths (the
  combiner's and the tick combiner's ``last_compiled``).
  Both fold into exponentially weighted moving averages under a lock —
  observations arrive from stage workers, publish timings from the step
  worker, and the 30 s metrics reader from the service thread.

  The bandwidth estimate is *effective ingest throughput* — host
  flatten + transfer, the number the policy must react to — not a pure
  wire measurement. On a host-bound day it saturates at the flatten
  rate, which is exactly when batch scaling stops helping; the policy
  thresholds are set against the transfer-bound regime where adaptation
  pays.

- **Policy with hysteresis.** :meth:`policy` maps the estimates to a
  :class:`LinkPolicy`:

  (a) ``window_scale`` — the batch-size target multiplier fed to the
      batcher (``RateAwareMessageBatcher.set_window`` when available;
      the adaptive batcher reacts through ``report_processing_time``
      backpressure either way). A degraded link amortizes per-batch
      fixed costs (dispatch, publish round trip) over more events —
      trading batch latency for link efficiency; a healthy link opens
      the throttle back to the base window.
  (b) ``compact_wire`` — the uint16 partitioned wire (ADR 0108):
      2 B/event instead of 4 doubles the link-bound ceiling. ``True``
      *forces* compact on every eligible histogrammer during prestage
      (``EventHistogrammer.set_wire_format``); ``None`` — the healthy
      state — leaves each histogrammer's construction-time default
      untouched (ADR 0108 already picks compact wherever offsets fit;
      the policy must never silently revert that to the wide wire).
  (c) ``depth`` — in-flight window bound for the pipeline
      (``core/ingest_pipeline.py``): a degraded or high-RTT link wants
      more windows in flight to keep the transfer stage fed; a healthy
      link wants the shallow bound for latency.
  (d) ``publish_coalesce`` — the publish-tick width (ADR 0113, applied
      via ``JobManager.set_publish_coalesce``): when the EWMA publish
      RTT alone approaches the ingest->publish budget, finalize runs
      only every Nth window so the (combined, one-per-device) publish
      round trip amortizes over more accumulation; healthy-RTT days
      keep N = 1 for latency. Hysteresis-latched like the other axes.

  The degraded latch flips on below ``degraded_bandwidth_bps`` and off
  only above ``recover_factor`` times that — the dead zone prevents the
  policy from flapping across a noisy threshold, the same shape as
  ``LoadGovernor``'s escalate/relax bands.
"""

from __future__ import annotations

import math
import threading
import time
from dataclasses import dataclass

#: Shared instrument (telemetry/instruments.py — defined there so a
#: serial service, which never imports this module, still exposes the
#: family). Recorded outside the monitor's lock: the instrument has its
#: own, and telemetry must never extend the estimator's critical
#: section.
from ..telemetry.instruments import PUBLISH_RTT_SECONDS as _RTT_SECONDS

__all__ = ["LinkMonitor", "LinkPolicy"]


@dataclass(frozen=True, slots=True)
class LinkPolicy:
    """One consistent adaptation decision (see module docstring)."""

    #: Multiplier on the batcher's base window (>= 1.0).
    window_scale: float
    #: True = force the uint16 compact partitioned wire (ADR 0108);
    #: None = leave each histogrammer's construction default untouched.
    compact_wire: bool | None
    #: In-flight window bound for the ingest pipeline.
    depth: int
    #: Publish-coalescing window (ADR 0113): finalize/publish only every
    #: Nth data window. 1 = publish every window (healthy RTT); a
    #: degraded relay widens the tick so the (combined) publish round
    #: trip amortizes over more accumulation.
    publish_coalesce: int = 1
    #: Fan-out demand axis (ADR 0117): the serving tier's contribution
    #: to ``publish_coalesce``. > 1 when nobody has been watching the
    #: broadcast plane for the idle grace period (publish work nobody
    #: consumes is pure relay load) or when every attached consumer is
    #: drowning (pressure latch). 1 = live demand at normal pressure —
    #: publish cadence stays RTT-governed. Already folded into
    #: ``publish_coalesce``; exposed so stats/telemetry name the axis.
    fanout_coalesce: int = 1


class LinkMonitor:
    """Thread-safe EWMA link estimator + adaptation policy."""

    def __init__(
        self,
        *,
        target_bandwidth_bps: float = 4.0e8,
        degraded_bandwidth_bps: float = 1.5e8,
        recover_factor: float = 2.0,
        rtt_deep_s: float = 0.03,
        rtt_coalesce_s: float = 0.05,
        max_publish_coalesce: int = 8,
        fanout_idle_coalesce: int = 4,
        fanout_idle_grace_s: float = 10.0,
        fanout_pressure_high: float = 0.75,
        fanout_pressure_low: float = 0.25,
        alpha: float = 0.25,
        max_window_scale: float = 8.0,
        base_depth: int = 2,
        max_depth: int = 4,
    ) -> None:
        if not 0.0 < alpha <= 1.0:
            raise ValueError("alpha must be in (0, 1]")
        if recover_factor < 1.0:
            raise ValueError("recover_factor must be >= 1.0")
        #: 4e8 B/s is the bandwidth that sustains the 1e8 ev/s target at
        #: the 4 B/event flat wire (PERF.md) — at or above it there is
        #: nothing to adapt.
        self._target = float(target_bandwidth_bps)
        self._degraded = float(degraded_bandwidth_bps)
        self._recover = float(degraded_bandwidth_bps) * float(recover_factor)
        self._recover_factor = float(recover_factor)
        self._rtt_deep = float(rtt_deep_s)
        #: Publish-coalescing latch threshold (ADR 0113): above this
        #: publish RTT the round trip alone dominates a ~1 Hz tick, so
        #: the policy widens the publish window; the latch releases only
        #: below ``rtt_coalesce_s / recover_factor`` — the same dead-zone
        #: shape as the bandwidth latch, so a noisy RTT can't flap the
        #: publish cadence.
        self._rtt_coalesce = float(rtt_coalesce_s)
        self._max_coalesce = max(1, int(max_publish_coalesce))
        self._alpha = float(alpha)
        self._max_scale = float(max_window_scale)
        self._base_depth = int(base_depth)
        self._max_depth = max(int(max_depth), int(base_depth))
        self._lock = threading.Lock()
        self._bw_bps: float | None = None
        self._rtt_s: float | None = None
        #: Per-mesh-slice publish RTT EWMAs (ADR 0115): a multi-slice
        #: service publishes concurrently from several devices, and one
        #: congested slice must widen the publish tick even while the
        #: others look healthy — the policy reads the WORST slice.
        #: Entries carry their last-observation time and expire after
        #: ``_SLICE_TTL_S``: a slice whose jobs stopped must not pin
        #: the worst-slice RTT (and the coalesce latch) forever with
        #: its final congested estimate.
        self._rtt_by_slice: dict[str, tuple[float, float]] = {}
        self._degraded_latch = False
        self._coalesce_latch = False
        self._n_staging = 0
        self._n_publish = 0
        self._bytes_observed = 0
        #: Fan-out demand axis (ADR 0117), fed by the broadcast plane
        #: through ``observe_fanout``. ``None`` subscribers = no serving
        #: plane has ever reported — the axis stays neutral, so a
        #: deployment without a serve port behaves exactly as before.
        #: Idle entry is time-latched (``fanout_idle_grace_s`` of
        #: continuous zero-subscriber reports) so a dashboard reconnect
        #: blip cannot flap the publish cadence; attach releases
        #: INSTANTLY — a viewer must never wait out a hysteresis band
        #: for fresh data. Queue pressure uses a high/low dead zone like
        #: every other latch here.
        self._fanout_idle_coalesce = max(1, int(fanout_idle_coalesce))
        self._fanout_idle_grace_s = float(fanout_idle_grace_s)
        self._fanout_pressure_high = float(fanout_pressure_high)
        self._fanout_pressure_low = float(fanout_pressure_low)
        self._fanout_subscribers: int | None = None
        self._fanout_pressure = 0.0
        self._fanout_idle_since: float | None = None
        self._fanout_pressure_latch = False

    # -- observations ------------------------------------------------------
    def observe_staging(self, nbytes: int, seconds: float) -> None:
        """Fold one staging event (bytes moved over wall seconds) in."""
        if nbytes <= 0 or seconds <= 0.0:
            return
        sample = nbytes / seconds
        with self._lock:
            self._n_staging += 1
            self._bytes_observed += int(nbytes)
            self._bw_bps = (
                sample
                if self._bw_bps is None
                else self._alpha * sample + (1.0 - self._alpha) * self._bw_bps
            )

    def observe_publish(
        self,
        seconds: float,
        *,
        compiled: bool = False,
        slice_key: str | None = None,
    ) -> None:
        """Fold one publish round trip's wall time in.

        The observation is the wall time of one real execute+fetch pair
        — a combined publish (ADR 0113) or a whole tick program
        (ops/tick.py, ADR 0114: step AND publish in the one dispatch, so
        the sample is the full device round trip a steady-state tick
        pays). Compile rounds (``PublishCombiner.last_compiled`` /
        ``TickCombiner.last_compiled``) are one-off XLA work worth
        hundreds of ms and must never reach the EWMA — a first-tick
        compile or a layout-swap/wire-flip recompile would otherwise
        latch the publish-coalescing policy on a healthy relay. Two ways
        to exclude them, by caller kind: the JobManager SKIPS the call
        when ``last_compiled`` is set (the observer slot is duck-typed —
        a stub observer need not accept this kwarg), while direct
        LinkMonitor users pass ``compiled=True`` and this method drops
        the sample. Both are load-bearing; a timing that might include
        compilation must take one of them.

        ``slice_key`` (mesh serving, ADR 0115) attributes the sample to
        the mesh slice that executed the tick; per-slice EWMAs feed the
        policy's worst-slice RTT so one congested device widens the
        publish tick even while the others look healthy. Sliceless
        samples (single-device deployments) keep the single estimate.
        """
        if compiled or seconds <= 0.0:
            return
        _RTT_SECONDS.observe(
            seconds, slice="all" if slice_key is None else str(slice_key)
        )
        with self._lock:
            self._n_publish += 1
            self._rtt_s = (
                seconds
                if self._rtt_s is None
                else self._alpha * seconds + (1.0 - self._alpha) * self._rtt_s
            )
            if slice_key is not None:
                now = time.monotonic()
                entry = self._rtt_by_slice.get(slice_key)
                prev = None if entry is None else entry[0]
                self._rtt_by_slice[slice_key] = (
                    (
                        seconds
                        if prev is None
                        else self._alpha * seconds
                        + (1.0 - self._alpha) * prev
                    ),
                    now,
                )

    def observe_fanout(
        self, subscribers: int, queue_pressure: float
    ) -> None:
        """Fold one broadcast-plane QoS report in (ADR 0117).

        ``subscribers`` is the attached-consumer count,
        ``queue_pressure`` the worst per-subscriber send-queue fill in
        [0, 1] (``BroadcastServer.qos``). Zero subscribers starts the
        idle clock (publish coalescing backs off once it has run
        ``fanout_idle_grace_s``); any subscriber clears it immediately
        — cadence tightens the moment a viewer attaches.
        """
        now = time.monotonic()
        with self._lock:
            subscribers = max(0, int(subscribers))
            self._fanout_pressure = min(1.0, max(0.0, float(queue_pressure)))
            if subscribers == 0:
                if (
                    self._fanout_subscribers is None
                    or self._fanout_subscribers > 0
                ):
                    self._fanout_idle_since = now
            else:
                self._fanout_idle_since = None
            self._fanout_subscribers = subscribers

    # -- estimates ---------------------------------------------------------
    def bandwidth_bps(self) -> float | None:
        with self._lock:
            return self._bw_bps

    #: Per-slice RTT entries expire this long after their last sample:
    #: long against any publish cadence (ticks are ~1 Hz, coalesced at
    #: most 8x), short against a service lifetime — a retired slice
    #: stops gating the policy within a minute.
    _SLICE_TTL_S = 60.0

    def rtt_s(self, slice_key: str | None = None) -> float | None:
        with self._lock:
            if slice_key is not None:
                entry = self._rtt_by_slice.get(slice_key)
                return None if entry is None else entry[0]
            return self._rtt_s

    def _policy_rtt_locked(self) -> float | None:
        """The RTT the adaptation policy reacts to (caller holds the
        lock): the WORST live per-slice estimate when slices report —
        the publish tick must widen for the slowest slice, not the mean
        — else the single global estimate. Expired slices (no sample
        within the TTL: their jobs stopped or migrated) are pruned here
        so a dead slice's last congested estimate cannot latch the
        coalescing policy forever."""
        if self._rtt_by_slice:
            cutoff = time.monotonic() - self._SLICE_TTL_S
            for key in [
                k
                for k, (_, seen) in self._rtt_by_slice.items()
                if seen < cutoff
            ]:
                del self._rtt_by_slice[key]
        if self._rtt_by_slice:
            worst = max(rtt for rtt, _ in self._rtt_by_slice.values())
            if self._rtt_s is None:
                return worst
            return max(worst, self._rtt_s)
        return self._rtt_s

    # -- policy ------------------------------------------------------------
    def policy(self) -> LinkPolicy:
        """The current adaptation decision; neutral until the first
        staging observation converges the bandwidth estimate."""
        with self._lock:
            return self._policy_locked()

    def _policy_locked(self) -> LinkPolicy:
        """Policy computation under the caller's lock acquisition —
        shared by :meth:`policy` and :meth:`stats` so a stats snapshot
        is ONE coherent read (policy fields and raw estimates from the
        same critical section; see the stats docstring)."""
        bw = self._bw_bps
        rtt = self._policy_rtt_locked()
        fanout = self._fanout_coalesce_locked()
        coalesce = self._publish_coalesce_locked(rtt, fanout)
        if bw is None:
            return LinkPolicy(
                window_scale=1.0,
                compact_wire=None,
                depth=self._base_depth,
                publish_coalesce=coalesce,
                fanout_coalesce=fanout,
            )
        if self._degraded_latch:
            if bw >= self._recover:
                # graftlint: disable=JGL012 caller holds self._lock
                self._degraded_latch = False
        elif bw < self._degraded:
            # graftlint: disable=JGL012 caller holds self._lock
            self._degraded_latch = True
        degraded = self._degraded_latch
        # Continuous target quantized to sqrt(2) steps: the batcher
        # regates streams on every window change, so a smoothly
        # drifting estimate must not retarget every batch.
        raw = min(self._max_scale, max(1.0, self._target / bw))
        step = round(math.log(raw, math.sqrt(2.0)))
        scale = min(self._max_scale, max(1.0, math.sqrt(2.0) ** step))
        deep = degraded or (rtt is not None and rtt > self._rtt_deep)
        return LinkPolicy(
            window_scale=scale,
            compact_wire=True if degraded else None,
            depth=self._max_depth if deep else self._base_depth,
            publish_coalesce=coalesce,
            fanout_coalesce=fanout,
        )

    def _fanout_coalesce_locked(self) -> int:
        """The fan-out demand contribution to publish coalescing
        (caller holds the lock; ADR 0117). Neutral (1) until a serving
        plane reports. Zero subscribers for the idle grace period →
        ``fanout_idle_coalesce`` (publish ticks nobody consumes are
        pure relay load); an attach releases instantly. With live
        subscribers, sustained worst-queue pressure over the high
        watermark latches a mild widening (2) until pressure falls
        under the low watermark — publishing less often is the only
        lever that helps a consumer that cannot drain."""
        if self._fanout_subscribers is None:
            return 1
        if self._fanout_subscribers == 0:
            since = self._fanout_idle_since
            if (
                since is not None
                and time.monotonic() - since >= self._fanout_idle_grace_s
            ):
                return min(self._max_coalesce, self._fanout_idle_coalesce)
            return 1
        if self._fanout_pressure_latch:
            if self._fanout_pressure < self._fanout_pressure_low:
                # graftlint: disable=JGL012 caller holds self._lock
                self._fanout_pressure_latch = False
        elif self._fanout_pressure > self._fanout_pressure_high:
            # graftlint: disable=JGL012 caller holds self._lock
            self._fanout_pressure_latch = True
        return 2 if self._fanout_pressure_latch else 1

    def _publish_coalesce_locked(
        self, rtt: float | None, fanout: int = 1
    ) -> int:
        """The RTT-adaptive publish-coalescing window (caller holds the
        lock). Latched with a dead zone; while latched the window is the
        RTT over the latch threshold, doubled and quantized to the
        NEAREST power of two (floor 2) — a barely-over-threshold 51 ms
        RTT coalesces 2 windows, the round-5 88 ms RTT 4, a 200 ms
        relay 8 (capped). ``fanout`` (ADR 0117) is the demand axis:
        the widest of the two wins, so an unwatched service backs off
        even on a healthy relay and a congested relay keeps its RTT
        width even with viewers attached."""
        # "_locked" contract: every caller (policy, and stats through
        # policy) already holds self._lock around this call.
        if rtt is not None:
            if self._coalesce_latch:
                if rtt <= self._rtt_coalesce / self._recover_factor:
                    # graftlint: disable=JGL012 caller holds self._lock
                    self._coalesce_latch = False
            elif rtt > self._rtt_coalesce:
                # graftlint: disable=JGL012 caller holds self._lock
                self._coalesce_latch = True
        rtt_width = 1
        if rtt is not None and self._coalesce_latch:
            raw = max(2.0, 2.0 * rtt / self._rtt_coalesce)
            rtt_width = min(self._max_coalesce, 1 << round(math.log2(raw)))
        return min(self._max_coalesce, max(rtt_width, fanout))

    def stats(self) -> dict[str, float | int | bool | None]:
        """Snapshot for the 30 s metrics line and the telemetry
        collector — ONE lock acquisition for the whole read. The old
        shape (``self.policy()`` then re-acquire for the raw fields)
        could interleave with observations between the two critical
        sections and report policy fields computed from DIFFERENT state
        than the latches/estimates next to them — e.g. ``degraded:
        True`` beside ``compact_wire: None``, an impossible pairing
        that sends an operator chasing a phantom policy bug. Pinned by
        the stats-coherence lock hammer in tests/core/link_monitor_test.
        """
        with self._lock:
            policy = self._policy_locked()
            return {
                "bandwidth_bps": self._bw_bps,
                "rtt_s": self._rtt_s,
                "rtt_by_slice": {
                    k: rtt for k, (rtt, _) in self._rtt_by_slice.items()
                },
                "n_staging": self._n_staging,
                "n_publish": self._n_publish,
                "bytes_observed": self._bytes_observed,
                "degraded": self._degraded_latch,
                "window_scale": policy.window_scale,
                "compact_wire": policy.compact_wire,
                "depth": policy.depth,
                "publish_coalesce": policy.publish_coalesce,
                "fanout_coalesce": policy.fanout_coalesce,
                "fanout_subscribers": self._fanout_subscribers,
                "fanout_pressure": self._fanout_pressure,
            }

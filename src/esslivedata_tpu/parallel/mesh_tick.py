"""Mesh-native tick serving: compile the ADR 0114 tick program onto a
data×bank mesh, and place every tick group on a mesh slice.

The single-device hot path runs a steady-state tick as ONE jitted
dispatch + ONE fetch (ops/tick.py). This module is the scale-out tier
that turns the standalone mesh demo (`MULTICHIP_r05.json`'s dryrun) into
the real serving topology (ADR 0115, ROADMAP item 1):

- :class:`MeshTickCombiner` compiles the SAME tick program under a
  ``Mesh`` + ``PartitionSpec``: the staged event wire enters sharded
  ``P('data')``, each member's rolling histogram state ``P('bank',
  None)``, the collective step is the sharded kernel's shard_map body
  (delta_psum / event_gather exchange, parallel/sharded_hist.py), and
  the publish bodies run over mesh-replicated views — so the packed
  output vector is replicated and ONE ``device_get`` serves the whole
  mesh. Donation is preserved straight through the outer jit
  (SNIPPETS.md [1]–[2]: donation composes with pjit-style explicit
  shardings; the shard_map fallback shim in :mod:`.mesh` covers jax
  lines without the modern entry point).

- :class:`DevicePlacement` makes the JobManager placement-aware: each
  (stream, fuse-key) tick group is assigned a mesh *slice* — a single
  device, round-robin over the mesh, for single-device histogrammers
  (K independent instrument streams spread across chips), or the WHOLE
  mesh for bank-sharded LOKI-scale jobs (whose state already spans it).
  The assignment is sticky for the group's lifetime, so staged wires,
  donated states and compiled programs never migrate between ticks;
  ``DeviceEventCache`` keys carry the slice, so each batch stages once
  per slice with the right placement (ADR 0110 extended per-slice).

Readback stays O(1) fetch per slice per tick: single-device slices fetch
their own packed vector; the mesh slice fetches one replicated vector.
Per-slice execute/fetch counts land in ``ops/publish.METRICS`` under
``slices`` and per-slice publish RTTs in the LinkMonitor, so the bench
(``bench.py --mesh``) asserts the contract directly.
"""

from __future__ import annotations

import inspect
import logging
import threading
from dataclasses import dataclass
from typing import Any

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.tick import TickCombiner

__all__ = ["DevicePlacement", "MeshTickCombiner", "TickSlice"]

logger = logging.getLogger(__name__)


class MeshTickCombiner(TickCombiner):
    """One execute + one replicated fetch for a whole mesh tick group.

    The program body is TickCombiner's verbatim — staged wire in, the
    group histogrammer's ``tick_step`` (here: the shard_map'ed
    collective step), each member's packed publish body over its
    stepped state — with one addition at the output seam: the packed
    vector and any static leaves are pinned to the replicated sharding,
    so GSPMD cannot leave them partially placed and the host-side
    ``device_get`` is a single-shard read however many devices the
    group spans. Per-member plan/unpack/containment machinery is shared
    with the base class (ADR 0113/0114), so the mesh path cannot
    diverge in spec handling or failure semantics.
    """

    #: Compile-event site label (telemetry, ADR 0116): mesh-program
    #: compiles are the expensive tier (GSPMD partitioning on top of
    #: XLA) and must decompose separately from single-device ticks.
    compile_site = "mesh_tick"

    def __init__(self, mesh: Mesh, max_programs: int = 16) -> None:
        super().__init__(max_programs)
        self._mesh = mesh
        self._replicated = NamedSharding(mesh, P())

    @property
    def mesh(self) -> Mesh:
        return self._mesh

    def _finish_outputs(self, packed, statics):
        constrain = lambda x: jax.lax.with_sharding_constraint(  # noqa: E731
            x, self._replicated
        )
        packed = constrain(packed)
        statics = tuple(
            tuple(constrain(leaf) for leaf in member) for member in statics
        )
        return packed, statics


@dataclass(frozen=True)
class TickSlice:
    """One tick group's placement on the serving mesh.

    ``device`` is set for single-device slices (the group's staged wire
    and donated states are committed there); ``mesh``/``combiner`` are
    set for whole-mesh groups. ``label`` keys the per-slice METRICS
    breakdown and the LinkMonitor's per-slice RTT estimate.
    """

    label: str
    device: Any | None = None
    mesh: Mesh | None = None
    combiner: MeshTickCombiner | None = None


class DevicePlacement:
    """Sticky (stream, fuse-key) → mesh-slice assignment policy.

    Single-device tick groups land round-robin over the mesh's devices
    in first-seen order — the cheapest policy that spreads independent
    instrument streams across chips while keeping every group's
    placement stable (a migrating group would re-stage its wire,
    re-commit its donated states and recompile its tick program for
    nothing). Mesh-sharded groups (the histogrammer carries a ``mesh``)
    get the whole mesh and the shared :class:`MeshTickCombiner`.

    Thread-safety: ``assign`` is called from the JobManager's window
    path under load; the table mutates under a lock and entries are
    immutable after insertion.
    """

    def __init__(self, mesh: Mesh) -> None:
        self._mesh = mesh
        self._devices = list(mesh.devices.flat)
        self._lock = threading.Lock()
        self._slices: dict[tuple, TickSlice] = {}
        self._next = 0
        self._mesh_combiners: dict[tuple, MeshTickCombiner] = {}

    @property
    def mesh(self) -> Mesh:
        return self._mesh

    @staticmethod
    def _supports_device_staging(hist) -> bool:
        """True when the histogrammer's staging surface accepts the
        slice ``device=`` kwarg. Bespoke duck-typed histogrammers
        predating slice placement don't — forwarding the kwarg would
        TypeError every window — so their groups pin to the default
        placement instead of a device slice."""
        stage = getattr(hist, "tick_staging", None)
        if stage is None:
            return False
        try:
            return "device" in inspect.signature(stage).parameters
        except (TypeError, ValueError):  # builtins/partials: unknown
            return False

    def assign(self, stream: str, group_key, hist) -> TickSlice:
        """The (sticky) slice for one tick/fused group."""
        key = (stream, group_key)
        with self._lock:
            s = self._slices.get(key)
            if s is not None:
                return s
            group_mesh = getattr(hist, "mesh", None)
            if not isinstance(group_mesh, Mesh) and (
                not self._supports_device_staging(hist)
            ):
                # Sticky, labeled, but UN-placed: the group serves from
                # the default device exactly as without a placement.
                s = TickSlice(label="default")
                self._slices[key] = s
                return s
            if isinstance(group_mesh, Mesh):
                ids = tuple(
                    int(d.id) for d in group_mesh.devices.flat
                )
                combiner = self._mesh_combiners.get(ids)
                if combiner is None:
                    combiner = self._mesh_combiners[ids] = (
                        MeshTickCombiner(group_mesh)
                    )
                s = TickSlice(
                    label="mesh:" + ",".join(str(i) for i in ids),
                    mesh=group_mesh,
                    combiner=combiner,
                )
            else:
                dev = self._devices[self._next % len(self._devices)]
                self._next += 1
                s = TickSlice(label=f"device:{int(dev.id)}", device=dev)
            self._slices[key] = s
            logger.info(
                "placed tick group %r/%r on %s", stream, group_key, s.label
            )
            return s

    def slices(self) -> dict[tuple, TickSlice]:
        with self._lock:
            return dict(self._slices)

    @staticmethod
    def state_on(state, device) -> bool:
        """True when every array leaf of ``state`` already lives on
        ``device`` (metadata probe, no sync). Uncommitted leaves count
        as elsewhere on purpose: placement commits them (one transfer)
        so every later probe — including the private path's
        ``_state_slice_device``, which reads committedness — sees the
        slice."""
        from ..ops.event_batch import leaf_device_set

        for leaf in jax.tree_util.tree_leaves(state):
            ds = leaf_device_set(leaf)
            if ds is None:
                continue
            if ds != {device} or not getattr(leaf, "committed", True):
                return False
        return True

    @staticmethod
    def place_state(state, device):
        """``state`` with every array leaf committed to ``device`` —
        the one-off migration when a group is first assigned its slice
        (or recovers from a reset on the default device). One async
        transfer per leaf; steady-state ticks never pay it because the
        returned (donated) carries stay on the slice."""
        return jax.tree_util.tree_map(
            lambda leaf: (
                jax.device_put(leaf, device)
                if isinstance(leaf, jax.Array)
                else leaf
            ),
            state,
        )

    def ensure_state_on(self, ingest, device) -> None:
        """Move one ingest offer's state to ``device`` if it is not
        already committed there (sticky slices make this a no-op on
        every tick after the first)."""
        state = ingest.get_state()
        if self.state_on(state, device):
            return
        ingest.set_state(self.place_state(state, device))

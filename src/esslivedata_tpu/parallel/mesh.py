"""Mesh construction helpers + the jax-version shard_map shim.

``shard_map`` is the mesh serving tier's one hard jax dependency and its
import path moved across jax releases: modern jax exposes ``jax.shard_map``
(with a ``check_vma`` kwarg), while the 0.4.x line ships it as
``jax.experimental.shard_map.shard_map`` (kwarg named ``check_rep``).
The shim below resolves whichever this jax provides so the sharded
kernels — and the mesh tick program built on them (parallel/mesh_tick.py,
ADR 0115) — compile on both, instead of the whole parallel layer dying
with an AttributeError on the older line (SNIPPETS.md [2]'s
prefer-explicit-shardings-else-shard_map fallback shape).
"""

from __future__ import annotations

from typing import Callable

import numpy as np

import jax
from jax.sharding import Mesh

__all__ = ["make_mesh", "mesh_from_spec", "shard_map", "shard_map_available"]


def _resolve_shard_map() -> tuple[Callable | None, bool]:
    """(shard_map callable, native) for this jax, else (None, False).

    ``native`` = the modern ``jax.shard_map`` entry point (accepts
    ``check_vma``); the experimental fallback takes ``check_rep``.
    """
    fn = getattr(jax, "shard_map", None)
    if fn is not None:
        return fn, True
    try:  # jax 0.4.x line
        from jax.experimental.shard_map import shard_map as legacy
    except ImportError:
        return None, False
    return legacy, False


_SHARD_MAP, _SHARD_MAP_NATIVE = _resolve_shard_map()


def shard_map_available() -> bool:
    """True when some shard_map entry point exists on this jax. When
    False, the collective mesh kernels cannot compile at all — callers
    (and the version-guarded tests) degrade to single-device serving
    with a message naming the gap instead of an AttributeError."""
    return _SHARD_MAP is not None


def shard_map(f, *, mesh, in_specs, out_specs, check_vma: bool = True):
    """Version-portable ``shard_map``.

    Maps ``check_vma`` onto the older line's ``check_rep`` (same
    semantics: disable the static varying-mesh-axes/replication check
    where a kernel's replication invariant holds by construction but
    cannot be inferred — the event_gather exchange, interpret-mode
    pallas)."""
    if _SHARD_MAP is None:
        raise RuntimeError(
            "This jax provides neither jax.shard_map nor "
            "jax.experimental.shard_map: the mesh-sharded kernels "
            "(parallel/) cannot compile. Upgrade jax or use the "
            "single-device serving path."
        )
    if _SHARD_MAP_NATIVE:
        return _SHARD_MAP(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=check_vma,
        )
    return _SHARD_MAP(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_rep=check_vma,
    )


def mesh_from_spec(spec: str, *, devices=None) -> Mesh:
    """Parse the service surface's ``--mesh data,bank`` form (also the
    ``LIVEDATA_MESH`` env value) into a 2-D ('data', 'bank') mesh.

    ``"2,4"`` = data=2 x bank=4; a single integer (``"8"``) puts every
    device on the bank axis (the memory-relieving default, matching
    ``make_mesh``); ``"auto"`` uses all visible devices the same way.
    """
    spec = spec.strip().lower()
    if devices is None:
        devices = jax.devices()
    if spec in ("auto", ""):
        return make_mesh(len(devices), devices=devices)
    parts = [p.strip() for p in spec.split(",")]
    try:
        dims = [int(p) for p in parts]
    except ValueError as err:
        raise ValueError(
            f"--mesh expects 'data,bank' integers or 'auto'; got {spec!r}"
        ) from err
    if any(d < 1 for d in dims):
        # A zero axis would build an EMPTY mesh: make_mesh's
        # data*bank == n_devices check passes at 0 == 0, and the
        # placement then degrades to unplaced serving one contained
        # ZeroDivisionError at a time — an operator typo must fail the
        # build instead.
        raise ValueError(
            f"--mesh axes must be >= 1; got {spec!r}"
        )
    if len(dims) == 1:
        return make_mesh(dims[0], devices=devices)
    if len(dims) != 2:
        raise ValueError(
            f"--mesh expects at most two axes (data,bank); got {spec!r}"
        )
    data, bank = dims
    return make_mesh(data * bank, data=data, bank=bank, devices=devices)


def make_mesh(
    n_devices: int | None = None,
    *,
    data: int | None = None,
    bank: int | None = None,
    devices=None,
) -> Mesh:
    """Build a 2-D ('data', 'bank') mesh over the first ``n_devices`` devices.

    ``data`` shards the event stream (DP analog); ``bank`` shards bin space
    (TP/SP analog). If only one of data/bank is given the other is inferred;
    if neither, devices all go to ``bank`` (bin-space sharding is the
    memory-relieving axis, which is the usual reason to shard).
    """
    if devices is None:
        devices = jax.devices()
    if n_devices is None:
        n_devices = len(devices)
    devices = devices[:n_devices]
    if len(devices) < n_devices:
        raise ValueError(
            f"Requested {n_devices} devices, only {len(devices)} available"
        )
    if data is None and bank is None:
        data, bank = 1, n_devices
    elif data is None:
        if n_devices % bank:
            raise ValueError(f"{n_devices} devices not divisible by bank={bank}")
        data = n_devices // bank
    elif bank is None:
        if n_devices % data:
            raise ValueError(f"{n_devices} devices not divisible by data={data}")
        bank = n_devices // data
    if data * bank != n_devices:
        raise ValueError(f"data*bank = {data * bank} != n_devices = {n_devices}")
    arr = np.asarray(devices).reshape(data, bank)
    return Mesh(arr, ("data", "bank"))

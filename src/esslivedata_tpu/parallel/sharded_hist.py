"""Multi-device sharded event histogrammer.

The multi-bank / long-axis scale-out path (BASELINE configs 3-4): screen
rows (detector banks) are sharded over the mesh's ``bank`` axis so a
histogram too large for one chip's HBM splits across chips, and the event
stream is sharded over the ``data`` axis. Parity with the single-device
``EventHistogrammer``: replica LUTs, per-pixel weights, decay, and the
fold semantics (steps touch only the window; the cumulative total folds at
publish rate).

Two exchange strategies merge the data shards (all XLA collectives over
ICI, no NCCL analog):

- ``delta_psum``: every data shard scatters into its own dense copy of
  its bank rows, then ``psum('data')`` merges. Per-step traffic is
  O(rows_per_bank * n_toa) per device regardless of how sparse the batch
  is — fine for small bin spaces (DREAM-size banks), ruinous at LOKI
  scale (1.5M x 100 bins: ~150 MB per shard per step).
- ``event_gather``: ``all_gather('data')`` the *event* shards instead —
  every device then scatters the full batch into its own bank rows, and
  the data-replicated window copies stay identical with no dense
  reduction at all. Per-step traffic is O(n_events * (data-1)/data),
  independent of bin-space size.

``exchange='auto'`` picks event_gather once a bank shard exceeds 1M bins
(the crossover is roughly where a dense delta outweighs a 4M-event
gather). Events are also replicated across the ``bank`` axis by their
P('data') sharding, so each bank shard routes gather-free: it scatters
the events landing in its rows and drops the rest via the dump bin.
"""

from __future__ import annotations

from functools import partial

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.histogram import EventProjection, HistogramState

__all__ = ["ShardedHistogrammer"]

#: Bins per bank shard above which 'auto' switches the data-shard merge
#: from a dense delta psum to an event all_gather.
_EVENT_GATHER_BINS = 1 << 20


class ShardedHistogrammer:
    """Scatter-add histogrammer with screen rows sharded over ``bank`` and
    events sharded over ``data`` mesh axes.

    The single-device equivalent is ``ops.histogram.EventHistogrammer``;
    this class accepts the same logical inputs (global pixel ids, toa) and
    produces the same global histogram, distributed.
    """

    def __init__(
        self,
        *,
        toa_edges: np.ndarray,
        n_screen: int,
        mesh: Mesh,
        pixel_lut: np.ndarray | None = None,
        pixel_weights: np.ndarray | None = None,
        decay: float | None = None,
        exchange: str = "auto",
        dtype=jnp.float32,
    ) -> None:
        if exchange not in ("auto", "delta_psum", "event_gather"):
            raise ValueError(f"Unknown exchange {exchange!r}")
        self._mesh = mesh
        self._n_bank = mesh.shape["bank"]
        self._n_data = mesh.shape["data"]
        if n_screen % self._n_bank:
            raise ValueError(
                f"n_screen={n_screen} must divide over bank axis {self._n_bank}"
            )
        # One projection kernel shared with EventHistogrammer: identical
        # TOA binning (incl. non-uniform edges), LUT/replica routing and
        # weight semantics; only the row window differs per bank shard.
        self._proj = EventProjection(
            toa_edges=toa_edges,
            pixel_lut=pixel_lut,
            pixel_weights=pixel_weights,
            n_screen=n_screen,
        )
        # Weights replicated on every device: gathers stay local. The
        # LUT rides the jitted step as an ARGUMENT (ADR 0105) so a
        # live-geometry rebuild swaps tables without recompiling; it is
        # replicated explicitly below.
        self._has_lut = self._proj.lut_host is not None
        self._replicate = lambda x: jax.device_put(
            x, NamedSharding(mesh, P())
        )
        # place_constants replicates the LUT straight from its HOST copy
        # (one placement, no default-device staging hop) and re-places
        # the weights; the replicated LUT then rides the jitted step as
        # an argument (ADR 0105).
        self._proj.place_constants(self._replicate)
        self._lut_rep = self._proj.lut if self._has_lut else None
        self._rows_per_bank = n_screen // self._n_bank
        self._n_screen = n_screen
        self._n_toa = self._proj.n_toa
        self._edges = self._proj.edges
        self._decay = decay
        self._dtype = dtype
        if exchange == "auto":
            exchange = (
                "event_gather"
                if self._rows_per_bank * self._n_toa > _EVENT_GATHER_BINS
                else "delta_psum"
            )
        self._exchange = exchange

        self._state_sharding = NamedSharding(mesh, P("bank", None))
        self._event_sharding = NamedSharding(mesh, P("data"))
        self._scalar_sharding = NamedSharding(mesh, P())
        # The no-decay step's unit update magnitude, staged once: building
        # it per step would dispatch a host->device scalar transfer on
        # every batch (graftlint JGL006).
        self._unit_scale = jax.device_put(
            jnp.asarray(1.0, self._dtype), self._scalar_sharding
        )

        lut_specs = (P(),) if self._has_lut else ()  # replicated LUT arg
        shard = partial(
            jax.shard_map,
            mesh=mesh,
            in_specs=(
                P("bank", None),  # window
                *lut_specs,
                P("data"),  # pixel_id
                P("data"),  # toa
                P(),  # inv_scale (replicated lazy-decay magnitude)
            ),
            out_specs=P("bank", None),
            # event_gather keeps the window replicated over 'data' by
            # construction (identical full-batch scatter on every copy
            # after the all_gather); the static varying-mesh-axes check
            # cannot infer that through the scatter, so only that mode
            # disables it — delta_psum keeps the safety net.
            check_vma=(self._exchange != "event_gather"),
        )
        if self._has_lut:

            def _local(win, lut, pid, toa, inv_scale):
                return self._step_local(win, pid, toa, inv_scale, lut=lut)

        else:

            def _local(win, pid, toa, inv_scale):
                return self._step_local(win, pid, toa, inv_scale)

        sharded_step = shard(_local)
        self._step = jax.jit(sharded_step, donate_argnums=(0,))

        if decay is not None:
            from ..ops.histogram import EventHistogrammer as _EH

            def _step_decay(win, *args):
                # Lazy decay fused into the one jitted program (the
                # single-device kernel does the same inside _advance):
                # scale shrinks, updates grow by 1/scale, renormalize on
                # underflow — no per-batch eager dispatches.
                *rest, scale = args
                scale = scale * decay
                win = sharded_step(win, *rest, 1.0 / scale)
                return jax.lax.cond(
                    scale < _EH._SCALE_FLOOR,
                    lambda w, sc: (w * sc, jnp.ones_like(sc)),
                    lambda w, sc: (w, sc),
                    win,
                    scale,
                )

            self._step_decay = jax.jit(_step_decay, donate_argnums=(0,))

        norm = partial(
            jax.shard_map,
            mesh=mesh,
            in_specs=(P("bank", None), P("data")),
            out_specs=P("bank", None),
        )
        self._normalize = jax.jit(norm(self._normalize_local))
        # Fold semantics as in EventHistogrammer: steps touch only the
        # window; the cumulative total is folded at publish rate.
        def _physical(win, scale):
            return win if scale is None else win * scale

        self._clear_window = jax.jit(
            lambda cum, win, scale: (
                cum + _physical(win, scale),
                jnp.zeros_like(win),
            ),
            donate_argnums=(0, 1),
        )
        self._views = jax.jit(
            lambda cum, win, scale: (
                cum + _physical(win, scale),
                _physical(win, scale),
            )
        )

    # -- local (per-shard) kernels ---------------------------------------
    def _step_local(self, win, pixel_id, toa, inv_scale, lut=None):
        """One shard's step. ``inv_scale`` is the lazy-decay update
        magnitude (1.0 without decay): the dense ``win * decay`` multiply
        the naive formulation would pay per step is folded into the
        scatter updates instead, exactly as in EventHistogrammer."""
        bank = jax.lax.axis_index("bank")
        row0 = bank * self._rows_per_bank
        n_local = self._rows_per_bank * self._n_toa

        if self._exchange == "event_gather":
            # Merge data shards by gathering the (small) event arrays;
            # every data-replicated window copy then applies the identical
            # full-batch scatter — no dense reduction. The dump index
            # (n_local) is out of bounds of the window and dropped.
            pixel_id = jax.lax.all_gather(
                pixel_id, "data", axis=0, tiled=True
            )
            toa = jax.lax.all_gather(toa, "data", axis=0, tiled=True)
            flat, w = self._proj.flat_and_weights(
                pixel_id, toa, row0=row0, n_rows=self._rows_per_bank, lut=lut
            )
            updates = (
                inv_scale if w is None else w.astype(self._dtype) * inv_scale
            )
            return (
                win.reshape(-1)
                .at[flat]
                .add(updates, mode="drop")
                .reshape(win.shape)
            )

        # delta_psum: scatter into a fresh local delta, merge over 'data'.
        flat, w = self._proj.flat_and_weights(
            pixel_id, toa, row0=row0, n_rows=self._rows_per_bank, lut=lut
        )
        updates = inv_scale if w is None else w.astype(self._dtype) * inv_scale
        delta = jnp.zeros((n_local + 1,), dtype=self._dtype)
        delta = delta.at[flat].add(updates, mode="drop")[:n_local]
        delta = delta.reshape(self._rows_per_bank, self._n_toa)
        delta = jax.lax.psum(delta, "data")
        return win + delta

    def _normalize_local(self, hist, monitor_counts):
        # monitor_counts: per-event-shard scalar counts; global total via psum.
        total = jax.lax.psum(jnp.sum(monitor_counts), "data")
        return hist / jnp.maximum(total, 1.0)

    # -- public API -------------------------------------------------------
    @property
    def mesh(self) -> Mesh:
        return self._mesh

    @property
    def exchange(self) -> str:
        return self._exchange

    @property
    def shape(self) -> tuple[int, int]:
        return (self._n_screen, self._n_toa)

    def init_state(self) -> HistogramState:
        zeros = jax.device_put(
            jnp.zeros((self._n_screen, self._n_toa), dtype=self._dtype),
            self._state_sharding,
        )
        scale = (
            jax.device_put(
                jnp.ones((), dtype=self._dtype), self._scalar_sharding
            )
            if self._decay is not None
            else None
        )
        return HistogramState(
            folded=zeros, window=jnp.array(zeros), scale=scale
        )

    def _shard_events(self, pixel_id, toa):
        n = pixel_id.shape[0]
        if n % self._n_data:
            raise ValueError(
                f"padded event count {n} must divide over data axis {self._n_data}"
            )
        from ..ops.event_batch import stage_for

        # One hop host->mesh (stage_for): dispatch_safe would commit the
        # batch to the DEFAULT device first and pay a second copy on the
        # resharded placement.
        return (
            stage_for(pixel_id, self._event_sharding),
            stage_for(toa, self._event_sharding),
        )

    @property
    def stage_key(self) -> tuple:
        """Cache key for pre-staged event shards (stage-once, ADR 0110):
        the placement depends only on the event sharding — mesh devices
        and data-axis extent — never on the projection layout, so every
        kernel sharing the mesh shares the staged shards."""
        devices = tuple(int(d.id) for d in self._mesh.devices.flat)
        return ("shard1", devices, self._n_data)

    def stage_events(self, pixel_id, toa):
        """Place one padded global batch onto the event sharding (one
        hop). ``step`` accepts the returned device arrays — already-placed
        arrays pass through ``stage_for`` untouched — so K jobs sharing a
        mesh stage each window's batch once via the window stream-cache
        (core/device_event_cache.py)."""
        return self._shard_events(pixel_id, toa)

    def step(self, state: HistogramState, pixel_id, toa) -> HistogramState:
        """Accumulate one padded global batch (host or pre-staged device
        arrays — see ``stage_events``)."""
        pid, t = self._shard_events(pixel_id, toa)
        lut_args = (self._lut_rep,) if self._has_lut else ()
        if self._decay is None:
            win = self._step(
                state.window, *lut_args, pid, t, self._unit_scale
            )
            return HistogramState(folded=state.folded, window=win)
        win, scale = self._step_decay(
            state.window, *lut_args, pid, t, state.scale
        )
        return HistogramState(folded=state.folded, window=win, scale=scale)

    def swap_projection(self, pixel_lut) -> bool:
        """Replace the pixel LUT on the running mesh without recompiling
        (ADR 0105): the table is a replicated jit argument, so a
        same-shape swap is one broadcast placement. Returns False for
        shape changes or LUT-less configurations (full rebuild); this is
        the sharded kernel's validity gate, mirroring the single-device
        ``EventHistogrammer.swap_projection``."""
        new = np.atleast_2d(np.asarray(pixel_lut, np.int32))
        if (
            self._proj.lut_host is None
            or new.shape != self._proj.lut_host.shape
        ):
            return False
        old_weights = self._proj.weights  # already mesh-replicated
        self._proj = EventProjection(
            toa_edges=self._edges,
            pixel_lut=new,
            n_screen=self._n_screen,
        )
        # Carry the replicated device array over: round-tripping it
        # through numpy would block on a d2h copy and lose the mesh
        # placement established in __init__. The new LUT is placed from
        # the host array directly — this is the per-swap live-geometry
        # path, so the default-device staging hop a jnp.asarray would add
        # is paid on every swap, not once.
        self._proj.weights = old_weights
        self._lut_rep = self._replicate(new)
        return True

    def clear_window(self, state: HistogramState) -> HistogramState:
        cum, win = self._clear_window(
            state.folded, state.window, state.scale
        )
        scale = (
            None if state.scale is None else jnp.ones_like(state.scale)
        )
        return HistogramState(folded=cum, window=win, scale=scale)

    def normalized(self, hist: jax.Array, monitor_counts) -> jax.Array:
        """hist / global monitor total — the monitor-normalized I(Q)-style
        output (BASELINE config 4). One-hop staging (stage_for), as in
        ``_shard_events``."""
        from ..ops.event_batch import stage_for

        return self._normalize(
            hist, stage_for(monitor_counts, self._event_sharding, dtype=self._dtype)
        )

    def read(self, state: HistogramState) -> tuple[np.ndarray, np.ndarray]:
        """Host copies of the (cumulative, window) views — same contract as
        ``EventHistogrammer.read`` (applies the lazy decay scale)."""
        cum, win = jax.device_get(
            self._views(state.folded, state.window, state.scale)
        )
        return np.asarray(cum), np.asarray(win)

    # -- state snapshot codec (ADR 0107, multichip shape) ------------------
    def dump_state_arrays(self, state: HistogramState) -> dict[str, np.ndarray]:
        """Gathered host copy of the sharded accumulation: snapshots are
        mesh-layout-independent, so a state dumped on one mesh restores
        onto a service with a different device count."""
        out = {
            "folded": np.asarray(jax.device_get(state.folded)),
            "window": np.asarray(jax.device_get(state.window)),
        }
        if state.scale is not None:
            out["scale"] = np.asarray(jax.device_get(state.scale))
        return out

    def restore_state_arrays(
        self, current: HistogramState, arrays: dict
    ) -> HistogramState | None:
        """Re-place dumped host arrays over THIS mesh's shardings, or
        None if they don't fit (shape-checked, never partially adopts)."""
        folded = np.asarray(arrays.get("folded"))
        window = np.asarray(arrays.get("window"))
        want = (self._n_screen, self._n_toa)
        if folded.shape != want or window.shape != want:
            return None
        has_scale = self._decay is not None
        if has_scale != ("scale" in arrays):
            return None
        return HistogramState(
            folded=jax.device_put(
                jnp.asarray(folded, dtype=self._dtype), self._state_sharding
            ),
            window=jax.device_put(
                jnp.asarray(window, dtype=self._dtype), self._state_sharding
            ),
            scale=(
                jax.device_put(
                    jnp.asarray(arrays["scale"], dtype=self._dtype),
                    self._scalar_sharding,
                )
                if has_scale
                else None
            ),
        )

    # Backwards-compatible alias.
    to_host = read

"""Multi-device sharded event histogrammer.

The multi-bank / long-axis scale-out path (BASELINE configs 3-4): screen
rows (detector banks) are sharded over the mesh's ``bank`` axis so a
histogram too large for one chip's HBM splits across chips, and the event
stream is sharded over the ``data`` axis with a ``psum`` merging per-shard
deltas over ICI. Monitor-normalized outputs use a second psum to form the
global monitor total on every shard.

Communication pattern per step (all XLA collectives, no NCCL analog):

    events [E] --split 'data'--> local scatter into local bank rows
    delta --psum('data')--> bank-replicated delta --add--> sharded state
    monitor counts --psum('data')--> global monitor total (for ratios)

Each bank shard sees the full event shard and drops events belonging to
other banks' rows (gather-free routing). For heavily bank-imbalanced
streams an all-to-all by destination bank would cut wasted work; measured
flat for uniform streams, so deferred.
"""

from __future__ import annotations

from functools import partial

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.histogram import HistogramState

__all__ = ["ShardedHistogrammer"]


class ShardedHistogrammer:
    """Scatter-add histogrammer with screen rows sharded over ``bank`` and
    events sharded over ``data`` mesh axes.

    The single-device equivalent is ``ops.histogram.EventHistogrammer``;
    this class accepts the same logical inputs (global pixel ids, toa) and
    produces the same global histogram, distributed.
    """

    def __init__(
        self,
        *,
        toa_edges: np.ndarray,
        n_screen: int,
        mesh: Mesh,
        pixel_lut: np.ndarray | None = None,
        decay: float | None = None,
        dtype=jnp.float32,
    ) -> None:
        toa_edges = np.asarray(toa_edges, dtype=np.float64)
        if not np.all(np.diff(toa_edges) > 0):
            raise ValueError("toa_edges must be strictly increasing")
        self._mesh = mesh
        self._n_bank = mesh.shape["bank"]
        self._n_data = mesh.shape["data"]
        if n_screen % self._n_bank:
            raise ValueError(
                f"n_screen={n_screen} must divide over bank axis {self._n_bank}"
            )
        self._rows_per_bank = n_screen // self._n_bank
        self._n_screen = n_screen
        self._n_toa = toa_edges.size - 1
        self._lo = float(toa_edges[0])
        self._hi = float(toa_edges[-1])
        self._inv_width = float(self._n_toa / (self._hi - self._lo))
        self._edges = toa_edges
        self._decay = decay
        self._dtype = dtype
        if pixel_lut is not None:
            lut = np.asarray(pixel_lut, dtype=np.int32)
            if lut.ndim != 1:
                raise ValueError("sharded histogrammer supports 1-D pixel_lut")
            # LUT replicated on every device: gather stays local.
            self._lut = jax.device_put(
                jnp.asarray(lut), NamedSharding(mesh, P())
            )
        else:
            self._lut = None

        self._state_sharding = NamedSharding(mesh, P("bank", None))
        self._event_sharding = NamedSharding(mesh, P("data"))
        self._scalar_sharding = NamedSharding(mesh, P())

        shard = partial(
            jax.shard_map,
            mesh=mesh,
            in_specs=(
                P("bank", None),  # window
                P("data"),  # pixel_id
                P("data"),  # toa
            ),
            out_specs=P("bank", None),
        )
        self._step = jax.jit(shard(self._step_local), donate_argnums=(0,))

        norm = partial(
            jax.shard_map,
            mesh=mesh,
            in_specs=(P("bank", None), P("data")),
            out_specs=P("bank", None),
        )
        self._normalize = jax.jit(norm(self._normalize_local))
        # Fold semantics as in EventHistogrammer: steps touch only the
        # window; the cumulative total is folded at publish rate.
        self._clear_window = jax.jit(
            lambda cum, win: (cum + win, jnp.zeros_like(win)),
            donate_argnums=(0, 1),
        )
        self._cum_view = jax.jit(lambda cum, win: cum + win)

    # -- local (per-shard) kernels ---------------------------------------
    def _step_local(self, win, pixel_id, toa):
        bank = jax.lax.axis_index("bank")
        row0 = bank * self._rows_per_bank
        tb = jnp.floor((toa - self._lo) * self._inv_width).astype(jnp.int32)
        t_ok = (toa >= self._lo) & (toa < self._hi)
        tb = jnp.clip(tb, 0, self._n_toa - 1)
        if self._lut is not None:
            n_pix = self._lut.shape[0]
            p_ok = (pixel_id >= 0) & (pixel_id < n_pix)
            screen = self._lut[jnp.clip(pixel_id, 0, n_pix - 1)]
            p_ok &= screen >= 0
        else:
            screen = pixel_id
            p_ok = (pixel_id >= 0) & (pixel_id < self._n_screen)
        local_row = screen - row0
        ok = p_ok & t_ok & (local_row >= 0) & (local_row < self._rows_per_bank)
        n_local = self._rows_per_bank * self._n_toa
        flat = jnp.where(ok, local_row * self._n_toa + tb, n_local)
        delta = jnp.zeros((n_local,), dtype=self._dtype)
        delta = delta.at[flat].add(1.0, mode="drop")
        delta = delta.reshape(self._rows_per_bank, self._n_toa)
        # Merge event shards: every data-shard scattered into its own copy.
        delta = jax.lax.psum(delta, "data")
        return win * self._decay + delta if self._decay is not None else win + delta

    def _normalize_local(self, hist, monitor_counts):
        # monitor_counts: per-event-shard scalar counts; global total via psum.
        total = jax.lax.psum(jnp.sum(monitor_counts), "data")
        return hist / jnp.maximum(total, 1.0)

    # -- public API -------------------------------------------------------
    @property
    def mesh(self) -> Mesh:
        return self._mesh

    @property
    def shape(self) -> tuple[int, int]:
        return (self._n_screen, self._n_toa)

    def init_state(self) -> HistogramState:
        zeros = jax.device_put(
            jnp.zeros((self._n_screen, self._n_toa), dtype=self._dtype),
            self._state_sharding,
        )
        return HistogramState(folded=zeros, window=jnp.array(zeros))

    def _shard_events(self, pixel_id, toa):
        n = pixel_id.shape[0]
        if n % self._n_data:
            raise ValueError(
                f"padded event count {n} must divide over data axis {self._n_data}"
            )
        from ..ops.event_batch import dispatch_safe

        pid = jax.device_put(
            jnp.asarray(dispatch_safe(pixel_id)), self._event_sharding
        )
        t = jax.device_put(jnp.asarray(dispatch_safe(toa)), self._event_sharding)
        return pid, t

    def step(self, state: HistogramState, pixel_id, toa) -> HistogramState:
        """Accumulate one padded global batch (host or device arrays)."""
        pid, t = self._shard_events(pixel_id, toa)
        win = self._step(state.window, pid, t)
        return HistogramState(folded=state.folded, window=win)

    def clear_window(self, state: HistogramState) -> HistogramState:
        cum, win = self._clear_window(state.folded, state.window)
        return HistogramState(folded=cum, window=win)

    def normalized(self, hist: jax.Array, monitor_counts) -> jax.Array:
        """hist / global monitor total — the monitor-normalized I(Q)-style
        output (BASELINE config 4)."""
        mc = jax.device_put(
            jnp.asarray(monitor_counts, dtype=self._dtype), self._event_sharding
        )
        return self._normalize(hist, mc)

    def read(self, state: HistogramState) -> tuple[np.ndarray, np.ndarray]:
        """Host copies of the (cumulative, window) views — same contract as
        ``EventHistogrammer.read``."""
        cum, win = jax.device_get(
            (self._cum_view(state.folded, state.window), state.window)
        )
        return np.asarray(cum), np.asarray(win)

    # Backwards-compatible alias.
    to_host = read

"""Multi-device sharded event histogrammer.

The multi-bank / long-axis scale-out path (BASELINE configs 3-4): screen
rows (detector banks) are sharded over the mesh's ``bank`` axis so a
histogram too large for one chip's HBM splits across chips, and the event
stream is sharded over the ``data`` axis. Parity with the single-device
``EventHistogrammer``: replica LUTs, per-pixel weights, decay, and the
fold semantics (steps touch only the window; the cumulative total folds at
publish rate).

Two exchange strategies merge the data shards (all XLA collectives over
ICI, no NCCL analog):

- ``delta_psum``: every data shard scatters into its own dense copy of
  its bank rows, then ``psum('data')`` merges. Per-step traffic is
  O(rows_per_bank * n_toa) per device regardless of how sparse the batch
  is — fine for small bin spaces (DREAM-size banks), ruinous at LOKI
  scale (1.5M x 100 bins: ~150 MB per shard per step).
- ``event_gather``: ``all_gather('data')`` the *event* shards instead —
  every device then scatters the full batch into its own bank rows, and
  the data-replicated window copies stay identical with no dense
  reduction at all. Per-step traffic is O(n_events * (data-1)/data),
  independent of bin-space size.

``exchange='auto'`` compares the two strategies' ACTUAL per-step wire
bytes — the dense delta each device psums (rows_per_bank x n_toa x
dtype itemsize) against the event bytes each device gathers from the
other data shards (n_events x 8 B x (data-1)/data) — and picks the
cheaper one. ``batch_hint`` (expected events per padded batch; default
the 4M headline batch) supplies the event count the crossover needs at
construction time. Events are also replicated across the ``bank`` axis
by their P('data') sharding, so each bank shard routes gather-free: it
scatters the events landing in its rows and drops the rest via the dump
bin.
"""

from __future__ import annotations

from functools import partial

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.histogram import EventProjection, HistogramState
from .mesh import shard_map

__all__ = ["ShardedHistogrammer"]

#: Default expected events per padded batch for the 'auto' exchange
#: crossover when the caller gives no hint: the 4M-event headline batch
#: the bench and the LOKI-scale ingest budget are sized around (PERF.md).
_DEFAULT_BATCH_HINT = 1 << 22

#: Wire bytes per event crossing the gather: int32 pixel_id + float32 toa.
_EVENT_WIRE_BYTES = 8


class ShardedHistogrammer:
    """Scatter-add histogrammer with screen rows sharded over ``bank`` and
    events sharded over ``data`` mesh axes.

    The single-device equivalent is ``ops.histogram.EventHistogrammer``;
    this class accepts the same logical inputs (global pixel ids, toa) and
    produces the same global histogram, distributed.
    """

    def __init__(
        self,
        *,
        toa_edges: np.ndarray,
        n_screen: int,
        mesh: Mesh,
        pixel_lut: np.ndarray | None = None,
        pixel_weights: np.ndarray | None = None,
        decay: float | None = None,
        exchange: str = "auto",
        dtype=jnp.float32,
        batch_hint: int | None = None,
    ) -> None:
        if exchange not in ("auto", "delta_psum", "event_gather"):
            raise ValueError(f"Unknown exchange {exchange!r}")
        self._mesh = mesh
        self._n_bank = mesh.shape["bank"]
        self._n_data = mesh.shape["data"]
        if n_screen % self._n_bank:
            raise ValueError(
                f"n_screen={n_screen} must divide over bank axis {self._n_bank}"
            )
        # One projection kernel shared with EventHistogrammer: identical
        # TOA binning (incl. non-uniform edges), LUT/replica routing and
        # weight semantics; only the row window differs per bank shard.
        self._proj = EventProjection(
            toa_edges=toa_edges,
            pixel_lut=pixel_lut,
            pixel_weights=pixel_weights,
            n_screen=n_screen,
        )
        # Weights replicated on every device: gathers stay local. The
        # LUT rides the jitted step as an ARGUMENT (ADR 0105) so a
        # live-geometry rebuild swaps tables without recompiling; it is
        # replicated explicitly below.
        self._has_lut = self._proj.lut_host is not None
        self._replicate = lambda x: jax.device_put(
            x, NamedSharding(mesh, P())
        )
        # place_constants replicates the LUT straight from its HOST copy
        # (one placement, no default-device staging hop) and re-places
        # the weights; the replicated LUT then rides the jitted step as
        # an argument (ADR 0105).
        self._proj.place_constants(self._replicate)
        self._lut_rep = self._proj.lut if self._has_lut else None
        self._rows_per_bank = n_screen // self._n_bank
        self._n_screen = n_screen
        self._n_toa = self._proj.n_toa
        self._edges = self._proj.edges
        self._decay = decay
        self._dtype = dtype
        self._batch_hint = int(
            _DEFAULT_BATCH_HINT if batch_hint is None else batch_hint
        )
        if exchange == "auto":
            exchange = self._resolve_exchange(
                rows_per_bank=self._rows_per_bank,
                n_toa=self._n_toa,
                n_data=self._n_data,
                dtype=dtype,
                batch_hint=self._batch_hint,
            )
        self._exchange = exchange

        self._state_sharding = NamedSharding(mesh, P("bank", None))
        self._event_sharding = NamedSharding(mesh, P("data"))
        self._scalar_sharding = NamedSharding(mesh, P())
        # The no-decay step's unit update magnitude, staged once: building
        # it per step would dispatch a host->device scalar transfer on
        # every batch (graftlint JGL006).
        self._unit_scale = jax.device_put(
            jnp.asarray(1.0, self._dtype), self._scalar_sharding
        )

        lut_specs = (P(),) if self._has_lut else ()  # replicated LUT arg
        shard = partial(
            shard_map,
            mesh=mesh,
            in_specs=(
                P("bank", None),  # window
                *lut_specs,
                P("data"),  # pixel_id
                P("data"),  # toa
                P(),  # inv_scale (replicated lazy-decay magnitude)
            ),
            out_specs=P("bank", None),
            # event_gather keeps the window replicated over 'data' by
            # construction (identical full-batch scatter on every copy
            # after the all_gather); the static varying-mesh-axes check
            # cannot infer that through the scatter, so only that mode
            # disables it — delta_psum keeps the safety net.
            check_vma=(self._exchange != "event_gather"),
        )
        if self._has_lut:

            def _local(win, lut, pid, toa, inv_scale):
                return self._step_local(win, pid, toa, inv_scale, lut=lut)

        else:

            def _local(win, pid, toa, inv_scale):
                return self._step_local(win, pid, toa, inv_scale)

        sharded_step = shard(_local)
        # The traceable (un-jitted) step body: the mesh tick program
        # (parallel/mesh_tick.py, ADR 0115) composes it with the packed
        # publish bodies under ONE outer jit via ``tick_step``.
        self._step_body = sharded_step
        self._decay_body = None
        self._step = jax.jit(sharded_step, donate_argnums=(0,))

        if decay is not None:
            from ..ops.histogram import EventHistogrammer as _EH

            def _step_decay(win, *args):
                # Lazy decay fused into the one jitted program (the
                # single-device kernel does the same inside _advance):
                # scale shrinks, updates grow by 1/scale, renormalize on
                # underflow — no per-batch eager dispatches.
                *rest, scale = args
                scale = scale * decay
                win = sharded_step(win, *rest, 1.0 / scale)
                return jax.lax.cond(
                    scale < _EH._SCALE_FLOOR,
                    lambda w, sc: (w * sc, jnp.ones_like(sc)),
                    lambda w, sc: (w, sc),
                    win,
                    scale,
                )

            self._decay_body = _step_decay
            self._step_decay = jax.jit(_step_decay, donate_argnums=(0,))

        # Fused K-state variant (one dispatch advances K donated states
        # from ONE staged batch; the jit caches one program per K) — the
        # mesh counterpart of EventHistogrammer._step_fused, feeding the
        # fused-stepping layer and the mesh tick program (ADR 0115).
        self._fused = jax.jit(self._tick_step_impl, donate_argnums=(0,))

        norm = partial(
            shard_map,
            mesh=mesh,
            in_specs=(P("bank", None), P("data")),
            out_specs=P("bank", None),
        )
        self._normalize = jax.jit(norm(self._normalize_local))
        # Fold semantics as in EventHistogrammer: steps touch only the
        # window; the cumulative total is folded at publish rate.
        def _physical(win, scale):
            return win if scale is None else win * scale

        self._clear_window = jax.jit(
            lambda cum, win, scale: (
                cum + _physical(win, scale),
                jnp.zeros_like(win),
            ),
            donate_argnums=(0, 1),
        )
        self._views = jax.jit(
            lambda cum, win, scale: (
                cum + _physical(win, scale),
                _physical(win, scale),
            )
        )

    @staticmethod
    def _resolve_exchange(
        *, rows_per_bank: int, n_toa: int, n_data: int, dtype, batch_hint: int
    ) -> str:
        """The cheaper data-shard merge for this configuration, by ACTUAL
        per-step bytes moved per device.

        - delta_psum: every device reduces a dense copy of its bank rows
          — ``rows_per_bank * n_toa * itemsize`` bytes, batch-size
          independent.
        - event_gather: every device receives the other data shards'
          events — ``n_events * 8 B * (data-1)/data`` bytes, bin-space
          independent (and zero when data == 1: the all_gather is the
          identity, so gather always wins a single-data-shard mesh).

        The old heuristic compared bins against a hard-coded 1<<20
        constant regardless of batch size or dtype, which mispicks on
        both sides of the crossover: a small-batch service on mid-size
        banks paid dense deltas that a cheap gather would beat, and a
        64M-event burst on just-over-threshold banks gathered more
        bytes than the delta it avoided (pinned both ways in
        tests/parallel/sharded_hist_test.py).
        """
        delta_bytes = rows_per_bank * n_toa * np.dtype(dtype).itemsize
        gather_bytes = batch_hint * _EVENT_WIRE_BYTES * (n_data - 1) / n_data
        return "event_gather" if gather_bytes < delta_bytes else "delta_psum"

    # -- local (per-shard) kernels ---------------------------------------
    def _step_local(self, win, pixel_id, toa, inv_scale, lut=None):
        """One shard's step. ``inv_scale`` is the lazy-decay update
        magnitude (1.0 without decay): the dense ``win * decay`` multiply
        the naive formulation would pay per step is folded into the
        scatter updates instead, exactly as in EventHistogrammer."""
        bank = jax.lax.axis_index("bank")
        row0 = bank * self._rows_per_bank
        n_local = self._rows_per_bank * self._n_toa

        if self._exchange == "event_gather":
            # Merge data shards by gathering the (small) event arrays;
            # every data-replicated window copy then applies the identical
            # full-batch scatter — no dense reduction. The dump index
            # (n_local) is out of bounds of the window and dropped.
            pixel_id = jax.lax.all_gather(
                pixel_id, "data", axis=0, tiled=True
            )
            toa = jax.lax.all_gather(toa, "data", axis=0, tiled=True)
            flat, w = self._proj.flat_and_weights(
                pixel_id, toa, row0=row0, n_rows=self._rows_per_bank, lut=lut
            )
            updates = (
                inv_scale if w is None else w.astype(self._dtype) * inv_scale
            )
            return (
                win.reshape(-1)
                .at[flat]
                .add(updates, mode="drop")
                .reshape(win.shape)
            )

        # delta_psum: scatter into a fresh local delta, merge over 'data'.
        flat, w = self._proj.flat_and_weights(
            pixel_id, toa, row0=row0, n_rows=self._rows_per_bank, lut=lut
        )
        updates = inv_scale if w is None else w.astype(self._dtype) * inv_scale
        delta = jnp.zeros((n_local + 1,), dtype=self._dtype)
        delta = delta.at[flat].add(updates, mode="drop")[:n_local]
        delta = delta.reshape(self._rows_per_bank, self._n_toa)
        delta = jax.lax.psum(delta, "data")
        return win + delta

    def _normalize_local(self, hist, monitor_counts):
        # monitor_counts: per-event-shard scalar counts; global total via psum.
        total = jax.lax.psum(jnp.sum(monitor_counts), "data")
        return hist / jnp.maximum(total, 1.0)

    # -- public API -------------------------------------------------------
    @property
    def mesh(self) -> Mesh:
        return self._mesh

    @property
    def exchange(self) -> str:
        return self._exchange

    @property
    def shape(self) -> tuple[int, int]:
        return (self._n_screen, self._n_toa)

    def init_state(self) -> HistogramState:
        zeros = jax.device_put(
            jnp.zeros((self._n_screen, self._n_toa), dtype=self._dtype),
            self._state_sharding,
        )
        scale = (
            jax.device_put(
                jnp.ones((), dtype=self._dtype), self._scalar_sharding
            )
            if self._decay is not None
            else None
        )
        return HistogramState(
            folded=zeros, window=jnp.array(zeros), scale=scale
        )

    def _shard_events(self, pixel_id, toa):
        n = pixel_id.shape[0]
        if n % self._n_data:
            raise ValueError(
                f"padded event count {n} must divide over data axis {self._n_data}"
            )
        from ..ops.event_batch import stage_for

        # One hop host->mesh (stage_for): dispatch_safe would commit the
        # batch to the DEFAULT device first and pay a second copy on the
        # resharded placement.
        return (
            stage_for(pixel_id, self._event_sharding),
            stage_for(toa, self._event_sharding),
        )

    @property
    def stage_key(self) -> tuple:
        """Cache key for pre-staged event shards (stage-once, ADR 0110):
        the placement depends only on the event sharding — mesh devices
        and data-axis extent — never on the projection layout, so every
        kernel sharing the mesh shares the staged shards."""
        devices = tuple(int(d.id) for d in self._mesh.devices.flat)
        return ("shard1", devices, self._n_data)

    # -- serving-tier surface (ADR 0110/0114/0115) -------------------------
    # The same duck-typed contract EventHistogrammer exposes, so mesh-
    # backed workflows ride the stage-once cache, the fused-stepping
    # layer, the combined publish and the one-dispatch tick program
    # exactly like single-device ones — the mesh stops being a
    # standalone demo and becomes a serving topology.

    @property
    def n_toa(self) -> int:
        return self._n_toa

    @property
    def n_screen(self) -> int:
        return self._n_screen

    @property
    def toa_edges(self) -> np.ndarray:
        return self._edges

    @property
    def decay(self) -> float | None:
        return self._decay

    @property
    def layout_digest(self) -> str:
        """The projection layout's content fingerprint (the static-
        publish cache token, ADR 0113) — a LUT/edge swap re-keys it."""
        return self._proj.layout_digest

    @property
    def supports_host_flatten(self) -> bool:
        """The mesh kernel projects on DEVICE (each bank shard routes its
        own rows); the host-flatten fast path does not apply."""
        return False

    @property
    def fuse_key(self) -> tuple:
        """Grouping key for fused stepping and tick programs
        (core/job_manager.py): equal keys promise identical staged wire
        AND an identical sharded step program — mesh devices, both axis
        extents, the exchange strategy, accumulation semantics, and the
        projection layout all participate."""
        devices = tuple(int(d.id) for d in self._mesh.devices.flat)
        return (
            "fuse-mesh",
            devices,
            self._n_data,
            self._n_bank,
            self._exchange,
            self._decay,
            np.dtype(self._dtype).str,
            self._proj.layout_digest,
        )

    def tick_staging(
        self,
        batch,
        cache,
        *,
        batch_tag: str = "",
        pool=None,
        device=None,
    ) -> tuple:
        """The staged mesh wire as a flat tuple ``(lut, pixel_id, toa)``
        shaped for ``tick_step``'s trailing arguments (ops/tick.py).

        The (pixel_id, toa) pair is placed onto the P('data') event
        sharding ONCE per window per (stream, tag, mesh) through the
        stream cache — the staged shards are layout-independent, so
        every kernel sharing the mesh shares them; the replicated LUT
        rides as an argument (ADR 0105: swaps stay transfers, never
        retraces). ``pool`` (host-flatten chunking) and ``device``
        (single-device slice placement, parallel/mesh_tick.py) do not
        apply to the mesh wire — the mesh IS the placement.
        """
        del pool, device  # single-device staging knobs; the mesh places
        pid, toa = batch.pixel_id, batch.toa

        def stage():
            return self._shard_events(pid, toa)

        if cache is None:
            staged = stage()
        else:
            staged = cache.get_or_stage(
                (batch_tag,) + self.stage_key, stage
            )
        return (self._lut_rep,) + tuple(staged)

    def _tick_step_impl(self, states, lut, pixel_id, toa):
        # graft: key-derived=_has_lut,_step_body,_decay_body,_unit_scale
        # pure functions of keyed configuration: fuse_key carries the
        # layout digest (which fingerprints the LUT _has_lut reflects),
        # the exchange/decay/dtype the step bodies were compiled from,
        # and the dtype the staged unit scale was built with.
        states = tuple(states)
        lut_args = (lut,) if self._has_lut else ()
        if self._decay is None:
            return tuple(
                HistogramState(
                    folded=s.folded,
                    window=self._step_body(
                        s.window, *lut_args, pixel_id, toa, self._unit_scale
                    ),
                    scale=None,
                )
                for s in states
            )

        def stepped(s: HistogramState) -> HistogramState:
            win, scale = self._decay_body(
                s.window, *lut_args, pixel_id, toa, s.scale
            )
            return HistogramState(folded=s.folded, window=win, scale=scale)

        # Trace-unrolled over the (small, stable-K) states tuple — the
        # same shape as EventHistogrammer's fused impls.
        return tuple(stepped(s) for s in states)

    def tick_step(self, states, *staged):
        """TRACEABLE fused step over ``tick_staging``'s arrays — the tick
        program (ops/tick.py / parallel/mesh_tick.py) composes this with
        the members' packed publish bodies so the collective step and
        the publish reductions ride ONE dispatch. Applies the exact
        per-state program ``step`` runs (same shard_map body, same lazy
        decay protocol), so tick results are identical to separate
        stepping."""
        return self._tick_step_impl(tuple(states), *staged)

    def step_many(
        self, states, batch, *, cache=None, batch_tag=""
    ) -> tuple[HistogramState, ...]:
        """Advance K independent mesh-sharded states from ONE staged
        batch in ONE jitted dispatch (the fused-stepping layer's kernel
        entry, core/job_manager.py). All states are donated."""
        states = tuple(states)
        if not states:
            return ()
        staged = self.tick_staging(batch, cache, batch_tag=batch_tag)
        return self._fused(states, *staged)

    def step_batch(
        self, state: HistogramState, batch, *, cache=None, batch_tag=""
    ) -> HistogramState:
        """Accumulate one staged ``EventBatch`` through the stream cache
        (the workflow-private path's entry; same keys as ``step_many``
        and the tick program, so whichever consumer stages first, the
        rest share the placed shards by reference)."""
        staged = self.tick_staging(batch, cache, batch_tag=batch_tag)
        (new,) = self._fused((state,), *staged)
        return new

    def stage_events(
        self, batch, cache, *, batch_tag: str = "", pool=None
    ) -> None:
        """Warm the window stream-cache with this mesh's staged wire —
        the pipelined ingest's prestage entry (ADR 0111), same contract
        as ``EventHistogrammer.stage_events``: exactly the staging the
        step/tick paths run, so a prestaged window is a guaranteed hit."""
        if cache is None:
            return
        self.tick_staging(batch, cache, batch_tag=batch_tag, pool=pool)

    def views_of(
        self, state: HistogramState
    ) -> tuple[jax.Array, jax.Array]:
        """Traceable (cumulative, window) views, ``[n_screen, n_toa]``,
        REPLICATED over the mesh — the composition surface the packed
        publish programs consume (ops/publish.py).

        The replication constraint is the publish-rate gather that keeps
        readback O(1): downstream reductions run on a replicated value,
        so the packed output vector is replicated by construction and
        one ``device_get`` serves the whole mesh (and the reduction HLO
        matches the single-device program's — the mesh↔single-device
        parity contract, tests/parallel/mesh_tick_test.py). Per-step
        collectives stay O(delta/gather); only the ~1 Hz publish pays
        the window gather."""
        replicated = NamedSharding(self._mesh, P())
        win = self.physical_window(state)
        win = jax.lax.with_sharding_constraint(win, replicated)
        cum = win + jax.lax.with_sharding_constraint(
            state.folded, replicated
        )
        return cum, win

    def physical_window(self, state: HistogramState) -> jax.Array:
        """The window in physical counts (applies the lazy decay scale);
        traceable, sharding-preserving."""
        if state.scale is None:
            return state.window
        return state.window * state.scale

    def fold_window(self, state: HistogramState) -> HistogramState:
        """Traceable window fold (the publish-program composition
        counterpart of ``clear_window``): the cumulative absorbs the
        physical window in place — both leaves keep their P('bank')
        sharding, so the fold is collective-free."""
        return HistogramState(
            folded=state.folded + self.physical_window(state),
            window=jnp.zeros_like(state.window),
            scale=(
                None if state.scale is None else jnp.ones_like(state.scale)
            ),
        )

    def clear(self, state: HistogramState) -> HistogramState:
        """Zero the full accumulation (run-transition reset), keeping
        every leaf's mesh sharding."""
        return HistogramState(
            folded=jnp.zeros_like(state.folded),
            window=jnp.zeros_like(state.window),
            scale=(
                None if state.scale is None else jnp.ones_like(state.scale)
            ),
        )

    def step(self, state: HistogramState, pixel_id, toa) -> HistogramState:
        """Accumulate one padded global batch (host or pre-staged device
        arrays — see ``stage_events``)."""
        pid, t = self._shard_events(pixel_id, toa)
        lut_args = (self._lut_rep,) if self._has_lut else ()
        if self._decay is None:
            win = self._step(
                state.window, *lut_args, pid, t, self._unit_scale
            )
            return HistogramState(folded=state.folded, window=win)
        win, scale = self._step_decay(
            state.window, *lut_args, pid, t, state.scale
        )
        return HistogramState(folded=state.folded, window=win, scale=scale)

    def swap_projection(self, pixel_lut) -> bool:
        """Replace the pixel LUT on the running mesh without recompiling
        (ADR 0105): the table is a replicated jit argument, so a
        same-shape swap is one broadcast placement. Returns False for
        shape changes or LUT-less configurations (full rebuild); this is
        the sharded kernel's validity gate, mirroring the single-device
        ``EventHistogrammer.swap_projection``."""
        new = np.atleast_2d(np.asarray(pixel_lut, np.int32))
        if (
            self._proj.lut_host is None
            or new.shape != self._proj.lut_host.shape
        ):
            return False
        old = self._proj
        self._proj = EventProjection(
            toa_edges=self._edges,
            pixel_lut=new,
            n_screen=self._n_screen,
        )
        # Carry the replicated device array over: round-tripping it
        # through numpy would block on a d2h copy and lose the mesh
        # placement established in __init__. The new LUT is placed from
        # the host array directly — this is the per-swap live-geometry
        # path, so the default-device staging hop a jnp.asarray would add
        # is paid on every swap, not once. The HOST weights copy rides
        # along so the rebuilt layout_digest — the key every staging/
        # fusion/static-publish cache hangs off (ADR 0110/0113) — still
        # fingerprints the weights.
        self._proj.weights = old.weights
        self._proj._weights_host = old._weights_host
        self._lut_rep = self._replicate(new)
        return True

    def clear_window(self, state: HistogramState) -> HistogramState:
        cum, win = self._clear_window(
            state.folded, state.window, state.scale
        )
        scale = (
            None if state.scale is None else jnp.ones_like(state.scale)
        )
        return HistogramState(folded=cum, window=win, scale=scale)

    def normalized(self, hist: jax.Array, monitor_counts) -> jax.Array:
        """hist / global monitor total — the monitor-normalized I(Q)-style
        output (BASELINE config 4). One-hop staging (stage_for), as in
        ``_shard_events``."""
        from ..ops.event_batch import stage_for

        return self._normalize(
            hist, stage_for(monitor_counts, self._event_sharding, dtype=self._dtype)
        )

    def read(self, state: HistogramState) -> tuple[np.ndarray, np.ndarray]:
        """Host copies of the (cumulative, window) views — same contract as
        ``EventHistogrammer.read`` (applies the lazy decay scale)."""
        cum, win = jax.device_get(
            self._views(state.folded, state.window, state.scale)
        )
        return np.asarray(cum), np.asarray(win)

    # -- state snapshot codec (ADR 0107, multichip shape) ------------------
    def dump_state_arrays(self, state: HistogramState) -> dict[str, np.ndarray]:
        """Gathered host copy of the sharded accumulation: snapshots are
        mesh-layout-independent, so a state dumped on one mesh restores
        onto a service with a different device count."""
        out = {
            "folded": np.asarray(jax.device_get(state.folded)),
            "window": np.asarray(jax.device_get(state.window)),
        }
        if state.scale is not None:
            out["scale"] = np.asarray(jax.device_get(state.scale))
        return out

    def restore_state_arrays(
        self, current: HistogramState, arrays: dict
    ) -> HistogramState | None:
        """Re-place dumped host arrays over THIS mesh's shardings, or
        None if they don't fit (shape-checked, never partially adopts)."""
        folded = np.asarray(arrays.get("folded"))
        window = np.asarray(arrays.get("window"))
        want = (self._n_screen, self._n_toa)
        if folded.shape != want or window.shape != want:
            return None
        has_scale = self._decay is not None
        if has_scale != ("scale" in arrays):
            return None
        return HistogramState(
            folded=jax.device_put(
                jnp.asarray(folded, dtype=self._dtype), self._state_sharding
            ),
            window=jax.device_put(
                jnp.asarray(window, dtype=self._dtype), self._state_sharding
            ),
            scale=(
                jax.device_put(
                    jnp.asarray(arrays["scale"], dtype=self._dtype),
                    self._scalar_sharding,
                )
                if has_scale
                else None
            ),
        )

    # Backwards-compatible alias.
    to_host = read

"""Multi-device Q-family histogrammer: the TABLE is what gets sharded.

The precompiled (pixel, toa-bin) -> bin tables of the reduction
families (ops/qhistogram.py) dominate device memory at scale — DREAM's
mantle Bragg table is ~0.5 GB int16 — while the OUTPUT bin space is
tiny (10^2-10^4 bins). So the scaling shape is the inverse of the
detector-view histogrammer (sharded_hist.py, which shards screen rows):

- table rows shard over the mesh's ``bank`` axis (each device holds
  ``n_rows / n_bank`` contiguous pixel rows);
- the event batch is replicated (its P() sharding broadcasts it);
- each device scatters only the events landing in its row range — the
  bank-local id shift routes them for free, everything else drops via
  the OOB bin;
- one ``psum('bank')`` over the small [n_bins] delta merges the
  partials, keeping the replicated QState identical on all devices.

Per-step ICI traffic is O(n_bins) — independent of both table size and
event count — so the table can grow with instrument cardinality while
collectives stay constant. The table rides the shard_mapped step as an
ARGUMENT (ADR 0105): a live recalibration (emission offset, sample
angle) re-shards a rebuilt table with one host->device transfer per
shard and zero recompiles.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.event_batch import sanitize_pixel_id, stage_for
from ..ops.qhistogram import PixelBinMap, QState, table_scatter_delta
from .mesh import shard_map

__all__ = ["ShardedQHistogrammer"]


def _pad_to_shards(table: np.ndarray, n_shards: int) -> np.ndarray:
    """Pad rows to the shard boundary with drop rows (-1): padded pixels
    can never be hit (ids beyond the bank range shift OOB)."""
    pad = (-table.shape[0]) % n_shards
    if pad:
        table = np.concatenate(
            [table, np.full((pad, table.shape[1]), -1, dtype=table.dtype)]
        )
    return table


class ShardedQHistogrammer:
    """Table-row-sharded scatter-add into a replicated Q-bin state.

    Single-device equivalent: ``ops.qhistogram.QHistogrammer`` — same
    logical inputs (global pixel ids, toa, monitor count), same QState
    semantics (window folds, cumulative monotone, monitor channel).
    """

    def __init__(
        self,
        *,
        qmap: PixelBinMap,
        toa_edges: np.ndarray,
        n_q: int,
        mesh: Mesh,
        axis: str = "bank",
        dtype=jnp.float32,
        method: str = "scatter",
    ) -> None:
        if method not in ("auto", "scatter", "pallas"):
            raise ValueError(f"Unknown method {method!r}")
        if method == "auto":
            # Same resolution as the single-device QHistogrammer: the
            # per-shard delta is a full [n_q] vector either way, so the
            # VMEM bound is the global one.
            from ..ops.pallas_hist import MAX_PALLAS_BINS

            method = (
                "pallas"
                if (
                    n_q + 1 <= MAX_PALLAS_BINS
                    and jax.default_backend() == "tpu"
                )
                else "scatter"
            )
        self._method = method
        table, id_base = qmap.table, int(qmap.id_base)
        toa_edges = np.asarray(toa_edges, dtype=np.float64)
        if table.shape[1] != toa_edges.size - 1:
            raise ValueError("qmap toa axis must match toa_edges")
        if table.max(initial=-1) >= n_q:
            raise ValueError("qmap entries must be < n_q")
        self._mesh = mesh
        self._axis = axis
        n_shards = mesh.shape[axis]
        table = _pad_to_shards(table, n_shards)
        self._rows_per_shard = table.shape[0] // n_shards
        self._id_base = id_base
        self._n_q = int(n_q)
        self._lo = float(toa_edges[0])
        self._hi = float(toa_edges[-1])
        n_toa = toa_edges.size - 1
        self._n_toa = n_toa
        self._inv_width = float(n_toa / (self._hi - self._lo))
        self._dtype = dtype
        self._table_sharding = NamedSharding(mesh, P(axis, None))
        self._table = jax.device_put(table, self._table_sharding)

        rows = self._rows_per_shard

        def _step(state, table_shard, pixel_id, toa, monitor_count):
            # Rows are contiguous: shard i covers
            # [id_base + i*rows, id_base + (i+1)*rows). Same traceable
            # core as the single-device kernel, with the shard-local base.
            shard = jax.lax.axis_index(axis)
            delta = table_scatter_delta(
                table_shard,
                pixel_id,
                toa,
                id_base=self._id_base + shard * rows,
                lo=self._lo,
                hi=self._hi,
                inv_width=self._inv_width,
                n_bins=self._n_q,
                dtype=dtype,
                method=self._method,
            )
            # The ONLY collective: O(n_q) regardless of table size.
            delta = jax.lax.psum(delta, axis)
            mc = jnp.asarray(monitor_count, dtype=dtype)
            return QState(
                cumulative=state.cumulative + delta,
                window=state.window + delta,
                monitor_cumulative=state.monitor_cumulative + mc,
                monitor_window=state.monitor_window + mc,
            )

        state_specs = QState(
            cumulative=P(), window=P(), monitor_cumulative=P(),
            monitor_window=P(),
        )
        self._step = jax.jit(
            shard_map(
                _step,
                mesh=mesh,
                in_specs=(state_specs, P(axis, None), P(), P(), P()),
                out_specs=state_specs,
                # Interpret-mode pallas inside shard_map trips a JAX vma
                # propagation gap (dynamic_slice with mixed varying
                # axes); the error message itself prescribes this
                # workaround. Scatter keeps full vma checking.
                check_vma=(method != "pallas"),
            ),
            donate_argnums=(0,),
        )
        self._replicated_sharding = NamedSharding(mesh, P())
        self._replicate = lambda x: jax.device_put(
            x, self._replicated_sharding
        )

    @property
    def mesh(self) -> Mesh:
        return self._mesh

    @property
    def n_q(self) -> int:
        return self._n_q

    @property
    def rows_per_shard(self) -> int:
        return self._rows_per_shard

    def init_state(self) -> QState:
        zeros = self._replicate(jnp.zeros((self._n_q,), dtype=self._dtype))
        scalar = self._replicate(jnp.zeros((), dtype=self._dtype))
        return QState(
            cumulative=zeros,
            window=jnp.array(zeros),
            monitor_cumulative=scalar,
            monitor_window=jnp.array(scalar),
        )

    def step(
        self, state: QState, pixel_id, toa, monitor_count: float = 0.0
    ) -> QState:
        # Same ingest-boundary guards as every other path: wide dtypes
        # sanitize (no int32 wrap) and staging copies decouple reused
        # host buffers from the async dispatch (event_batch.py). Device
        # arrays pass through untouched (already int32/float32, no sync).
        if not isinstance(pixel_id, jax.Array):
            pixel_id = sanitize_pixel_id(np.asarray(pixel_id))

        # One hop host->mesh (stage_for): dispatch_safe would commit the
        # batch to the DEFAULT device and pay a second device->device
        # copy on the replicated placement.
        sharding = self._replicated_sharding
        return self._step(
            state,
            self._table,
            stage_for(pixel_id, sharding, dtype=jnp.int32),
            stage_for(toa, sharding, dtype=jnp.float32),
            stage_for(monitor_count, sharding, dtype=self._dtype),
        )

    def swap_table(self, qmap: PixelBinMap) -> None:
        """Re-shard a rebuilt table (live recalibration) — one transfer
        per shard, no recompile (the table is a step argument)."""
        table, id_base = qmap.table, int(qmap.id_base)
        if id_base != self._id_base:
            raise ValueError(
                f"swap_table id_base {id_base} != compiled {self._id_base}"
            )
        if table.max(initial=-1) >= self._n_q:
            raise ValueError("qmap entries must be < n_q")
        if table.shape[1] != self._n_toa:
            raise ValueError(
                "swap_table must keep the toa binning: the step's TOA "
                f"projection compiled against {self._n_toa} bins"
            )
        n_shards = self._mesh.shape[self._axis]
        table = _pad_to_shards(table, n_shards)
        if table.shape[0] // n_shards != self._rows_per_shard:
            raise ValueError("swap_table must keep the row count")
        self._table = jax.device_put(table, self._table_sharding)

    def clear_window(self, state: QState) -> QState:
        return QState(
            cumulative=state.cumulative,
            window=jnp.zeros_like(state.window),
            monitor_cumulative=state.monitor_cumulative,
            monitor_window=jnp.zeros_like(state.monitor_window),
        )

    def read(self, state: QState) -> tuple[np.ndarray, np.ndarray, float, float]:
        """(cumulative, window, monitor_cumulative, monitor_window)."""
        return (
            np.asarray(state.cumulative),
            np.asarray(state.window),
            float(state.monitor_cumulative),
            float(state.monitor_window),
        )

"""Device-mesh serving tier: sharded kernels, mesh tick programs, placement.

The reference scales out with OS processes partitioned by Kafka topic
(SURVEY.md section 2.10) and has no collective backend at all;
compute-level scale-out here is TPU-native instead: a
``jax.sharding.Mesh`` with a ``data`` axis (event-stream shards, the DP
analog) and a ``bank`` axis (bin-space shards over detector banks/screen
rows — the TP/SP analog for a histogramming workload), with XLA
collectives riding ICI for cross-shard merges and monitor/detector
normalization. Kafka over DCN remains the inter-host system bus,
unchanged.

This package is the production serving topology, not a demo (ADR 0115):
the sharded kernels expose the same stage-once / fused-step / tick
contract as the single-device ``EventHistogrammer``, so mesh-backed
jobs ride the JobManager's one-dispatch tick program
(:mod:`.mesh_tick` ``MeshTickCombiner`` — one collective execute + one
replicated fetch per tick), and ``DevicePlacement`` assigns every
(stream, fuse-key) tick group a sticky mesh slice: single-device jobs
spread round-robin across chips, bank-sharded LOKI-scale jobs take the
whole mesh. Service surface: ``--mesh data,bank`` / ``LIVEDATA_MESH``
(services/service_factory.py); per-slice dispatch counts and publish
RTTs report through ``ops/publish.METRICS`` and the link monitor.
:mod:`.mesh` also carries the jax-version ``shard_map`` shim (modern
``jax.shard_map`` vs the 0.4.x experimental entry point).
"""

from .mesh import make_mesh, mesh_from_spec, shard_map, shard_map_available
from .mesh_tick import DevicePlacement, MeshTickCombiner, TickSlice
from .sharded_hist import ShardedHistogrammer
from .sharded_qhist import ShardedQHistogrammer

__all__ = [
    "DevicePlacement",
    "MeshTickCombiner",
    "ShardedHistogrammer",
    "ShardedQHistogrammer",
    "TickSlice",
    "make_mesh",
    "mesh_from_spec",
    "shard_map",
    "shard_map_available",
]

"""Shared streaming plumbing for QHistogrammer-backed reductions.

SANS I(Q) and the Q-E spectrometer map differ only in the precompiled
(pixel, toa-bin) -> bin map and the output formatting; everything
between — aux-monitor counting, monitor-only windows via an empty
padded batch, and the fused single-round-trip publish of the QState —
lives here once.
"""

from __future__ import annotations

from collections.abc import Mapping
from typing import Any

import numpy as np

from ..ops.event_batch import EventBatch
from ..preprocessors.event_data import StagedEvents

__all__ = ["QStreamingMixin", "latest_sample_value"]


def latest_sample_value(sample: Any) -> float | None:
    """Latest numeric value of a context sample (NXlog DataArray latest,
    LogData, or plain scalar) — the one idiom every live-calibration
    consumer shares."""
    if sample is None:
        return None
    values = getattr(sample, "values", sample)
    arr = np.asarray(values).reshape(-1)
    return float(arr[-1]) if arr.size else None


class QStreamingMixin:
    """Requires ``_hist`` (QHistogrammer), ``_state``, ``_primary_stream``,
    ``_monitor_streams`` and ``_publish = None`` set by the subclass.

    An optional second monitor channel (``_transmission_streams``, e.g.
    the SANS transmission monitor, reference loki/specs.py:96) is counted
    host-side: event *counts* are already host data before staging, so a
    scalar channel needs no device round trip. The counters mirror the
    device monitor channel's fold semantics exactly — window zeroed at
    each publish fold, cumulative monotone — so the two channels stay
    comparable across windows.
    """

    _transmission_streams: frozenset[str] = frozenset()
    _trans_win: float = 0.0
    _trans_cum: float = 0.0
    #: Combined-publish hand-off (ADR 0113): outputs prefetched by the
    #: JobManager's fused tick round trip, consumed by ``_take_publish``.
    _prefetched_publish: dict | None = None

    def accumulate(self, data: Mapping[str, Any]) -> None:
        monitor_count = 0.0
        detector: EventBatch | None = None
        det_cache = None
        for key, value in data.items():
            if not isinstance(value, StagedEvents):
                continue
            is_trans = key in self._transmission_streams
            if is_trans:
                self._trans_win += float(value.n_events)
                self._trans_cum += float(value.n_events)
            if key in self._monitor_streams:
                monitor_count += float(value.n_events)
            elif not is_trans and (
                self._primary_stream is None or key == self._primary_stream
            ):
                detector = value.batch
                # Window stream-cache slot: the raw (pixel_id, toa) wire
                # is layout-independent, so K Q-family jobs — and any
                # device-path histogram job — share ONE transfer.
                det_cache = value.cache
        if detector is not None or monitor_count:
            if detector is None:
                # monitor-only window: empty padded batch keeps shapes static
                detector = EventBatch.from_arrays(
                    np.empty(0, dtype=np.int32), np.empty(0, dtype=np.float32)
                )
                det_cache = None
            self._state = self._hist.step(
                self._state, detector, monitor_count, cache=det_cache
            )

    # -- state snapshots (core/state_snapshot.py, ADR 0107) ----------------
    def state_fingerprint(self) -> str:
        """The BIN SPACE's identity, deliberately NOT the table bytes:
        accumulated counts mean "events in bin k of this binning" — a
        live table recalibration (powder emission offset, reflectometry
        omega move) changes where FUTURE events land but not what the
        accumulated bins mean, and these workflows preserve state across
        swaps by design. The bin space is fully determined by the
        workflow class and its params, both available even before a
        context-gated workflow builds its first table."""
        import hashlib

        h = hashlib.sha1()
        h.update(type(self).__name__.encode())
        params = getattr(self, "_params", None)
        if params is not None and hasattr(params, "model_dump_json"):
            h.update(params.model_dump_json().encode())
        return h.hexdigest()

    def dump_state(self) -> dict[str, np.ndarray]:
        if getattr(self, "_state", None) is None:
            # Context-gated workflows (reflectometry before the first
            # sample angle) have nothing to dump yet; an empty dict is
            # skipped by the snapshot writer rather than overwriting a
            # prior useful snapshot.
            return {}
        out = {
            field: np.asarray(getattr(self._state, field))
            for field in self._state._fields
        }
        # The host-side transmission counters share the fold semantics
        # of the device channels and must travel with them.
        out["trans_win"] = np.asarray(self._trans_win)
        out["trans_cum"] = np.asarray(self._trans_cum)
        return out

    def restore_state(self, arrays: dict[str, np.ndarray]) -> bool:
        if getattr(self, "_state", None) is None:
            # No device state to adopt into yet (schedule-time restore of
            # a context-gated workflow). Refusing here is safe: the
            # caller keeps the snapshot file for a later attempt.
            return False
        import jax.numpy as jnp

        from ..ops.qhistogram import QState

        restored = {}
        for field in QState._fields:
            if field not in arrays:
                return False
            value = np.asarray(arrays[field])
            current = getattr(self._state, field)
            if value.shape != current.shape:
                return False
            restored[field] = jnp.asarray(value, dtype=current.dtype)
        self._state = QState(**restored)
        self._trans_win = float(arrays.get("trans_win", 0.0))
        self._trans_cum = float(arrays.get("trans_cum", 0.0))
        return True

    def _publisher(self):
        if self._publish is None:
            from ..ops.publish import PackedPublisher

            def program(state):
                outputs = {
                    "win": state.window,
                    "cum": state.cumulative,
                    "mon_win": state.monitor_window,
                    "mon_cum": state.monitor_cumulative,
                }
                return outputs, self._hist.fold_window(state)

            self._publish = PackedPublisher(program)
        return self._publish

    def event_ingest(self, stream: str, staged: StagedEvents):
        """Fused-stepping/tick offer (core/job_manager.py, ADR 0114):
        the Q family's detector ingest is one table-gather step over
        this job's private state, so a detector-only window steps AND
        publishes in ONE tick dispatch (``QHistogrammer.tick_staging``/
        ``tick_step`` — the PR 6 coverage gap, closed). The fuse key
        carries the kernel's instance token, so Q groups are
        singletons: each job owns its own calibration table, and
        member[0]'s table must never reduce another job's events.
        Monitor/transmission streams decline — their counts fold
        host-side in ``accumulate``, and a window carrying them is not
        tick-eligible anyway (the manager requires a single-stream
        window)."""
        if getattr(self, "_state", None) is None:
            return None  # context-gated workflow before its first table
        if (
            stream in self._monitor_streams
            or stream in self._transmission_streams
        ):
            return None
        if self._primary_stream is not None and stream != self._primary_stream:
            return None
        from ..core.device_event_cache import EventIngest

        def set_state(state) -> None:
            self._state = state

        return EventIngest(
            key=self._hist.fuse_key + ("",),
            hist=self._hist,
            batch=staged.batch,
            batch_tag="",
            get_state=lambda: self._state,
            set_state=set_state,
        )

    def publish_offer(self):
        """Combined-publish offer (ADR 0113): every QHistogrammer-backed
        reduction due in a tick joins the one device round trip; with
        the ingest offer above, a detector-only window upgrades to the
        full tick program (ADR 0114) — step + publish in one dispatch.
        The host-side transmission counters never ride the device
        publish."""
        if getattr(self, "_state", None) is None:
            return None  # context-gated workflow before its first table
        from ..ops.publish import make_publish_offer

        return make_publish_offer(
            self,
            self._publisher(),
            (self._state,),
            fresh_state=self._hist.init_state,
        )

    def _take_publish(self) -> tuple[np.ndarray, np.ndarray, float, float]:
        """One fused publish: (window, cumulative, monitor_window,
        monitor_cumulative) on host; the window folds."""
        out = self._prefetched_publish
        if out is not None:
            self._prefetched_publish = None
        else:
            out, self._state = self._publisher()(self._state)
        return (
            out["win"],
            out["cum"],
            float(out["mon_win"]),
            float(out["mon_cum"]),
        )

    def _take_transmission(self) -> tuple[float, float]:
        """(window, cumulative) transmission-monitor counts; folds the
        window (zeroes it) like ``_take_publish`` folds the device state."""
        win = self._trans_win
        self._trans_win = 0.0
        return win, self._trans_cum

    def clear(self) -> None:
        self._state = self._hist.clear()
        self._trans_win = 0.0
        self._trans_cum = 0.0
        self._prefetched_publish = None


#: Wire-schema contract (graftlint trace pass, JGL105 / ADR 0123) for
#: every QHistogrammer-backed family publishing through _publisher():
#: output name -> (ndim, dtype); see detector_view/workflow.py.
TICK_WIRE_SCHEMA = {
    "cum": (1, "float32"),
    "mon_cum": (0, "float32"),
    "mon_win": (0, "float32"),
    "win": (1, "float32"),
}

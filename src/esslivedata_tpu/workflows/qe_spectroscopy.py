"""Indirect-geometry Q–E rebinning workflow (BIFROST spectroscopy).

The reference reduces BIFROST through scippneutron/sciline conversion
graphs per cycle; the TPU-native shape is the same as SANS I(Q): all
per-event physics precompiles into a host-built (pixel, toa-bin) →
flat (Q, E)-bin map (ops/qhistogram.build_qe_map), and the streaming
work is one gather+scatter per batch into a ``[n_q * n_e]`` state with
fold semantics. Outputs are S(Q, ω)-style 2-D maps in current and
cumulative views, raw and monitor-normalized, published through the
fused single-round-trip program (ops/publish.py).
"""

from __future__ import annotations

import numpy as np
from pydantic import BaseModel, ConfigDict, Field

from ..config.models import TOARange
from ..ops.qhistogram import QHistogrammer, build_qe_map
from ..utils.labeled import DataArray, Variable
from .qshared import QStreamingMixin

__all__ = ["QESpectroscopyParams", "QESpectroscopyWorkflow"]


class QESpectroscopyParams(BaseModel):
    model_config = ConfigDict(frozen=True)

    q_bins: int = 80
    q_min: float = 0.2  # 1/angstrom
    q_max: float = 2.6
    e_bins: int = 60
    e_min: float = -3.0  # meV energy transfer
    e_max: float = 6.0
    toa_bins: int = 320
    # Long-frame arrival window: BIFROST's 162 m incident path puts
    # cold-neutron arrivals hundreds of ms after the pulse.
    toa_range: TOARange = Field(
        default_factory=lambda: TOARange(low=8.0e7, high=4.0e8)
    )
    l1: float = 162.0  # m, moderator->sample


class QESpectroscopyWorkflow(QStreamingMixin):
    """Detector events -> S(Q, E); aux monitor events -> normalization."""

    def __init__(
        self,
        *,
        two_theta: np.ndarray,
        ef_mev: np.ndarray,
        l2: np.ndarray,
        pixel_ids: np.ndarray,
        params: QESpectroscopyParams | None = None,
        primary_stream: str | None = None,
        monitor_streams: set[str] | None = None,
    ) -> None:
        params = params or QESpectroscopyParams()
        self._params = params
        q_edges = np.linspace(params.q_min, params.q_max, params.q_bins + 1)
        e_edges = np.linspace(params.e_min, params.e_max, params.e_bins + 1)
        toa_edges = np.linspace(
            params.toa_range.low, params.toa_range.high, params.toa_bins + 1
        )
        qe_map = build_qe_map(
            two_theta=two_theta,
            ef_mev=ef_mev,
            l2=l2,
            pixel_ids=pixel_ids,
            toa_edges=toa_edges,
            q_edges=q_edges,
            e_edges=e_edges,
            l1=params.l1,
        )
        self._n_q = params.q_bins
        self._n_e = params.e_bins
        self._hist = QHistogrammer(
            qmap=qe_map,
            toa_edges=toa_edges,
            n_q=params.q_bins * params.e_bins,
            method="auto",
        )
        self._state = self._hist.init_state()
        self._q_var = Variable(q_edges, ("Q",), "1/angstrom")
        self._e_var = Variable(e_edges, ("dE",), "meV")
        self._primary_stream = primary_stream
        self._monitor_streams = monitor_streams or set()
        self._publish = None

    def _map2d(self, flat: np.ndarray, name: str) -> DataArray:
        return DataArray(
            Variable(
                flat.reshape(self._n_q, self._n_e), ("Q", "dE"), "counts"
            ),
            coords={"Q": self._q_var, "dE": self._e_var},
            name=name,
        )

    def finalize(self) -> dict[str, DataArray]:
        win, cum, mon_win, mon_cum = self._take_publish()
        results = {
            "sqw_current": self._map2d(win, "sqw_current"),
            "sqw_cumulative": self._map2d(cum, "sqw_cumulative"),
            "counts_current": DataArray(
                Variable(np.asarray(win.sum()), (), "counts"),
                name="counts_current",
            ),
            "monitor_counts_current": DataArray(
                Variable(np.asarray(mon_win), (), "counts"),
                name="monitor_counts_current",
            ),
        }
        norm = self._map2d(cum / max(mon_cum, 1.0), "sqw_normalized")
        norm.data = Variable(norm.values, ("Q", "dE"), "")
        results["sqw_normalized"] = norm
        return results



"""Monitor-normalized streaming SANS I(Q) workflow (BASELINE config 4).

The reference's LOKI I(Q) runs esssans' sciline graph per cycle
(reference: instruments/loki/factories.py:21-120); here the whole reduction
is the precompiled Q-map scatter kernel (ops/qhistogram.py) plus a
monitor-ratio at finalize. The monitor arrives as an aux stream of staged
events (ADR-0002-style aux binding through WorkflowConfig.aux_source_names).
"""

from __future__ import annotations

from collections.abc import Mapping
from typing import Any

import numpy as np
from pydantic import BaseModel, ConfigDict, Field

from ..config.models import TOARange
from ..ops.event_batch import EventBatch
from ..ops.qhistogram import QHistogrammer, build_sans_qmap
from ..preprocessors.event_data import StagedEvents
from ..utils.labeled import DataArray, Variable

__all__ = ["SansIQParams", "SansIQWorkflow"]


class SansIQParams(BaseModel):
    model_config = ConfigDict(frozen=True)

    q_bins: int = 100
    q_min: float = 0.005  # 1/angstrom
    q_max: float = 0.5
    toa_bins: int = 200  # resolution of the TOF->lambda mapping
    toa_range: TOARange = Field(default_factory=TOARange)
    l1: float = 23.0  # m, source->sample


class SansIQWorkflow:
    """Detector events -> I(Q); aux monitor events -> normalization."""

    def __init__(
        self,
        *,
        positions: np.ndarray,
        pixel_ids: np.ndarray,
        params: SansIQParams | None = None,
        primary_stream: str | None = None,
        monitor_streams: set[str] | None = None,
    ) -> None:
        params = params or SansIQParams()
        self._params = params
        q_edges = np.linspace(params.q_min, params.q_max, params.q_bins + 1)
        toa_edges = np.linspace(
            params.toa_range.low, params.toa_range.high, params.toa_bins + 1
        )
        qmap = build_sans_qmap(
            positions=positions,
            pixel_ids=pixel_ids,
            toa_edges=toa_edges,
            q_edges=q_edges,
            l1=params.l1,
        )
        self._hist = QHistogrammer(
            qmap=qmap, toa_edges=toa_edges, n_q=params.q_bins
        )
        self._state = self._hist.init_state()
        self._q_edges_var = Variable(q_edges, ("Q",), "1/angstrom")
        self._primary_stream = primary_stream
        self._monitor_streams = monitor_streams or set()
        self._publish = None

    def accumulate(self, data: Mapping[str, Any]) -> None:
        monitor_count = 0.0
        detector: EventBatch | None = None
        for key, value in data.items():
            if not isinstance(value, StagedEvents):
                continue
            if key in self._monitor_streams:
                monitor_count += float(value.n_events)
            elif self._primary_stream is None or key == self._primary_stream:
                detector = value.batch
        if detector is not None or monitor_count:
            if detector is None:
                # monitor-only window: empty padded batch keeps shapes static
                detector = EventBatch.from_arrays(
                    np.empty(0, dtype=np.int32), np.empty(0, dtype=np.float32)
                )
            self._state = self._hist.step(self._state, detector, monitor_count)

    def _iq(self, counts: np.ndarray, monitor: float) -> DataArray:
        norm = counts / max(monitor, 1.0)
        return DataArray(
            Variable(norm, ("Q",), ""),
            coords={"Q": self._q_edges_var},
        )

    def finalize(self) -> dict[str, DataArray]:
        if self._publish is None:
            from ..ops.publish import PackedPublisher

            def program(state):
                outputs = {
                    "win": state.window,
                    "cum": state.cumulative,
                    "mon_win": state.monitor_window,
                    "mon_cum": state.monitor_cumulative,
                }
                return outputs, self._hist.fold_window(state)

            # One execute + one packed fetch per publish (ops/publish.py).
            self._publish = PackedPublisher(program)
        out, self._state = self._publish(self._state)
        win, cum = out["win"], out["cum"]
        mon_win, mon_cum = float(out["mon_win"]), float(out["mon_cum"])
        coords = {"Q": self._q_edges_var}
        return {
            "iq_current": self._iq(win, mon_win),
            "iq_cumulative": self._iq(cum, mon_cum),
            "counts_q_current": DataArray(
                Variable(win, ("Q",), "counts"), coords=coords
            ),
            "monitor_counts_current": DataArray(
                Variable(np.asarray(mon_win), (), "counts")
            ),
        }

    def clear(self) -> None:
        self._state = self._hist.clear()

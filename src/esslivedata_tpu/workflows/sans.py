"""Monitor-normalized streaming SANS I(Q) workflow (BASELINE config 4).

The reference's LOKI I(Q) runs esssans' sciline graph per cycle
(reference: instruments/loki/factories.py:21-120); here the whole reduction
is the precompiled Q-map scatter kernel (ops/qhistogram.py) plus a
monitor-ratio at finalize. The monitor arrives as an aux stream of staged
events (ADR-0002-style aux binding through WorkflowConfig.aux_source_names).
Detector staging rides the window stream-cache (ADR 0110, via
QStreamingMixin.accumulate): the raw (pixel_id, toa) wire is shared with
every other device-path consumer of the stream.
"""

from __future__ import annotations

import enum

import numpy as np
from pydantic import BaseModel, ConfigDict, Field

from ..config.models import TOARange
from ..ops.qhistogram import QHistogrammer, build_sans_qmap
from ..utils.labeled import DataArray, Variable
from .qshared import QStreamingMixin

__all__ = ["SansIQParams", "SansIQWorkflow", "TransmissionMode"]


class TransmissionMode(str, enum.Enum):
    """Live transmission correction (reference: loki/specs.py:38-61).

    Only modes that need no separate empty-beam run are available live:
    ``constant`` applies no correction (fraction = 1); ``current_run``
    estimates the fraction as transmission-monitor / incident-monitor
    counts within the current run.
    """

    constant = "constant"
    current_run = "current_run"


class SansIQParams(BaseModel):
    model_config = ConfigDict(frozen=True)

    q_bins: int = 100
    q_min: float = 0.005  # 1/angstrom
    q_max: float = 0.5
    toa_bins: int = 200  # resolution of the TOF->lambda mapping
    toa_range: TOARange = Field(default_factory=TOARange)
    toa_offset_ns: float = 0.0  # emission-time correction
    l1: float = 23.0  # m, source->sample
    transmission_mode: TransmissionMode = TransmissionMode.current_run
    # Beam-center position on the detector (m); shifts the scattering-angle
    # origin (reference: loki/specs.py BeamCenterXY).
    beam_center_x: float = 0.0
    beam_center_y: float = 0.0


class SansIQWorkflow(QStreamingMixin):
    """Detector events -> I(Q); aux monitor events -> normalization."""

    def __init__(
        self,
        *,
        positions: np.ndarray,
        pixel_ids: np.ndarray,
        params: SansIQParams | None = None,
        primary_stream: str | None = None,
        monitor_streams: set[str] | None = None,
        transmission_streams: set[str] | None = None,
    ) -> None:
        params = params or SansIQParams()
        self._params = params
        q_edges = np.linspace(params.q_min, params.q_max, params.q_bins + 1)
        toa_edges = np.linspace(
            params.toa_range.low, params.toa_range.high, params.toa_bins + 1
        )
        qmap = build_sans_qmap(
            positions=positions,
            pixel_ids=pixel_ids,
            toa_edges=toa_edges,
            q_edges=q_edges,
            l1=params.l1,
            toa_offset_ns=params.toa_offset_ns,
            beam_center=(params.beam_center_x, params.beam_center_y),
        )
        self._hist = QHistogrammer(
            qmap=qmap, toa_edges=toa_edges, n_q=params.q_bins, method="auto"
        )
        self._state = self._hist.init_state()
        self._q_edges_var = Variable(q_edges, ("Q",), "1/angstrom")
        self._primary_stream = primary_stream
        self._monitor_streams = monitor_streams or set()
        self._transmission_streams = frozenset(transmission_streams or ())
        self._publish = None

    def _transmission_fraction(self, trans: float, incident: float) -> float:
        """current_run estimate: raw transmission/incident monitor ratio.

        Falls back to 1 (no correction) when either channel is empty.
        The ratio is deliberately NOT clamped to 1: a value above 1
        signals monitor efficiency/rate mismatch, which should be
        visible in the published fraction rather than silently hidden.
        """
        if (
            self._params.transmission_mode is not TransmissionMode.current_run
            or not self._transmission_streams
            or trans <= 0.0
            or incident <= 0.0
        ):
            return 1.0
        return trans / incident

    def _iq(self, counts: np.ndarray, monitor: float, fraction: float) -> DataArray:
        norm = counts / (max(monitor, 1.0) * fraction)
        return DataArray(
            Variable(norm, ("Q",), ""),
            coords={"Q": self._q_edges_var},
        )

    def finalize(self) -> dict[str, DataArray]:
        win, cum, mon_win, mon_cum = self._take_publish()
        trans_win, trans_cum = self._take_transmission()
        t_win = self._transmission_fraction(trans_win, mon_win)
        t_cum = self._transmission_fraction(trans_cum, mon_cum)
        coords = {"Q": self._q_edges_var}
        return {
            "iq_current": self._iq(win, mon_win, t_win),
            "iq_cumulative": self._iq(cum, mon_cum, t_cum),
            "counts_q_current": DataArray(
                Variable(win, ("Q",), "counts"), coords=coords
            ),
            "monitor_counts_current": DataArray(
                Variable(np.asarray(mon_win), (), "counts")
            ),
            "transmission_current": DataArray(
                Variable(np.asarray(t_win), (), "")
            ),
        }



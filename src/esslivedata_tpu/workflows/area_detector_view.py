"""Area-detector (camera) view: ad00 images with current+cumulative outputs
and an optional logical transform (reference: workflows/area_detector_view.py:22).
"""

from __future__ import annotations

from collections.abc import Mapping
from typing import Any

import numpy as np
from pydantic import BaseModel, ConfigDict

from ..utils.labeled import DataArray, Variable

__all__ = ["AreaDetectorParams", "AreaDetectorView"]


class AreaDetectorParams(BaseModel):
    model_config = ConfigDict(frozen=True)

    transpose: bool = False
    flip_y: bool = False
    flip_x: bool = False


class AreaDetectorView:
    """Accumulates 2-D camera frames; cumulative restarts automatically on
    shape change (camera ROI reconfigured upstream)."""

    def __init__(self, *, params: AreaDetectorParams | None = None) -> None:
        self._params = params or AreaDetectorParams()
        self._window: np.ndarray | None = None
        self._cumulative: np.ndarray | None = None
        self._unit = None

    def _transform(self, values: np.ndarray) -> np.ndarray:
        p = self._params
        if p.transpose:
            values = values.T
        if p.flip_y:
            values = values[::-1, :]
        if p.flip_x:
            values = values[:, ::-1]
        return values

    def accumulate(self, data: Mapping[str, Any]) -> None:
        for value in data.values():
            if not isinstance(value, DataArray) or value.data.ndim != 2:
                continue
            frame = self._transform(np.asarray(value.values, dtype=np.float64))
            self._unit = value.unit
            if self._cumulative is None or self._cumulative.shape != frame.shape:
                self._cumulative = frame.copy()
                self._window = frame.copy()
            else:
                self._cumulative += frame
                if self._window is None or self._window.shape != frame.shape:
                    self._window = frame.copy()
                else:
                    self._window += frame

    def finalize(self) -> dict[str, DataArray]:
        if self._cumulative is None:
            return {}
        ny, nx = self._cumulative.shape
        coords = {
            "y": Variable(np.arange(ny, dtype=np.float64), ("y",), ""),
            "x": Variable(np.arange(nx, dtype=np.float64), ("x",), ""),
        }
        window = self._window if self._window is not None else np.zeros_like(
            self._cumulative
        )
        out = {
            "current": DataArray(
                Variable(window.copy(), ("y", "x"), self._unit),
                coords=coords,
                name="current",
            ),
            "cumulative": DataArray(
                Variable(self._cumulative.copy(), ("y", "x"), self._unit),
                coords=coords,
                name="cumulative",
            ),
        }
        self._window = np.zeros_like(self._cumulative)
        return out

    def clear(self) -> None:
        self._window = None
        self._cumulative = None

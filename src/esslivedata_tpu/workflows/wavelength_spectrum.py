"""Per-pixel wavelength spectrum for position-resolved detectors.

The reference offers a wavelength coordinate mode on its detector
histograms via the unwrap LUT providers (monitor_workflow.py:169,
detector_view providers); here the per-pixel TOF->wavelength conversion
precompiles into the standard (pixel, toa-bin) -> bin table
(ops/qhistogram.build_wavelength_map) — a detector-wide lambda spectrum
at the same streaming cost as every other reduction family, with
monitor normalization through the shared mixin.
"""

from __future__ import annotations

import numpy as np
from pydantic import BaseModel, ConfigDict, Field, model_validator

from ..config.models import TOARange
from ..ops.qhistogram import QHistogrammer, build_wavelength_map
from ..utils.labeled import DataArray, Variable
from .qshared import QStreamingMixin

__all__ = ["WavelengthSpectrumParams", "WavelengthSpectrumWorkflow"]


class WavelengthSpectrumParams(BaseModel):
    model_config = ConfigDict(frozen=True)

    wavelength_bins: int = 200
    wavelength_min: float = 0.5  # angstrom
    wavelength_max: float = 12.0
    toa_bins: int = 300
    toa_range: TOARange = Field(default_factory=TOARange)
    toa_offset_ns: float = 0.0
    l1: float = 23.0  # m, source->sample

    @model_validator(mode="after")
    def _ordered(self) -> WavelengthSpectrumParams:
        if self.wavelength_max <= self.wavelength_min:
            raise ValueError("wavelength range must satisfy min < max")
        return self


class WavelengthSpectrumWorkflow(QStreamingMixin):
    """Detector events -> I(lambda); aux monitor -> normalization."""

    def __init__(
        self,
        *,
        positions: np.ndarray,
        pixel_ids: np.ndarray,
        params: WavelengthSpectrumParams | None = None,
        primary_stream: str | None = None,
        monitor_streams: set[str] | None = None,
    ) -> None:
        params = params or WavelengthSpectrumParams()
        self._params = params
        lam_edges = np.linspace(
            params.wavelength_min,
            params.wavelength_max,
            params.wavelength_bins + 1,
        )
        toa_edges = np.linspace(
            params.toa_range.low, params.toa_range.high, params.toa_bins + 1
        )
        positions = np.asarray(positions, dtype=np.float64)
        l_total = params.l1 + np.linalg.norm(positions, axis=1)
        wmap = build_wavelength_map(
            l_total=l_total,
            pixel_ids=pixel_ids,
            toa_edges=toa_edges,
            wavelength_edges=lam_edges,
            toa_offset_ns=params.toa_offset_ns,
        )
        self._hist = QHistogrammer(
            qmap=wmap, toa_edges=toa_edges, n_q=params.wavelength_bins, method="auto"
        )
        self._state = self._hist.init_state()
        self._lam_var = Variable(lam_edges, ("wavelength",), "angstrom")
        self._primary_stream = primary_stream
        self._monitor_streams = monitor_streams or set()
        self._publish = None

    def _spectrum(self, values: np.ndarray, name: str, unit="counts"):
        return DataArray(
            Variable(values, ("wavelength",), unit),
            coords={"wavelength": self._lam_var},
            name=name,
        )

    def finalize(self) -> dict[str, DataArray]:
        win, cum, mon_win, mon_cum = self._take_publish()
        return {
            "wavelength_current": self._spectrum(win, "wavelength_current"),
            "wavelength_cumulative": self._spectrum(
                cum, "wavelength_cumulative"
            ),
            "wavelength_normalized": self._spectrum(
                cum / max(mon_cum, 1.0), "wavelength_normalized", unit=""
            ),
            "counts_current": DataArray(
                Variable(np.asarray(win.sum()), (), "counts"),
                name="counts_current",
            ),
        }

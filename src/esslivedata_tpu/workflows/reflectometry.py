"""Specular reflectometry R(Qz) workflow (ESTIA).

The reference reduces ESTIA through ess.estia's sciline workflow; the
TPU-native shape matches the other reductions with one twist: the
(pixel, toa-bin) -> Qz-bin table depends on the SAMPLE ANGLE, which is
a live motor position. The workflow therefore gates on the
``sample_angle`` context stream (jobs hold until the angle is known)
and rebuilds the table when the angle moves beyond a tolerance —
between batches, on the host, without touching the stream; the fold
state carries over because bin shapes never change.
"""

from __future__ import annotations

from collections.abc import Mapping
from typing import Any

import numpy as np
from pydantic import BaseModel, ConfigDict, Field

from ..config.models import TOARange
from ..ops.qhistogram import QHistogrammer, build_qz_map
from ..utils.labeled import DataArray, Variable
from .qshared import QStreamingMixin, latest_sample_value

__all__ = ["ReflectometryParams", "ReflectometryWorkflow"]


class ReflectometryParams(BaseModel):
    model_config = ConfigDict(frozen=True)

    qz_bins: int = 200
    qz_min: float = 0.005  # 1/angstrom
    qz_max: float = 0.3
    toa_bins: int = 400
    toa_range: TOARange = Field(default_factory=TOARange)
    l1: float = 35.0  # m, moderator->sample
    #: Sample-angle moves below this are measurement noise, not a
    #: reconfiguration — no table rebuild. Above it the host rebuilds
    #: the table and swaps it into the running kernel (no recompile).
    rebuild_tolerance_deg: float = 0.02


class ReflectometryWorkflow(QStreamingMixin):
    """Detector events -> R(Qz); gates on the live sample angle."""

    def __init__(
        self,
        *,
        pixel_offset_rad: np.ndarray,  # per-pixel angle above the horizon
        l2: np.ndarray,  # sample->pixel path (m); l1 comes from params
        pixel_ids: np.ndarray,
        params: ReflectometryParams | None = None,
        primary_stream: str | None = None,
        monitor_streams: set[str] | None = None,
        angle_stream: str = "sample_angle",
    ) -> None:
        params = params or ReflectometryParams()
        self._params = params
        self._offsets = np.asarray(pixel_offset_rad, dtype=np.float64)
        self._l_total = params.l1 + np.asarray(l2, dtype=np.float64)
        self._pixel_ids = np.asarray(pixel_ids)
        self._qz_edges = np.linspace(
            params.qz_min, params.qz_max, params.qz_bins + 1
        )
        self._toa_edges = np.linspace(
            params.toa_range.low, params.toa_range.high, params.toa_bins + 1
        )
        self._angle_stream = angle_stream
        self._omega_deg: float | None = None
        self._built_omega_deg: float | None = None
        self._primary_stream = primary_stream
        self._monitor_streams = monitor_streams or set()
        self._hist: QHistogrammer | None = None
        self._state = None
        self._publish = None
        self._qz_var = Variable(self._qz_edges, ("Qz",), "1/angstrom")

    # -- context -----------------------------------------------------------
    def set_context(self, context: Mapping[str, Any]) -> None:
        if (
            value := latest_sample_value(context.get(self._angle_stream))
        ) is not None:
            self._omega_deg = value

    def _ensure_table(self) -> bool:
        """(Re)build the Qz table for the current sample angle; returns
        False while the angle is unknown (no accumulation possible)."""
        if self._omega_deg is None:
            return False
        if (
            self._built_omega_deg is not None
            and abs(self._omega_deg - self._built_omega_deg)
            < self._params.rebuild_tolerance_deg
        ):
            return True
        grazing = np.deg2rad(self._omega_deg) + self._offsets
        qz_map = build_qz_map(
            grazing_angle=grazing,
            l_total=self._l_total,
            pixel_ids=self._pixel_ids,
            toa_edges=self._toa_edges,
            qz_edges=self._qz_edges,
        )
        if self._hist is None:
            self._hist = QHistogrammer(
                qmap=qz_map,
                toa_edges=self._toa_edges,
                n_q=self._params.qz_bins,
                method="auto",
            )
            self._state = self._hist.init_state()
        else:
            # Continuous omega scans cross the tolerance every few
            # batches: the table rides the jitted step as an argument,
            # so a move costs one device transfer — no recompile, and
            # the accumulated state stays (bin space is unchanged).
            self._hist.swap_table(qz_map)
        self._built_omega_deg = self._omega_deg
        return True

    # -- Workflow protocol -------------------------------------------------
    def accumulate(self, data: Mapping[str, Any]) -> None:
        if not self._ensure_table():
            return  # gated: angle not yet known
        super().accumulate(data)

    def finalize(self) -> dict[str, DataArray]:
        if not self._ensure_table():
            return {}
        win, cum, mon_win, mon_cum = self._take_publish()
        coords = {"Qz": self._qz_var}

        def spectrum(values, name, unit="counts"):
            return DataArray(
                Variable(values, ("Qz",), unit), coords=coords, name=name
            )

        return {
            "r_qz_current": spectrum(win, "r_qz_current"),
            "r_qz_cumulative": spectrum(cum, "r_qz_cumulative"),
            "r_qz_normalized": spectrum(
                cum / max(mon_cum, 1.0), "r_qz_normalized", unit=""
            ),
            "counts_current": DataArray(
                Variable(np.asarray(win.sum()), (), "counts"),
                name="counts_current",
            ),
            "monitor_counts_current": DataArray(
                Variable(np.asarray(mon_win), (), "counts"),
                name="monitor_counts_current",
            ),
            "sample_angle_deg": DataArray(
                Variable(np.asarray(self._built_omega_deg), (), "deg"),
                name="sample_angle_deg",
            ),
        }

    def clear(self) -> None:
        if self._hist is not None:
            self._state = self._hist.clear()

"""Monitor histogram workflow (reference: workflows/monitor_workflow.py).

Handles both monitor data modes like the reference (_histogram_monitor:65):
event-mode (ev44 -> staged event batches -> 1-row device histogram) and
histogram-mode (da00 dense histograms -> host rebin onto the target edges,
accumulated with Cumulative). Outputs current/cumulative 1-D spectra on
the configured coordinate: TOA (ns) or wavelength (angstrom) — the
latter via the same device kernel over lambda-derived edges.
"""

from __future__ import annotations

from collections.abc import Mapping
from typing import Any, Literal

import numpy as np
from pydantic import BaseModel, ConfigDict, Field, model_validator

from ..config.models import TOARange
from ..ops.histogram import EventHistogrammer, HistogramState
from ..preprocessors.event_data import StagedEvents
from ..utils.labeled import DataArray, Variable

__all__ = ["MonitorWorkflow", "MonitorParams", "rebin_1d"]




class MonitorParams(BaseModel):
    model_config = ConfigDict(frozen=True)

    toa_bins: int = 100
    toa_range: TOARange = Field(default_factory=TOARange)
    # Coordinate mode (reference: monitor_workflow.py:169 coordinate_mode):
    # "toa" histograms time-of-arrival; "wavelength" histograms
    # lambda = (h/m_n) * t / L. lambda is linear in t for a fixed flight
    # path, so wavelength mode is the SAME device kernel over transformed
    # edges — no per-event conversion, no second code path on device.
    coordinate: Literal["toa", "wavelength"] = "toa"
    wavelength_min: float = 0.5  # angstrom (wavelength mode)
    wavelength_max: float = 12.0
    distance_m: float = 25.0  # source->monitor flight path (m)
    toa_offset_ns: float = 0.0  # emission-time / frame offset correction
    # Position moves beyond this clear accumulation (reference:
    # monitor_workflow.py:36 MONITOR_TRANSFORM geometry-signal coord —
    # a moved monitor samples a different beam, so stale counts lie).
    # In the position log's NATIVE units — set it per instrument to
    # match what the positioner publishes (mm at ESS beamlines).
    position_tolerance: float = 1.0

    @model_validator(mode="after")
    def _wavelength_mode_consistent(self) -> MonitorParams:
        if self.wavelength_max <= self.wavelength_min:
            raise ValueError("wavelength range must satisfy min < max")
        if self.distance_m <= 0:
            raise ValueError("distance_m must be positive")
        if self.coordinate == "wavelength":
            default = TOARange()
            narrowed = self.toa_range.enabled and (
                self.toa_range.low != default.low
                or self.toa_range.high != default.high
            )
            if narrowed:
                raise ValueError(
                    "toa_range does not apply in wavelength mode — the "
                    "spectrum is windowed by wavelength_min/max instead; "
                    "reset toa_range or switch coordinate back to 'toa'"
                )
        return self


def rebin_1d(
    values: np.ndarray, src_edges: np.ndarray, dst_edges: np.ndarray
) -> np.ndarray:
    """Conservative rebin of a dense 1-D histogram onto new edges
    (fractional-overlap weighting, the host-side analog of scipp's rebin
    used by the reference for histogram-mode monitors)."""
    src_edges = np.asarray(src_edges, dtype=np.float64)
    dst_edges = np.asarray(dst_edges, dtype=np.float64)
    out = np.zeros(dst_edges.size - 1)
    # Overlap of each src bin [a,b) with each dst bin via interval clipping.
    a = src_edges[:-1]
    b = src_edges[1:]
    widths = b - a
    for j in range(dst_edges.size - 1):
        lo, hi = dst_edges[j], dst_edges[j + 1]
        overlap = np.clip(np.minimum(b, hi) - np.maximum(a, lo), 0.0, None)
        with np.errstate(invalid="ignore", divide="ignore"):
            frac = np.where(widths > 0, overlap / widths, 0.0)
        out[j] = float((values * frac).sum())
    return out


class MonitorWorkflow:
    """1-D monitor spectrum (TOA or wavelength axis), event- or
    histogram-mode."""

    def __init__(
        self,
        *,
        params: MonitorParams | None = None,
        position_stream: str | None = None,
    ) -> None:
        params = params or MonitorParams()
        self._params = params
        if params.coordinate == "wavelength":
            from ..ops.chopper_cascade import ALPHA_NS_PER_M_A

            lam_edges = np.linspace(
                params.wavelength_min, params.wavelength_max, params.toa_bins + 1
            )
            # t[ns] = ALPHA * L * lambda, shifted back by the emission
            # offset so event TOA (not true TOF) bins correctly.
            self._edges = (
                lam_edges * params.distance_m * ALPHA_NS_PER_M_A
                - params.toa_offset_ns
            )
            self._axis = "wavelength"
            self._axis_var = Variable(lam_edges, ("wavelength",), "angstrom")
        else:
            self._edges = np.linspace(
                params.toa_range.low, params.toa_range.high, params.toa_bins + 1
            )
            self._axis = "toa"
            self._axis_var = Variable(self._edges, ("toa",), "ns")
        self._hist = EventHistogrammer(
            toa_edges=self._edges, n_screen=1, method="auto"
        )
        self._state: HistogramState = self._hist.init_state()

        def publish_program(state):
            cum, win = self._hist.views_of(state)
            return (
                {"cum": cum[0], "win": win[0]},
                self._hist.fold_window(state),
            )

        from ..ops.publish import PackedPublisher

        # One execute + one fetch per publish (see ops/publish.py).
        self._publish = PackedPublisher(publish_program)
        #: Combined-publish hand-off (ADR 0113): outputs prefetched by
        #: the JobManager's fused tick round trip, consumed in finalize.
        self._prefetched_publish: dict | None = None
        # Dense-mode accumulation happens host-side (tiny arrays).
        self._dense_cumulative = np.zeros(params.toa_bins)
        self._dense_window = np.zeros(params.toa_bins)
        # Which context stream carries this monitor's position, injected
        # by the instrument factory (same pattern as the powder/
        # reflectometry workflows' stream-name injection); None = fixed
        # monitor, feature off. _position anchors at the last CLEAR (or
        # first sample) — comparing against the last sample instead
        # would let a slow scan creep arbitrarily far without reset.
        self._position_stream = position_stream
        self._position: float | None = None

    def set_context(self, context: Mapping[str, Any]) -> None:
        """Track the monitor's position (optional context stream): a move
        beyond the tolerance clears accumulated spectra — a moved monitor
        samples a different beam."""
        from .qshared import latest_sample_value

        if self._position_stream is None:
            return
        value = latest_sample_value(context.get(self._position_stream))
        if value is None:
            return
        if self._position is None:
            self._position = value
        elif abs(value - self._position) > self._params.position_tolerance:
            self.clear()
            self._position = value

    @staticmethod
    def _row0_impl(batch):
        if batch.pixel_id.size and batch.pixel_id.max() > 0:
            from ..ops import EventBatch

            return (
                EventBatch(
                    pixel_id=np.where(
                        batch.pixel_id >= 0, 0, -1
                    ).astype(np.int32),
                    toa=batch.toa,
                    n_valid=batch.n_valid,
                    owner=batch.owner,
                ),
                "mon-row0",
            )
        return batch, ""

    @classmethod
    def _row0_batch(cls, batch, cache=None):
        """(batch, batch_tag) with pixel ids folded onto screen row 0.

        A pixellated monitor's staged events carry real pixel ids; this
        1-D TOA histogram is id-agnostic, so every valid event folds onto
        screen row 0 (the -1 padding sentinel stays excluded). Without
        the clamp the n_screen=1 kernel would mask ids >= 1 and silently
        zero the spectrum. The non-empty tag keeps the clamped wire from
        ever colliding with the raw stream in the window stream-cache —
        and lets every monitor job SHARE the clamped staging. The clamp
        itself (a full-array scan + rewrite) memoizes through the same
        slot, so K monitor jobs pay it once per window, not K times."""
        if cache is None:
            return cls._row0_impl(batch)
        return cache.get_or_stage(
            ("mon-row0-host", batch.padded_size),
            lambda: cls._row0_impl(batch),
        )

    def accumulate(self, data: Mapping[str, Any]) -> None:
        for value in data.values():
            if isinstance(value, StagedEvents):
                batch, tag = self._row0_batch(value.batch, value.cache)
                self._state = self._hist.step_batch(
                    self._state, batch, cache=value.cache, batch_tag=tag
                )
            elif isinstance(value, DataArray):
                self._add_dense(value)

    def event_ingest(self, stream: str, staged: StagedEvents):
        """Fused-stepping offer (core/job_manager.py): K same-axis
        monitor jobs on one stream advance in a single dispatch from one
        (possibly row0-clamped) staged batch; on publish ticks the tick
        program (ops/tick.py, ADR 0114) fuses that step with the packed
        publish into the same dispatch. The row0 clamp stays a
        host-side batch transform keyed by its ``batch_tag``, so K
        monitor ticks share one clamped staging either way. Dense
        histogram-mode data never arrives as StagedEvents, so it keeps
        the private path."""
        from ..core.device_event_cache import EventIngest

        batch, tag = self._row0_batch(staged.batch, staged.cache)

        def set_state(state) -> None:
            self._state = state

        return EventIngest(
            key=self._hist.fuse_key + (tag,),
            hist=self._hist,
            batch=batch,
            batch_tag=tag,
            get_state=lambda: self._state,
            set_state=set_state,
        )

    def _add_dense(self, da: DataArray) -> None:
        coord_name = next(
            (c for c in ("toa", "time_of_arrival", "tof") if c in da.coords), None
        )
        if coord_name is None or da.data.ndim != 1:
            raise ValueError(
                f"Histogram-mode monitor data needs a 1-D TOA coord, got {da!r}"
            )
        src_edges = da.coords[coord_name].to_unit("ns").numpy
        if coord_name == "tof" and self._params.toa_offset_ns:
            # True time-of-flight -> event-TOA space (our edges' frame):
            # toa = tof - offset. Without this a nonzero offset would be
            # applied twice for tof-coord dense data in wavelength mode.
            src_edges = src_edges - self._params.toa_offset_ns
        values = np.asarray(da.values, dtype=np.float64)
        if src_edges.size == values.size:  # midpoints: synthesize edges
            mids = src_edges
            steps = np.diff(mids)
            edges = np.concatenate(
                [
                    [mids[0] - steps[0] / 2],
                    mids[:-1] + steps / 2,
                    [mids[-1] + steps[-1] / 2],
                ]
            )
            src_edges = edges
        rebinned = rebin_1d(values, src_edges, self._edges)
        self._dense_window += rebinned
        self._dense_cumulative += rebinned

    def publish_offer(self):
        """Combined-publish offer (ADR 0113): K monitor jobs due in one
        tick share a single device round trip — under the tick program
        (ADR 0114) that round trip also carries the event step, args[0]
        being the pre-step state per the make_publish_offer contract.
        The dense histogram-mode accumulation is host-side and merges at
        finalize as always (the device publish never sees it, so the
        tick's in-dispatch publish stays correct when dense data and
        staged events share a window — the manager only ticks
        single-stream windows regardless)."""
        from ..ops.publish import make_publish_offer

        return make_publish_offer(
            self,
            self._publish,
            (self._state,),
            fresh_state=self._hist.init_state,
        )

    def finalize(self) -> dict[str, DataArray]:
        out = self._prefetched_publish
        if out is not None:
            self._prefetched_publish = None
        else:
            out, self._state = self._publish(self._state)
        win = out["win"] + self._dense_window
        cum = out["cum"] + self._dense_cumulative
        self._dense_window = np.zeros_like(self._dense_window)
        axis = self._axis
        coords = {axis: self._axis_var}
        return {
            "current": DataArray(
                Variable(win, (axis,), "counts"), coords=coords, name="current"
            ),
            "cumulative": DataArray(
                Variable(cum, (axis,), "counts"), coords=coords, name="cumulative"
            ),
            "counts_current": DataArray(
                Variable(np.asarray(win.sum()), (), "counts"), name="counts_current"
            ),
            "counts_cumulative": DataArray(
                Variable(np.asarray(cum.sum()), (), "counts"),
                name="counts_cumulative",
            ),
        }

    def clear(self) -> None:
        self._state = self._hist.clear(self._state)
        self._dense_cumulative[:] = 0.0
        self._dense_window[:] = 0.0
        self._prefetched_publish = None

    # -- state snapshots (core/state_snapshot.py, ADR 0107) ----------------
    def state_fingerprint(self) -> str:
        """Axis edges + full params: everything that gives the spectrum
        bins physical meaning (a position move resets accumulation
        in-process, so the anchor position itself is not part of the
        bins' meaning and travels with the dump instead)."""
        import hashlib

        h = hashlib.sha1()
        h.update(self._edges.tobytes())
        h.update(self._params.model_dump_json().encode())
        return h.hexdigest()

    def dump_state(self) -> dict[str, np.ndarray]:
        out = EventHistogrammer.dump_state_arrays(self._state)
        out["dense_window"] = self._dense_window.copy()
        out["dense_cumulative"] = self._dense_cumulative.copy()
        if self._position is not None:
            # The reset-on-move anchor: without it, a restart during a
            # slow scan would re-anchor at the next sample and blend
            # pre-move counts with post-move ones.
            out["position"] = np.asarray(float(self._position))
        return out

    def restore_state(self, arrays: dict[str, np.ndarray]) -> bool:
        dense_w = np.asarray(arrays.get("dense_window"))
        dense_c = np.asarray(arrays.get("dense_cumulative"))
        if (
            dense_w.shape != self._dense_window.shape
            or dense_c.shape != self._dense_cumulative.shape
        ):
            return False
        restored = self._hist.restore_state_arrays(self._state, arrays)
        if restored is None:
            return False
        self._state = restored
        self._dense_window = dense_w.astype(self._dense_window.dtype)
        self._dense_cumulative = dense_c.astype(self._dense_cumulative.dtype)
        if "position" in arrays:
            self._position = float(arrays["position"])
        return True


#: Wire-schema contract (graftlint trace pass, JGL105 / ADR 0123):
#: output name -> (ndim, dtype); see detector_view/workflow.py.
TICK_WIRE_SCHEMA = {
    "cum": (1, "float32"),
    "win": (1, "float32"),
}

"""Elastic-line Q-space map (BIFROST; reference: bifrost/specs.py:376
elastic_qmap, :188 BifrostElasticQMapParams).

A 2-D map of scattering intensity over two selectable momentum-transfer
components (Qx/Qy/Qz) for quasi-elastic events. The TPU shape matches
the other reduction families: the component selection, bin edges AND
the elastic cut all precompile into one host-built (pixel, toa-bin) ->
flat-bin table (ops/qhistogram.build_elastic_q2d_map); streaming cost
is the same gather+scatter as every other family.
"""

from __future__ import annotations

from typing import Literal

import numpy as np
from pydantic import BaseModel, ConfigDict, Field, model_validator

from ..config.models import TOARange
from ..ops.qhistogram import QHistogrammer, build_elastic_q2d_map
from ..utils.labeled import DataArray, Variable
from .qshared import QStreamingMixin

__all__ = ["ElasticQAxis", "ElasticQMapParams", "ElasticQMapWorkflow"]


class ElasticQAxis(BaseModel):
    """One axis of the Q-space map: which component it spans + edges."""

    model_config = ConfigDict(frozen=True)

    component: Literal["Qx", "Qy", "Qz"]
    low: float = -3.0  # 1/angstrom
    high: float = 3.0
    bins: int = 100

    @model_validator(mode="after")
    def _ordered(self) -> ElasticQAxis:
        if self.high <= self.low:
            raise ValueError("axis range must satisfy low < high")
        return self

    def edges(self) -> np.ndarray:
        return np.linspace(self.low, self.high, self.bins + 1)


class ElasticQMapParams(BaseModel):
    model_config = ConfigDict(frozen=True)

    axis1: ElasticQAxis = Field(
        default_factory=lambda: ElasticQAxis(component="Qx")
    )
    axis2: ElasticQAxis = Field(
        default_factory=lambda: ElasticQAxis(component="Qz")
    )
    e_window_mev: float = 0.25  # |Ei - Ef| accepted as elastic
    toa_bins: int = 320
    toa_range: TOARange = Field(
        default_factory=lambda: TOARange(low=8.0e7, high=4.0e8)
    )
    l1: float = 162.0  # m, moderator->sample

    @model_validator(mode="after")
    def _distinct_axes(self) -> ElasticQMapParams:
        if self.axis1.component == self.axis2.component:
            raise ValueError("axis1 and axis2 must span different components")
        if self.e_window_mev <= 0:
            raise ValueError("e_window_mev must be positive")
        return self


class ElasticQMapWorkflow(QStreamingMixin):
    """Detector events -> I(axis1, axis2) on the elastic line."""

    def __init__(
        self,
        *,
        two_theta: np.ndarray,
        azimuth: np.ndarray,
        ef_mev: np.ndarray,
        l2: np.ndarray,
        pixel_ids: np.ndarray,
        params: ElasticQMapParams | None = None,
        primary_stream: str | None = None,
        monitor_streams: set[str] | None = None,
    ) -> None:
        params = params or ElasticQMapParams()
        self._params = params
        a1, a2 = params.axis1, params.axis2
        e1, e2 = a1.edges(), a2.edges()
        toa_edges = np.linspace(
            params.toa_range.low, params.toa_range.high, params.toa_bins + 1
        )
        table = build_elastic_q2d_map(
            two_theta=two_theta,
            azimuth=azimuth,
            ef_mev=ef_mev,
            l2=l2,
            pixel_ids=pixel_ids,
            toa_edges=toa_edges,
            axis1=a1.component,
            axis1_edges=e1,
            axis2=a2.component,
            axis2_edges=e2,
            l1=params.l1,
            e_window_mev=params.e_window_mev,
        )
        self._n1, self._n2 = a1.bins, a2.bins
        self._hist = QHistogrammer(
            qmap=table, toa_edges=toa_edges, n_q=a1.bins * a2.bins, method="auto"
        )
        self._state = self._hist.init_state()
        self._a1_var = Variable(e1, (a1.component,), "1/angstrom")
        self._a2_var = Variable(e2, (a2.component,), "1/angstrom")
        self._dims = (a1.component, a2.component)
        self._primary_stream = primary_stream
        self._monitor_streams = monitor_streams or set()
        self._publish = None

    def _map2d(self, flat: np.ndarray, name: str, unit: str = "counts") -> DataArray:
        return DataArray(
            Variable(flat.reshape(self._n1, self._n2), self._dims, unit),
            coords={self._dims[0]: self._a1_var, self._dims[1]: self._a2_var},
            name=name,
        )

    def finalize(self) -> dict[str, DataArray]:
        win, cum, mon_win, mon_cum = self._take_publish()
        return {
            "qmap_current": self._map2d(win, "qmap_current"),
            "qmap_cumulative": self._map2d(cum, "qmap_cumulative"),
            "qmap_normalized": self._map2d(
                cum / max(mon_cum, 1.0), "qmap_normalized", unit=""
            ),
            "counts_current": DataArray(
                Variable(np.asarray(win.sum()), (), "counts"),
                name="counts_current",
            ),
        }

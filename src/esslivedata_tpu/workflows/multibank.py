"""Multi-bank detector workflow: one kernel over all banks, mesh-shardable.

BIFROST-style instruments have many detector banks (9 analyzer triplets)
merged into one logical stream (reference: Ev44ToDetectorEventsAdapter
merge-detectors, message_adapter.py:416). TPU-native shape: the screen
space is the *concatenation of all banks* — one [n_banks*rows, toa] state,
one scatter per window — and when the process owns a multi-device mesh the
same workflow shards that bank axis over devices via ShardedHistogrammer
(BASELINE config 3). Per-bank outputs are slices of the global state.
"""

from __future__ import annotations

from collections.abc import Mapping
from typing import Any, Literal

import numpy as np
from pydantic import BaseModel, ConfigDict, Field

import jax

from ..config.models import TOARange
from ..ops.histogram import EventHistogrammer
from ..parallel.mesh import make_mesh
from ..parallel.sharded_hist import ShardedHistogrammer
from ..preprocessors.event_data import StagedEvents
from ..utils.labeled import DataArray, Variable

__all__ = ["MultiBankParams", "MultiBankViewWorkflow"]




class MultiBankParams(BaseModel):
    model_config = ConfigDict(frozen=True)

    toa_bins: int = 100
    toa_range: TOARange = Field(default_factory=TOARange)
    use_mesh: bool = True
    """Shard the bank axis over all visible devices when more than one."""
    mesh_exchange: Literal["auto", "delta_psum", "event_gather"] = "auto"
    """Data-shard merge strategy for the sharded kernel; 'auto' compares
    actual delta vs gather bytes (parallel/sharded_hist.py)."""
    mesh_batch_hint: int | None = None
    """Expected events per padded batch for the 'auto' crossover."""


class MultiBankViewWorkflow:
    """Per-bank TOA histograms from a merged multi-bank event stream."""

    def __init__(
        self,
        *,
        bank_detector_numbers: Mapping[str, np.ndarray],
        params: MultiBankParams | None = None,
        mesh=None,
    ) -> None:
        params = params or MultiBankParams()
        self._params = params
        self._bank_names = list(bank_detector_numbers)
        n_banks = len(self._bank_names)
        sizes = [np.asarray(d).size for d in bank_detector_numbers.values()]
        if len(set(sizes)) != 1:
            raise ValueError("All banks must have equal pixel counts")
        self._pixels_per_bank = sizes[0]
        n_screen = n_banks * self._pixels_per_bank

        # Global LUT: detector_number -> bank*pixels_per_bank + local index
        max_id = max(int(np.asarray(d).max()) for d in bank_detector_numbers.values())
        lut = np.full(max_id + 1, -1, dtype=np.int32)
        for b, det in enumerate(bank_detector_numbers.values()):
            ids = np.asarray(det).reshape(-1)
            lut[ids] = b * self._pixels_per_bank + np.arange(ids.size)

        edges = np.linspace(
            params.toa_range.low, params.toa_range.high, params.toa_bins + 1
        )
        n_devices = len(jax.devices())
        # The bank axis shards only in whole banks; use the largest device
        # count that divides n_screen bank-wise. An explicit ``mesh``
        # (service placement, bench, tests) wins — the mesh serving tier
        # (parallel/mesh_tick.py, ADR 0115) hands LOKI-scale jobs the
        # whole serving mesh this way.
        self._sharded = None
        if mesh is None and params.use_mesh and n_devices > 1:
            bank_axis = n_devices
            while bank_axis > 1 and n_banks % bank_axis:
                bank_axis -= 1
            if bank_axis > 1:
                mesh = make_mesh(bank_axis, bank=bank_axis)
        if mesh is not None and params.use_mesh:
            self._sharded = ShardedHistogrammer(
                toa_edges=edges,
                n_screen=n_screen,
                mesh=mesh,
                pixel_lut=lut,
                exchange=params.mesh_exchange,
                batch_hint=params.mesh_batch_hint,
            )
        if self._sharded is not None:
            self._hist = self._sharded
        else:
            self._hist = EventHistogrammer(
                toa_edges=edges, n_screen=n_screen, pixel_lut=lut
            )
        self._state = self._hist.init_state()
        self._edges_var = Variable(edges, ("toa",), "ns")
        self._n_banks = n_banks
        self._publish = None
        self._prefetched_publish: dict | None = None

    @property
    def is_sharded(self) -> bool:
        return self._sharded is not None

    def accumulate(self, data: Mapping[str, Any]) -> None:
        for value in data.values():
            if isinstance(value, StagedEvents):
                # Single-chip and mesh-sharded kernels share the contract:
                # stage through the window stream-cache (K jobs place the
                # batch once — onto the default device or onto the mesh's
                # P('data') event sharding) and advance the donated state
                # in one dispatch.
                self._state = self._hist.step_batch(
                    self._state, value.batch, cache=value.cache
                )

    def event_ingest(self, stream: str, staged: StagedEvents):
        """Fused-stepping offer — BOTH kernels (core/job_manager.py).
        Feeds the tick program too (ops/tick.py, ADR 0114/0115): the
        bank reductions in the publish program below then ride the
        step's dispatch, one round trip for the whole window. On the
        mesh, that one dispatch IS the collective step (shard_map body)
        plus the replicated publish reductions — the whole serving mesh
        turns over in one execute + one fetch per tick."""
        from ..core.device_event_cache import EventIngest

        def set_state(state) -> None:
            self._state = state

        return EventIngest(
            key=self._hist.fuse_key + ("",),
            hist=self._hist,
            batch=staged.batch,
            batch_tag="",
            get_state=lambda: self._state,
            set_state=set_state,
        )

    def _publisher(self):
        """Lazy fused publish program, both kernels: bank reductions on
        device, one execute + one packed fetch, window fold included
        (ops/publish.py). ``views_of`` is the kernel-portable seam —
        the single-chip kernel slices its flat state, the mesh kernel
        gathers the window to a replicated value (so the reductions
        below and the packed vector replicate, one fetch serves the
        mesh, and the reduction HLO matches the single-device program:
        the byte-parity contract of ADR 0115)."""
        if self._publish is None:
            from ..ops.publish import PackedPublisher

            def program(state):
                cum, win = self._hist.views_of(state)
                shape = (self._n_banks, self._pixels_per_bank, -1)
                win3 = win.reshape(shape)
                cum3 = cum.reshape(shape)
                outputs = {
                    "bank_spectra_current": win3.sum(axis=1),
                    "bank_spectra_cumulative": cum3.sum(axis=1),
                    "bank_counts_current": win3.sum(axis=(1, 2)),
                    "bank_counts_cumulative": cum3.sum(axis=(1, 2)),
                    "counts_current": win3.sum(),
                    "counts_cumulative": cum3.sum(),
                }
                return outputs, self._hist.fold_window(state)

            self._publish = PackedPublisher(program)
        return self._publish

    def publish_offer(self):
        """Combined-publish offer (ADR 0113), both kernels. Tick-capable
        (ADR 0114/0115): args[0] is the pre-step state and the carry is
        exactly ``(new_state,)``, the make_publish_offer contract the
        tick program's donation layout relies on. Mesh-sharded states
        group by their device SET (ops/publish.publish_device), so a
        combined program never mixes mesh and single-device members."""
        from ..ops.publish import make_publish_offer

        return make_publish_offer(
            self,
            self._publisher(),
            (self._state,),
            fresh_state=self._hist.init_state,
        )

    def finalize(self) -> dict[str, DataArray]:
        out = self._prefetched_publish
        if out is not None:
            self._prefetched_publish = None
        else:
            out, self._state = self._publisher()(self._state)
        win_spectra = out["bank_spectra_current"]
        cum_spectra = out["bank_spectra_cumulative"]
        win_counts = out["bank_counts_current"]
        cum_counts = out["bank_counts_cumulative"]
        total_win = out["counts_current"]
        total_cum = out["counts_cumulative"]
        bank_coord = Variable(
            np.arange(self._n_banks), ("bank",), ""
        )
        coords = {"toa": self._edges_var, "bank": bank_coord}
        return {
            "bank_spectra_current": DataArray(
                Variable(win_spectra, ("bank", "toa"), "counts"),
                coords=coords,
                name="bank_spectra_current",
            ),
            "bank_spectra_cumulative": DataArray(
                Variable(cum_spectra, ("bank", "toa"), "counts"),
                coords=coords,
                name="bank_spectra_cumulative",
            ),
            "bank_counts_current": DataArray(
                Variable(win_counts, ("bank",), "counts"),
                coords={"bank": bank_coord},
                name="bank_counts_current",
            ),
            "bank_counts_cumulative": DataArray(
                Variable(cum_counts, ("bank",), "counts"),
                coords={"bank": bank_coord},
                name="bank_counts_cumulative",
            ),
            "counts_current": DataArray(
                Variable(np.asarray(total_win), (), "counts"),
                name="counts_current",
            ),
            "counts_cumulative": DataArray(
                Variable(np.asarray(total_cum), (), "counts"),
                name="counts_cumulative",
            ),
        }

    def clear(self) -> None:
        self._state = self._hist.clear(self._state)
        self._prefetched_publish = None

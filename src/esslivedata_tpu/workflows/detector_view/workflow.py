"""The detector-view streaming workflow.

Reference parity: workflows/detector_view/workflow.py:67 (pipeline),
providers.py:169-328 (histogram, image, counts, spectrum, ROI spectra),
roi.py:31-188 (ROI masks/spectra). The whole per-cycle pipeline is two
jitted programs: ``step`` (scatter-add accumulate, ops/histogram.py) and
``_finalize`` (image/spectrum/counts/ROI summaries computed on device and
pulled to host as small dense outputs).
"""

from __future__ import annotations

from collections.abc import Mapping
from typing import Any, Literal

import json

import jax.numpy as jnp
import numpy as np
from pydantic import BaseModel, ConfigDict, Field

from ...config.models import ROI, PolygonROI, RectangleROI, TOARange
from ...config.roi_names import default_roi_mapper
from ...ops.histogram import EventHistogrammer, HistogramState
from ...preprocessors.event_data import StagedEvents
from ...utils.labeled import DataArray, Variable
from .projectors import ProjectionTable

__all__ = ["DetectorViewParams", "DetectorViewWorkflow", "MAX_ROIS"]



MAX_ROIS = 8
"""ROI mask matrix rows are fixed at this size so ROI edits never trigger
an XLA recompile — unused rows are zero."""


class DetectorViewParams(BaseModel):
    model_config = ConfigDict(frozen=True)

    toa_bins: int = 100
    toa_range: TOARange = Field(default_factory=TOARange)
    pixel_weighting: bool = False
    # Optional TOA sub-range restricting the IMAGE sums (reference:
    # providers.py:236-255 HistogramSlice / counts_in_range:328). The
    # spectrum keeps the full axis. Bin edges are static under jit, so
    # the slice compiles to a static index range — zero runtime cost.
    image_toa_slice: TOARange | None = None
    # Histogram kernel selection (ops/histogram.py): 'scatter' (XLA
    # scatter-add, the safe default), or 'pallas2d' (MXU-tiled kernel,
    # ops/pallas_hist2d.py) for host-flattenable configurations — falls
    # back to 'scatter' when the configuration can't take it
    # (pixel weighting, replica LUTs).
    histogram_method: Literal["scatter", "pallas2d"] = "scatter"


def _density_weights(lut: np.ndarray) -> np.ndarray:
    """Per-pixel 1/occupancy weights compensating projection density
    (reference providers.py:98): screen bins fed by many pixels are
    downweighted so the image reflects rate per screen area."""
    valid = lut[0] >= 0
    counts = np.bincount(lut[0][valid])
    w = np.zeros(lut.shape[1], dtype=np.float32)
    w[valid] = 1.0 / np.maximum(counts[lut[0][valid]], 1)
    return w


class DetectorViewWorkflow:
    """Histogram events on a projected 2-D screen; emit image, spectrum,
    total counts and ROI spectra in current (window) and cumulative views.
    """

    def __init__(
        self,
        *,
        projection: ProjectionTable,
        params: DetectorViewParams | None = None,
        primary_stream: str | None = None,
        filters=None,
    ) -> None:
        params = params or DetectorViewParams()
        self._proj = projection
        self._params = params
        # Optional per-event filter chain (workloads/filters.py, ADR
        # 0122): a digest-tagged host batch transform — rejected events
        # become pixel_id -1 before staging, so filtering costs zero
        # extra device dispatches and same-chain jobs share one
        # filtered wire. None/empty = identity (tag "").
        if filters is None:
            from ...workloads.filters import FilterChain

            filters = FilterChain()
        self._filters = filters
        edges = np.linspace(
            params.toa_range.low, params.toa_range.high, params.toa_bins + 1
        )
        weights = (
            _density_weights(projection.lut) if params.pixel_weighting else None
        )
        method = params.histogram_method
        if method == "pallas2d" and (
            weights is not None
            or (projection.lut is not None and projection.lut.shape[0] > 1)
        ):
            # pallas2d consumes host-partitioned flat indices; weighted
            # and replica configurations stay on the scatter.
            method = "scatter"
        self._hist = EventHistogrammer(
            toa_edges=edges,
            n_screen=projection.n_screen,
            pixel_lut=projection.lut,
            pixel_weights=weights,
            method=method,
        )
        self._state: HistogramState = self._hist.init_state()
        self._primary_stream = primary_stream
        self._roi_mapper = default_roi_mapper()
        assert self._roi_mapper.total_rois <= MAX_ROIS
        self._roi_names: list[str] = []
        self._rois_by_index: dict[int, tuple[str, ROI]] = {}
        self._roi_masks = jnp.zeros(
            (MAX_ROIS, projection.n_screen), dtype=jnp.float32
        )
        ny, nx = projection.ny, projection.nx
        n_toa = self._hist.n_toa
        n_bins = projection.n_screen * n_toa
        # Static slice bounds for the image sums: full axis when the
        # param is absent/disabled. Any bin OVERLAPPING [low, high) is
        # included, so the realized range always covers the request.
        sl = params.image_toa_slice
        if sl is not None and sl.enabled:
            a = max(int(np.searchsorted(edges, sl.low, side="right")) - 1, 0)
            b = min(int(np.searchsorted(edges, sl.high, side="left")), n_toa)
            if a >= b:
                raise ValueError(
                    "image_toa_slice selects no bins within toa_range"
                )
        else:
            a, b = 0, n_toa

        def publish_program(state, roi_masks):
            # The histogrammer owns the state layout (flat, dump bin, lazy
            # decay scale); compose its traceable view here so the fold
            # into the cumulative fuses into the reductions below, and the
            # window fold into the same program — publish is ONE execute
            # plus ONE packed fetch (ops/publish.py).
            win = self._hist.physical_window(state)[:n_bins].reshape(
                projection.n_screen, n_toa
            )
            cum = win + state.folded[:n_bins].reshape(
                projection.n_screen, n_toa
            )
            win_img = win[:, a:b]
            cum_img = cum[:, a:b]
            outputs = {
                "image_current": win_img.sum(axis=1).reshape(ny, nx),
                "image_cumulative": cum_img.sum(axis=1).reshape(ny, nx),
                "spectrum_current": win.sum(axis=0),
                "spectrum_cumulative": cum.sum(axis=0),
                "counts_current": win.sum(),
                "counts_cumulative": cum.sum(),
                "counts_in_range_current": win_img.sum(),
                "counts_in_range_cumulative": cum_img.sum(),
                # [MAX_ROIS, n_toa] on the MXU; unused rows are zero.
                "roi_spectra": roi_masks @ win,
                "roi_spectra_cumulative": roi_masks @ cum,
            }
            return outputs, self._hist.fold_window(state)

        from ...ops.publish import PackedPublisher

        # The ROI spectra blocks are layout-constant (all zeros) until
        # real masks are installed: on the common no-ROI dashboard they
        # are 6.4 KB/tick of fetched-and-discarded data (the majority
        # of the packed vector for small screens), so they ride the
        # static channel — fetched once per layout digest, served from
        # the host cache after (ADR 0113). ``set_rois`` flips them
        # dynamic the moment masks make them carry data.
        self._publish = PackedPublisher(
            publish_program, static_keys=self._STATIC_ROI_KEYS
        )
        #: Combined-publish hand-off (ops/publish.py PublishOffer): the
        #: JobManager prefetches this job's outputs through one fused
        #: device round trip; finalize consumes instead of dispatching.
        self._prefetched_publish: dict | None = None
        self._toa_edges_var = Variable(edges, ("toa",), "ns")
        assert n_toa == edges.size - 1

    _STATIC_ROI_KEYS = ("roi_spectra", "roi_spectra_cumulative")

    def swap_projection(self, projection: ProjectionTable) -> bool:
        """Adopt a rebuilt projection WITHOUT recompiling anything.

        Live-geometry moves (motor-driven LUT rebuilds) land here first:
        when the new table has the same screen shape and this
        configuration runs the host-flatten fast path, the swap is a
        host-side LUT replacement — the jitted step, fold and publish
        programs are untouched. State resets (moved-geometry counts must
        not blend) and installed ROI masks recompute against the new
        screen edges. Returns False when only a full rebuild is correct
        (shape change, per-pixel weighting, device-projection configs).
        """
        if (
            projection.n_screen != self._proj.n_screen
            or projection.ny != self._proj.ny
            or projection.nx != self._proj.nx
            or self._params.pixel_weighting
            or not self._hist.supports_host_flatten
        ):
            return False
        if not self._hist.swap_projection(projection.lut):
            return False  # LUT shape mismatch: full rebuild
        self._proj = projection
        self._state = self._hist.clear(self._state)
        self._prefetched_publish = None
        if self._rois_by_index:
            self.set_rois(
                {name: roi for name, roi in self._rois_by_index.values()}
            )
        return True

    # -- ROI management ----------------------------------------------------
    def set_rois(self, rois: Mapping[str, ROI]) -> None:
        """Install ROI masks (from the dashboard's ROI topic round trip,
        reference roi.py:293).

        Each ROI is assigned a *global index* following the
        ``config/roi_names.py`` partition (rectangles and polygons own
        disjoint index ranges), which is also its mask-matrix row — so the
        ``roi`` coord on the spectra outputs and the readback indices agree
        with the naming convention the dashboard uses for labels. Per-type
        capacity is bounded by the mapper so ROI edits never change array
        shapes (no XLA recompile).
        """
        from ...utils.labeled import midpoints

        xc = midpoints(self._proj.x_edges).numpy
        yc = midpoints(self._proj.y_edges).numpy
        masks = np.zeros((MAX_ROIS, self._proj.n_screen), dtype=np.float32)
        counters = {g.geometry_type: iter(g.index_range) for g in self._roi_mapper.geometries}
        indexed: dict[int, tuple[str, ROI]] = {}
        for name, roi in rois.items():
            gtype = next(
                (
                    g.geometry_type
                    for g in self._roi_mapper.geometries
                    if isinstance(roi, g.roi_class)
                ),
                None,
            )
            if gtype is None:
                raise ValueError(
                    f"ROI {name!r} has unsupported type {type(roi).__name__}"
                )
            try:
                index = next(counters[gtype])
            except StopIteration:
                limit = next(
                    g.num_rois
                    for g in self._roi_mapper.geometries
                    if g.geometry_type == gtype
                )
                raise ValueError(
                    f"At most {limit} {gtype} ROIs supported"
                ) from None
            masks[index] = roi.mask(xc, yc).reshape(-1).astype(np.float32)
            indexed[index] = (name, roi)
        self._rois_by_index = dict(sorted(indexed.items()))
        self._roi_names = [name for name, _ in self._rois_by_index.values()]
        self._roi_masks = jnp.asarray(masks)
        # Installed masks make the ROI spectra carry data: publish them
        # on the dynamic (per-tick) channel. Clearing every ROI flips
        # them back to the static zero blocks.
        self._publish.set_static_keys(
            () if self._rois_by_index else self._STATIC_ROI_KEYS
        )

    @property
    def roi_names(self) -> list[str]:
        return list(self._roi_names)

    # -- Workflow protocol -------------------------------------------------
    def accumulate(self, data: Mapping[str, Any]) -> None:
        for key, value in data.items():
            if isinstance(value, StagedEvents):
                if self._primary_stream is None or key == self._primary_stream:
                    # value.cache (the window's stream slot, attached by
                    # the JobManager) makes flatten + transfer run once
                    # per (stream, layout) across every subscribed job.
                    batch, tag = self._filters.apply(
                        value.batch, value.cache
                    )
                    self._state = self._hist.step_batch(
                        self._state, batch, cache=value.cache,
                        batch_tag=tag,
                    )

    def event_ingest(self, stream: str, staged: StagedEvents):
        """Fused-stepping offer (core/job_manager.py): ingesting a
        primary-stream batch is exactly one histogrammer step over
        this job's private state, so K same-layout detector views can
        advance in one dispatch from one staged batch. On publish ticks
        the same offer feeds the tick program (ops/tick.py, ADR 0114),
        which composes this step with the packed publish below into ONE
        dispatch — ``get_state`` must return the same object
        ``publish_offer`` passes as args[0] (the manager verifies the
        identity and degrades to separate dispatches otherwise)."""
        from ...workloads.filters import filtered_event_ingest

        return filtered_event_ingest(
            self,
            hist=self._hist,
            filters=self._filters,
            primary_stream=self._primary_stream,
            stream=stream,
            staged=staged,
        )

    def publish_offer(self):
        """Combined-publish offer (core/job_manager.py, ADR 0113): this
        job's packed publish program joins the tick's fused device round
        trip; ``finalize`` then consumes the prefetched tree. Under the
        tick program (ADR 0114) args[0] is the PRE-step state — the
        program steps it in-dispatch and publishes the stepped result,
        so one execute + one fetch covers the whole window. The ROI
        static split and the layout-digest token carry through both
        paths unchanged."""
        from ...ops.publish import make_publish_offer

        return make_publish_offer(
            self,
            self._publish,
            (self._state, self._roi_masks),
            static_token=self._hist.layout_digest,
            fresh_state=self._hist.init_state,
        )

    def finalize(self) -> dict[str, DataArray]:
        out = self._prefetched_publish
        if out is not None:
            self._prefetched_publish = None
        else:
            out, self._state = self._publish(
                self._state,
                self._roi_masks,
                static_token=self._hist.layout_digest,
            )

        img_coords = {
            "x": self._proj.x_edges,
            "y": self._proj.y_edges,
        }
        spec_coords = {"toa": self._toa_edges_var}
        results: dict[str, DataArray] = {
            "image_current": DataArray(
                Variable(out["image_current"], ("y", "x"), "counts"),
                coords=img_coords,
                name="image_current",
            ),
            "image_cumulative": DataArray(
                Variable(out["image_cumulative"], ("y", "x"), "counts"),
                coords=img_coords,
                name="image_cumulative",
            ),
            "spectrum_current": DataArray(
                Variable(out["spectrum_current"], ("toa",), "counts"),
                coords=spec_coords,
                name="spectrum_current",
            ),
            "spectrum_cumulative": DataArray(
                Variable(out["spectrum_cumulative"], ("toa",), "counts"),
                coords=spec_coords,
                name="spectrum_cumulative",
            ),
            **{
                k: DataArray(
                    Variable(np.asarray(out[k]), (), "counts"), name=k
                )
                for k in (
                    "counts_current",
                    "counts_cumulative",
                    "counts_in_range_current",
                    "counts_in_range_cumulative",
                )
            },
        }
        if self._rois_by_index:
            indices = np.asarray(list(self._rois_by_index), dtype=np.int32)
            roi_idx = Variable(indices, ("roi",), "")
            for key in ("roi_spectra", "roi_spectra_cumulative"):
                spectra = out[key][indices]
                results[key] = DataArray(
                    Variable(spectra, ("roi", "toa"), "counts"),
                    coords={"toa": self._toa_edges_var, "roi": roi_idx},
                    name=key,
                )
        results.update(self._roi_readbacks())
        return results

    def _roi_readbacks(self) -> dict[str, DataArray]:
        """Applied-ROI readback outputs (reference roi.py:293-355): the
        dashboard renders what the backend actually applied, not what it
        asked for. da00 is numeric-only, so shapes ride as index-keyed
        coordinate arrays (config/roi_names.py convention): rectangles as
        per-ROI bound coords, polygons as per-vertex coords with a roi
        index. Always emitted — an empty readback tells the frontend the
        coordinate units to use when creating ROIs."""
        x_unit = self._proj.x_edges.unit
        y_unit = self._proj.y_edges.unit
        rects = [
            (i, r)
            for i, (_, r) in self._rois_by_index.items()
            if isinstance(r, RectangleROI)
        ]
        polys = [
            (i, r)
            for i, (_, r) in self._rois_by_index.items()
            if isinstance(r, PolygonROI)
        ]
        rect_idx = np.asarray([i for i, _ in rects], dtype=np.int32)
        rect = DataArray(
            Variable(rect_idx, ("roi",), ""),
            coords={
                "x_min": Variable(
                    np.asarray([r.x_min for _, r in rects]), ("roi",), x_unit
                ),
                "x_max": Variable(
                    np.asarray([r.x_max for _, r in rects]), ("roi",), x_unit
                ),
                "y_min": Variable(
                    np.asarray([r.y_min for _, r in rects]), ("roi",), y_unit
                ),
                "y_max": Variable(
                    np.asarray([r.y_max for _, r in rects]), ("roi",), y_unit
                ),
            },
            name="roi_rectangle",
        )
        vert_roi = np.asarray(
            [i for i, p in polys for _ in p.x], dtype=np.int32
        )
        poly = DataArray(
            Variable(vert_roi, ("vertex",), ""),
            coords={
                "x": Variable(
                    np.asarray([x for _, p in polys for x in p.x]),
                    ("vertex",),
                    x_unit,
                ),
                "y": Variable(
                    np.asarray([y for _, p in polys for y in p.y]),
                    ("vertex",),
                    y_unit,
                ),
            },
            name="roi_polygon",
        )
        return {"roi_rectangle": rect, "roi_polygon": poly}

    def clear(self) -> None:
        self._state = self._hist.clear(self._state)
        # A reset between prefetch and finalize must not resurrect the
        # pre-reset window on the next publish.
        self._prefetched_publish = None

    # -- state snapshots (core/state_snapshot.py) --------------------------
    def state_fingerprint(self) -> str:
        """Hash over everything that gives the accumulated bins physical
        meaning; a restored state with a different fingerprint would put
        counts in bins that mean something else."""
        import hashlib

        h = hashlib.sha1()
        h.update(np.ascontiguousarray(self._proj.lut).tobytes())
        h.update(self._toa_edges_var.numpy.tobytes())
        h.update(
            f"{self._proj.ny}x{self._proj.nx}:{self._hist.n_toa}:".encode()
        )
        # Full params EXCEPT the kernel choice: two jobs differing in any
        # physically meaningful parameter must not exchange state, but
        # histogram_method only selects HOW the same bins accumulate —
        # the snapshot codec adapts the layouts (restore_state_arrays),
        # so a kernel switch between runs keeps its recovery snapshot.
        h.update(
            json.dumps(
                self._params.model_dump(exclude={"histogram_method"}),
                sort_keys=True,
            ).encode()
        )
        # Filtered and unfiltered accumulations must never exchange
        # state: the bins mean "events that PASSED this chain".
        h.update(self._filters.digest.encode())
        return h.hexdigest()

    def dump_state(self) -> dict[str, np.ndarray]:
        """Host copy of the device accumulation (folded, window, scale)."""
        return EventHistogrammer.dump_state_arrays(self._state)

    def restore_state(self, arrays: dict[str, np.ndarray]) -> bool:
        """Adopt a dumped accumulation; shape-checked against the current
        kernel (fingerprint matching happens in the store, but a corrupt
        file must not poison the device state)."""
        restored = self._hist.restore_state_arrays(self._state, arrays)
        if restored is None:
            return False
        self._state = restored
        return True

    # -- introspection -----------------------------------------------------
    @property
    def histogrammer(self) -> EventHistogrammer:
        return self._hist

    @property
    def state(self) -> HistogramState:
        return self._state


#: Wire-schema contract (graftlint trace pass, JGL105 / ADR 0123):
#: publish output name -> (ndim, dtype) as serialized on the da00
#: wire. Pinned HERE, next to the publish program it constrains, so a
#: program edit and its schema change ride the same diff — drift
#: between the two breaks the delta codec's keyframe contract and is
#: caught at lint time, not by a subscriber.
TICK_WIRE_SCHEMA = {
    "counts_cumulative": (0, "float32"),
    "counts_current": (0, "float32"),
    "counts_in_range_cumulative": (0, "float32"),
    "counts_in_range_current": (0, "float32"),
    "image_cumulative": (2, "float32"),
    "image_current": (2, "float32"),
    "roi_spectra": (2, "float32"),
    "roi_spectra_cumulative": (2, "float32"),
    "spectrum_cumulative": (1, "float32"),
    "spectrum_current": (1, "float32"),
}

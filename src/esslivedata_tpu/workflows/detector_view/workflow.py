"""The detector-view streaming workflow.

Reference parity: workflows/detector_view/workflow.py:67 (pipeline),
providers.py:169-328 (histogram, image, counts, spectrum, ROI spectra),
roi.py:31-188 (ROI masks/spectra). The whole per-cycle pipeline is two
jitted programs: ``step`` (scatter-add accumulate, ops/histogram.py) and
``_finalize`` (image/spectrum/counts/ROI summaries computed on device and
pulled to host as small dense outputs).
"""

from __future__ import annotations

from collections.abc import Mapping
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
from pydantic import BaseModel, ConfigDict, Field

from ...config.models import ROI, TOARange
from ...ops.histogram import EventHistogrammer, HistogramState
from ...preprocessors.event_data import StagedEvents
from ...utils.labeled import DataArray, Variable
from .projectors import ProjectionTable

__all__ = ["DetectorViewParams", "DetectorViewWorkflow", "MAX_ROIS"]

MAX_ROIS = 8
"""ROI mask matrix rows are fixed at this size so ROI edits never trigger
an XLA recompile — unused rows are zero."""


class DetectorViewParams(BaseModel):
    model_config = ConfigDict(frozen=True)

    toa_bins: int = 100
    toa_range: TOARange = Field(default_factory=TOARange)
    pixel_weighting: bool = False


def _density_weights(lut: np.ndarray) -> np.ndarray:
    """Per-pixel 1/occupancy weights compensating projection density
    (reference providers.py:98): screen bins fed by many pixels are
    downweighted so the image reflects rate per screen area."""
    valid = lut[0] >= 0
    counts = np.bincount(lut[0][valid])
    w = np.zeros(lut.shape[1], dtype=np.float32)
    w[valid] = 1.0 / np.maximum(counts[lut[0][valid]], 1)
    return w


class DetectorViewWorkflow:
    """Histogram events on a projected 2-D screen; emit image, spectrum,
    total counts and ROI spectra in current (window) and cumulative views.
    """

    def __init__(
        self,
        *,
        projection: ProjectionTable,
        params: DetectorViewParams | None = None,
        primary_stream: str | None = None,
    ) -> None:
        params = params or DetectorViewParams()
        self._proj = projection
        self._params = params
        edges = np.linspace(
            params.toa_range.low, params.toa_range.high, params.toa_bins + 1
        )
        weights = (
            _density_weights(projection.lut) if params.pixel_weighting else None
        )
        self._hist = EventHistogrammer(
            toa_edges=edges,
            n_screen=projection.n_screen,
            pixel_lut=projection.lut,
            pixel_weights=weights,
        )
        self._state: HistogramState = self._hist.init_state()
        self._primary_stream = primary_stream
        self._roi_names: list[str] = []
        self._roi_masks = jnp.zeros(
            (MAX_ROIS, projection.n_screen), dtype=jnp.float32
        )
        ny, nx = projection.ny, projection.nx
        n_toa = self._hist.n_toa

        def summarize(cum, win, roi_masks):
            return {
                "image_current": win.sum(axis=1).reshape(ny, nx),
                "image_cumulative": cum.sum(axis=1).reshape(ny, nx),
                "spectrum_current": win.sum(axis=0),
                "spectrum_cumulative": cum.sum(axis=0),
                "counts_current": win.sum(),
                "counts_cumulative": cum.sum(),
                # [MAX_ROIS, n_toa] on the MXU; unused rows are zero.
                "roi_spectra": roi_masks @ win,
            }

        self._summarize = jax.jit(summarize)
        self._toa_edges_var = Variable(edges, ("toa",), "ns")
        assert n_toa == edges.size - 1

    # -- ROI management ----------------------------------------------------
    def set_rois(self, rois: Mapping[str, ROI]) -> None:
        """Install ROI masks (from the dashboard's ROI topic round trip,
        reference roi.py:293). Limited to MAX_ROIS, extra ROIs rejected."""
        if len(rois) > MAX_ROIS:
            raise ValueError(f"At most {MAX_ROIS} ROIs supported, got {len(rois)}")
        from ...utils.labeled import midpoints

        xc = midpoints(self._proj.x_edges).numpy
        yc = midpoints(self._proj.y_edges).numpy
        masks = np.zeros((MAX_ROIS, self._proj.n_screen), dtype=np.float32)
        names = []
        for i, (name, roi) in enumerate(rois.items()):
            masks[i] = roi.mask(xc, yc).reshape(-1).astype(np.float32)
            names.append(name)
        self._roi_names = names
        self._roi_masks = jnp.asarray(masks)

    @property
    def roi_names(self) -> list[str]:
        return list(self._roi_names)

    # -- Workflow protocol -------------------------------------------------
    def accumulate(self, data: Mapping[str, Any]) -> None:
        for key, value in data.items():
            if isinstance(value, StagedEvents):
                if self._primary_stream is None or key == self._primary_stream:
                    self._state = self._hist.step(self._state, value.batch)

    def finalize(self) -> dict[str, DataArray]:
        out = self._summarize(
            self._state.cumulative, self._state.window, self._roi_masks
        )
        out = {k: np.asarray(v) for k, v in out.items()}
        self._state = self._hist.clear_window(self._state)

        img_coords = {
            "x": self._proj.x_edges,
            "y": self._proj.y_edges,
        }
        spec_coords = {"toa": self._toa_edges_var}
        results: dict[str, DataArray] = {
            "image_current": DataArray(
                Variable(out["image_current"], ("y", "x"), "counts"),
                coords=img_coords,
                name="image_current",
            ),
            "image_cumulative": DataArray(
                Variable(out["image_cumulative"], ("y", "x"), "counts"),
                coords=img_coords,
                name="image_cumulative",
            ),
            "spectrum_current": DataArray(
                Variable(out["spectrum_current"], ("toa",), "counts"),
                coords=spec_coords,
                name="spectrum_current",
            ),
            "spectrum_cumulative": DataArray(
                Variable(out["spectrum_cumulative"], ("toa",), "counts"),
                coords=spec_coords,
                name="spectrum_cumulative",
            ),
            "counts_current": DataArray(
                Variable(np.asarray(out["counts_current"]), (), "counts"),
                name="counts_current",
            ),
            "counts_cumulative": DataArray(
                Variable(np.asarray(out["counts_cumulative"]), (), "counts"),
                name="counts_cumulative",
            ),
        }
        if self._roi_names:
            spectra = out["roi_spectra"][: len(self._roi_names)]
            results["roi_spectra"] = DataArray(
                Variable(spectra, ("roi", "toa"), "counts"),
                coords={
                    "toa": self._toa_edges_var,
                    "roi": Variable(np.arange(len(self._roi_names)), ("roi",), ""),
                },
                name="roi_spectra",
            )
        return results

    def clear(self) -> None:
        self._state = self._hist.clear(self._state)

    # -- introspection -----------------------------------------------------
    @property
    def histogrammer(self) -> EventHistogrammer:
        return self._hist

    @property
    def state(self) -> HistogramState:
        return self._state

"""Detector ratemeter: counts in a selected analyzer arc + pixel range
(reference: bifrost/specs.py:350 detector_ratemeter, :59
DetectorRatemeterRegionParams).

The region — one analyzer arc (selected by its final energy) and a
pixel index range along it — precompiles into a pixel LUT mapping
selected pixels to one screen bin and everything else to drop, so the
streaming cost is the standard scatter kernel with n_screen=1 and one
TOA bin. Current/cumulative outputs carry the time coords the job layer
stamps on results, which the dashboard's Rate option divides by.
"""

from __future__ import annotations

from collections.abc import Mapping
from typing import Any

import numpy as np
from pydantic import BaseModel, ConfigDict, Field, model_validator

from ..config.models import TOARange
from ..ops.histogram import EventHistogrammer
from ..preprocessors.event_data import StagedEvents
from ..utils.labeled import DataArray, Variable

__all__ = ["RatemeterParams", "RatemeterWorkflow"]

#: Match tolerance when selecting an arc by final energy (meV).
_ARC_EF_TOL = 0.05


class RatemeterParams(BaseModel):
    model_config = ConfigDict(frozen=True)

    # Arc selected by its analyzer final energy (BIFROST: 2.7, 3.2,
    # 3.8, 4.4 or 5.0 meV).
    arc_ef_mev: float = 5.0
    pixel_start: int = 0  # index along the arc (two_theta order)
    pixel_stop: int = 900
    # Accepted arrival window. BIFROST's 162 m incident path delivers
    # long-frame arrivals far beyond one pulse period, so the default
    # spans the whole frame rather than [0, pulse) — the same window
    # family the QE/elastic maps use (qe_spectroscopy.py toa_range).
    toa_range: TOARange = Field(
        default_factory=lambda: TOARange(low=0.0, high=4.0e8)
    )

    @model_validator(mode="after")
    def _range_valid(self) -> RatemeterParams:
        if self.pixel_start < 0:
            raise ValueError("pixel_start must be >= 0")
        if self.pixel_start >= self.pixel_stop:
            raise ValueError("pixel_start must be less than pixel_stop")
        return self


class RatemeterWorkflow:
    """Counts for a selected arc + pixel range, window and cumulative."""

    def __init__(
        self,
        *,
        two_theta: np.ndarray,
        ef_mev: np.ndarray,
        pixel_ids: np.ndarray,
        params: RatemeterParams | None = None,
        primary_stream: str | None = None,
    ) -> None:
        params = params or RatemeterParams()
        self._params = params
        ef = np.asarray(ef_mev, dtype=np.float64)
        ids = np.asarray(pixel_ids)
        on_arc = np.abs(ef - params.arc_ef_mev) <= _ARC_EF_TOL
        if not on_arc.any():
            levels = sorted({float(x) for x in np.round(ef, 2)})
            raise ValueError(
                f"no pixels on an arc at Ef = {params.arc_ef_mev} meV; "
                f"available levels: {levels}"
            )
        # Order the arc by scattering angle, then apply the index range.
        arc_ids = ids[on_arc][np.argsort(np.asarray(two_theta)[on_arc])]
        selected = arc_ids[params.pixel_start : params.pixel_stop]
        if selected.size == 0:
            raise ValueError(
                f"pixel range [{params.pixel_start}, {params.pixel_stop}) "
                f"is beyond the arc's {arc_ids.size} pixels"
            )
        lut = np.full((1, int(ids.max()) + 1), -1, dtype=np.int32)
        lut[0, selected] = 0
        self._n_region_pixels = int(selected.size)
        self._hist = EventHistogrammer(
            toa_edges=np.array([params.toa_range.low, params.toa_range.high]),
            n_screen=1,
            pixel_lut=lut,
        )
        self._state = self._hist.init_state()
        self._primary_stream = primary_stream

    @property
    def n_region_pixels(self) -> int:
        return self._n_region_pixels

    def accumulate(self, data: Mapping[str, Any]) -> None:
        for key, value in data.items():
            if isinstance(value, StagedEvents):
                if self._primary_stream is None or key == self._primary_stream:
                    # Stage-once (ADR 0110): K ratemeters on one stream
                    # share the window's staged batch by reference.
                    self._state = self._hist.step_batch(
                        self._state, value.batch, cache=value.cache
                    )

    def event_ingest(self, stream: str, staged: StagedEvents):
        """Fused-stepping offer (core/job_manager.py): same shape as the
        detector view — one histogrammer step per primary-stream batch."""
        if self._primary_stream is not None and stream != self._primary_stream:
            return None
        from ..core.device_event_cache import EventIngest

        def set_state(state) -> None:
            self._state = state

        return EventIngest(
            key=self._hist.fuse_key + ("",),
            hist=self._hist,
            batch=staged.batch,
            batch_tag="",
            get_state=lambda: self._state,
            set_state=set_state,
        )

    def finalize(self) -> dict[str, DataArray]:
        cum, win = self._hist.read(self._state)
        self._state = self._hist.clear_window(self._state)
        return {
            "detector_region_counts": DataArray(
                Variable(np.asarray(float(win.sum())), (), "counts"),
                name="detector_region_counts",
            ),
            "detector_region_counts_cumulative": DataArray(
                Variable(np.asarray(float(cum.sum())), (), "counts"),
                name="detector_region_counts_cumulative",
            ),
        }

    def clear(self) -> None:
        self._state = self._hist.clear(self._state)

"""f144-driven dynamic geometry: motor motion -> projection-table rebuild.

Parity with reference ``workflows/dynamic_transforms.py`` (synthesised
providers patching live motor values into NeXus ``depends_on`` transform
chains) re-expressed for the TPU design: the projection is a precomputed
pixel->screen LUT (detector_view/projectors.py), so live geometry means
*rebuilding that LUT on the host* when a bound motor value moves, without
stalling the stream, and resetting accumulated histograms — moved-geometry
counts must not blend with old-geometry counts (the reference's
reset-on-geometry-change semantics, accumulators.py NoCopyAccumulator and
monitor geometry_signal).

A ``TransformChain`` is the NeXus ``NXtransformations`` model: an ordered
sequence of axis transforms (translation along / rotation about a vector),
each either static or bound to a context stream (a motor's synthesized
Device stream or plain f144 log). Chains apply depends_on-style: the last
entry is applied first, base positions are in the component's local frame.
"""

from __future__ import annotations

from collections.abc import Mapping
from dataclasses import dataclass, field
from typing import Any, Callable, Literal

import numpy as np

from ..utils.labeled import DataArray
from .detector_view.projectors import ProjectionTable, project_geometric

__all__ = [
    "DynamicGeometry",
    "DynamicGeometryWorkflow",
    "Transform",
    "TransformChain",
]


@dataclass(frozen=True)
class Transform:
    """One NXtransformations axis: translate along or rotate about ``vector``.

    ``value`` is the static magnitude (translation in ``unit``, rotation in
    degrees); ``stream`` optionally binds it to a context stream whose
    latest sample replaces the static value at evaluation time.
    """

    kind: Literal["translation", "rotation"]
    vector: tuple[float, float, float]
    value: float = 0.0
    stream: str | None = None
    offset: tuple[float, float, float] = (0.0, 0.0, 0.0)

    def resolve(self, values: Mapping[str, float]) -> float:
        if self.stream is not None and self.stream in values:
            return float(values[self.stream])
        return self.value

    def matrix(self, value: float) -> np.ndarray:
        """4x4 homogeneous matrix for this axis at ``value``."""
        m = np.eye(4)
        v = np.asarray(self.vector, dtype=float)
        norm = np.linalg.norm(v)
        if norm == 0:
            raise ValueError("Transform vector must be non-zero")
        v = v / norm
        if self.kind == "translation":
            m[:3, 3] = v * value
        else:  # rotation by `value` degrees about v (Rodrigues)
            theta = np.deg2rad(value)
            k = np.array(
                [[0, -v[2], v[1]], [v[2], 0, -v[0]], [-v[1], v[0], 0]]
            )
            m[:3, :3] = (
                np.eye(3) + np.sin(theta) * k + (1 - np.cos(theta)) * (k @ k)
            )
        m[:3, 3] += np.asarray(self.offset, dtype=float)
        return m


@dataclass(frozen=True)
class TransformChain:
    """Ordered depends_on chain; ``transforms[0]`` is closest to the root."""

    transforms: tuple[Transform, ...] = ()

    def bound_streams(self) -> list[str]:
        return [t.stream for t in self.transforms if t.stream is not None]

    def apply(
        self, positions: np.ndarray, values: Mapping[str, float]
    ) -> np.ndarray:
        """Transform [n, 3] positions through the chain with live values."""
        m = np.eye(4)
        for t in self.transforms:
            m = m @ t.matrix(t.resolve(values))
        out = positions @ m[:3, :3].T + m[:3, 3]
        return out

    def signature(self, values: Mapping[str, float]) -> tuple[float, ...]:
        """The live values actually in effect — the geometry signal."""
        return tuple(t.resolve(values) for t in self.transforms)


@dataclass
class DynamicGeometry:
    """A detector bank whose position depends on live motor values."""

    base_positions: np.ndarray  # [n, 3] in the component frame
    pixel_ids: np.ndarray
    chain: TransformChain
    projection: str = "xy_plane"
    resolution: tuple[int, int] = (128, 128)
    noise_sigma: float = 0.0
    n_replica: int = 1
    atol: float = 1e-6
    """Geometry-signal change below this does not count as motion."""
    extent: tuple[float, float, float, float] | None = None
    _last_signature: tuple[float, ...] | None = field(default=None, repr=False)

    def moved(self, values: Mapping[str, float]) -> bool:
        """True when bound values moved since the last build (or never built)."""
        sig = self.chain.signature(values)
        if self._last_signature is None:
            return True
        return any(
            abs(a - b) > self.atol
            for a, b in zip(sig, self._last_signature, strict=True)
        )

    def build_projection(self, values: Mapping[str, float]) -> ProjectionTable:
        self._last_signature = self.chain.signature(values)
        positions = self.chain.apply(self.base_positions, values)
        return project_geometric(
            positions,
            self.pixel_ids,
            mode=self.projection,
            resolution=self.resolution,
            noise_sigma=self.noise_sigma,
            n_replica=self.n_replica,
            extent=self.extent,
        )


def _latest_value(sample: Any) -> float | None:
    """Latest numeric sample from an NXlog series / LogData / scalar."""
    if sample is None:
        return None
    if isinstance(sample, DataArray):
        values = np.atleast_1d(np.asarray(sample.data.values))
        return float(values[-1]) if values.size else None
    if hasattr(sample, "value"):
        values = np.atleast_1d(np.asarray(sample.value))
        return float(values[-1]) if values.size else None
    try:
        return float(sample)
    except (TypeError, ValueError):
        return None


class DynamicGeometryWorkflow:
    """Workflow decorator rebuilding the projection when geometry moves.

    Wraps a factory ``make(projection) -> Workflow`` (e.g. a
    DetectorViewWorkflow closure). ``set_context`` extracts the latest
    value of every chain-bound stream; when the geometry signal moves the
    inner workflow is rebuilt from a fresh projection table — accumulated
    state intentionally resets (moved-geometry counts must not blend) and
    installed ROIs are re-applied.
    """

    def __init__(
        self,
        *,
        geometry: DynamicGeometry,
        make: Callable[[ProjectionTable], Any],
    ) -> None:
        self._geometry = geometry
        self._make = make
        self._values: dict[str, float] = {}
        self._rois: Mapping[str, Any] | None = None
        self._inner = make(geometry.build_projection({}))

    @property
    def inner(self) -> Any:
        return self._inner

    def set_context(self, context: Mapping[str, Any]) -> None:
        for stream in self._geometry.chain.bound_streams():
            if (value := _latest_value(context.get(stream))) is not None:
                self._values[stream] = value
        if self._geometry.moved(self._values):
            projection = self._geometry.build_projection(self._values)
            # Same-shape rebuilds swap the LUT into the running kernel
            # (no recompile — see DetectorViewWorkflow.swap_projection);
            # anything else falls back to a full rebuild.
            if not (
                hasattr(self._inner, "swap_projection")
                and self._inner.swap_projection(projection)
            ):
                self._inner = self._make(projection)
                # The swap branch re-installs its own ROI masks; only a
                # fresh inner needs them applied here.
                if self._rois is not None and hasattr(self._inner, "set_rois"):
                    self._inner.set_rois(self._rois)
        if hasattr(self._inner, "set_context"):
            self._inner.set_context(context)

    def set_rois(self, rois: Mapping[str, Any]) -> None:
        self._rois = rois
        if hasattr(self._inner, "set_rois"):
            self._inner.set_rois(rois)

    def accumulate(self, data: Mapping[str, Any]) -> None:
        self._inner.accumulate(data)

    def publish_offer(self):
        """Delegate combined-publish offers (ADR 0113): geometry can
        only move in ``set_context``, which the JobManager delivers
        before the publish phase — the inner workflow is stable between
        the offer and its finalize."""
        offer_fn = getattr(self._inner, "publish_offer", None)
        return None if offer_fn is None else offer_fn()

    def finalize(self) -> dict[str, DataArray]:
        return self._inner.finalize()

    def clear(self) -> None:
        self._inner.clear()

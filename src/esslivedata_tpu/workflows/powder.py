"""Powder-diffraction d-spacing workflow (DREAM).

The reference reduces DREAM through ess.powder's sciline graph
(reference: instruments/dream/factories.py — CorrectedDspacing with
proton-charge run normalization). The TPU-native shape matches the
other reductions: Bragg physics precompiles into a host-built
(pixel, toa-bin) -> d-bin map (ops/qhistogram.build_dspacing_map), the
streaming work is one gather+scatter per batch into fold-semantics
state, and normalization divides by the aux-monitor counts (this
framework's stand-in for accumulated proton charge).

The emission-time correction (a WFM subframe T0 from the chopper
cascade) is LIVE: when an ``emission_offset`` context stream is bound,
its value overrides the static ``toa_offset_ns`` param and changes
rebuild + swap the Bragg table into the running kernel (ADR 0105) —
counts persist because the d bin space is unchanged.
"""

from __future__ import annotations

from collections.abc import Mapping
from typing import Any

import numpy as np
from pydantic import BaseModel, ConfigDict, Field

from ..config.models import TOARange
from ..ops.chopper_cascade import ALPHA_NS_PER_M_A
from ..ops.qhistogram import PixelBinMap, QHistogrammer, build_dspacing_map
from ..utils.labeled import DataArray, Variable
from .qshared import QStreamingMixin, latest_sample_value

__all__ = [
    "PowderDiffractionParams",
    "PowderDiffractionWorkflow",
    "PowderVanadiumWorkflow",
    "vanadium_acceptance",
]


class PowderDiffractionParams(BaseModel):
    model_config = ConfigDict(frozen=True)

    d_bins: int = 400
    d_min: float = 0.4  # angstrom
    d_max: float = 2.8
    toa_bins: int = 500
    toa_range: TOARange = Field(default_factory=TOARange)
    #: Emission-time correction (e.g. WFM subframe T0 from the chopper
    #: cascade); a live recalibration rebuilds + swaps the table.
    toa_offset_ns: float = 0.0
    #: Offset moves below this are jitter, not a recalibration.
    offset_tolerance_ns: float = 1000.0
    #: 2-theta resolution of the d-2theta map (reference:
    #: FocussedDataDspacingTwoTheta, dream/factories.py:249). The 1-D
    #: I(d) is the marginal of this map, so one kernel feeds both.
    two_theta_bins: int = Field(default=8, ge=1)


def vanadium_acceptance(
    table: np.ndarray, n_bins: int, *, n_bands: int = 1
) -> np.ndarray:
    """Per-d-bin instrument acceptance from the Bragg table itself.

    A vanadium run measures the incoherent (flat-in-d) response of the
    instrument: how many (pixel, TOF-bin) cells feed each d bin. That
    count IS readable off the precompiled table — ``bincount`` of its
    valid entries — giving the live-mode analog of the reference's
    vanadium normalization (reference: dream/factories.py:267, which
    divides by a recorded vanadium run). The result is scaled to mean 1
    over the populated bins so normalized intensities keep the
    magnitude of the monitor-normalized spectrum; bins with zero
    acceptance stay 0 and are masked at division time. A measured
    vanadium spectrum can replace this via
    ``PowderVanadiumWorkflow.set_vanadium``.

    ``n_bands``: the tables :class:`PowderDiffractionWorkflow` builds are
    composite — entry ``d_bin * n_bands + band`` — so pass the workflow's
    2-theta band count to decompose them back to d bins. The default 1
    accepts raw ``build_dspacing_map`` tables whose entries are plain
    d bins.
    """
    from ..ops.qhistogram import _MAP_CHUNK

    # Chunk over leading-axis rows (a same-shape reshape never copies,
    # unlike reshape(-1) on a non-contiguous table).
    arr = np.asarray(table)
    rows = arr.reshape(1, -1) if arr.ndim == 1 else arr.reshape(arr.shape[0], -1)
    rows_per_chunk = max(1, _MAP_CHUNK // rows.shape[1]) if rows.shape[1] else 1
    counts = np.zeros(n_bins, dtype=np.float64)
    # Chunked: no full-table boolean/quotient temporary.
    for lo in range(0, rows.shape[0], rows_per_chunk):
        sl = np.ravel(rows[lo : lo + rows_per_chunk])
        valid = sl[sl >= 0].astype(np.int64) // n_bands
        counts += np.bincount(valid, minlength=n_bins)
    populated = counts > 0
    if populated.any():
        counts[populated] /= counts[populated].mean()
    return counts


class PowderDiffractionWorkflow(QStreamingMixin):
    """Detector events -> I(d); aux monitor events -> normalization."""

    def __init__(
        self,
        *,
        two_theta: np.ndarray,
        l_total: np.ndarray,
        pixel_ids: np.ndarray,
        params: PowderDiffractionParams | None = None,
        primary_stream: str | None = None,
        monitor_streams: set[str] | None = None,
        offset_stream: str = "emission_offset",
    ) -> None:
        params = params or PowderDiffractionParams()
        self._params = params
        d_edges = np.linspace(params.d_min, params.d_max, params.d_bins + 1)
        toa_edges = np.linspace(
            params.toa_range.low, params.toa_range.high, params.toa_bins + 1
        )
        self._geometry = {
            "two_theta": np.asarray(two_theta, dtype=np.float64),
            "l_total": np.asarray(l_total, dtype=np.float64),
            "pixel_ids": np.asarray(pixel_ids),
        }
        self._d_edges = d_edges
        self._toa_edges = toa_edges
        self._offset_stream = offset_stream
        self._offset_ns = float(params.toa_offset_ns)
        self._built_offset_ns = self._offset_ns
        # Per-pixel 2-theta band for the (d, 2theta) map; the composite
        # flat bin is d_bin * n_bands + band.
        tt = self._geometry["two_theta"]
        self._n_bands = int(params.two_theta_bins)
        self._tt_edges = np.linspace(
            float(tt.min()), float(np.nextafter(tt.max(), np.inf)),
            self._n_bands + 1,
        )
        self._band = np.clip(
            np.searchsorted(self._tt_edges, tt, side="right") - 1,
            0,
            self._n_bands - 1,
        )
        dmap = self._build_table()
        self._hist = QHistogrammer(
            qmap=dmap,
            toa_edges=toa_edges,
            n_q=params.d_bins * self._n_bands,
            method="auto",
        )
        self._state = self._hist.init_state()
        self._d_var = Variable(d_edges, ("dspacing",), "angstrom")
        self._tt_var = Variable(self._tt_edges, ("two_theta",), "rad")
        # DIFC from the mean geometry: tof = ALPHA * L * 2 sin(theta) * d
        # (the reference's d -> TOF conversion for the focussed spectrum,
        # dream/factories.py:180).
        difc = (
            ALPHA_NS_PER_M_A
            * float(self._geometry["l_total"].mean())
            * 2.0
            * np.sin(float(tt.mean()) / 2.0)
        )
        self._tof_var = Variable(d_edges * difc, ("tof",), "ns")
        self._primary_stream = primary_stream
        self._monitor_streams = monitor_streams or set()
        self._publish = None

    def _build_table(self) -> PixelBinMap:
        dmap = build_dspacing_map(
            **self._geometry,
            toa_edges=self._toa_edges,
            d_edges=self._d_edges,
            toa_offset_ns=self._offset_ns,
        )
        # Compose the per-pixel 2-theta band into the flat bin. Band is
        # indexed by table row (bank-local ids), widening to int32 when
        # the composite bin space outgrows int16. Chunked over rows to
        # keep peak host memory at the same chunk-bound the map builders
        # guarantee (mantle-scale tables are ~GB as int32).
        from ..ops.qhistogram import _MAP_CHUNK

        ids = self._geometry["pixel_ids"]
        band_by_row = np.zeros(dmap.table.shape[0], dtype=np.int32)
        band_by_row[np.asarray(ids) - dmap.id_base] = self._band
        n_flat = (len(self._d_edges) - 1) * self._n_bands
        dtype = np.int16 if n_flat < np.iinfo(np.int16).max else np.int32
        composite = np.empty(dmap.table.shape, dtype=dtype)
        for lo in range(0, dmap.table.shape[0], _MAP_CHUNK):
            sl = slice(lo, min(lo + _MAP_CHUNK, dmap.table.shape[0]))
            t = dmap.table[sl].astype(np.int32)
            composite[sl] = np.where(
                t >= 0, t * self._n_bands + band_by_row[sl, None], -1
            ).astype(dtype)
        return PixelBinMap(table=composite, id_base=dmap.id_base)

    def set_context(self, context: Mapping[str, Any]) -> None:
        """A live emission-time calibration (WFM subframe T0) arrives as
        context; moves beyond the tolerance swap a rebuilt Bragg table
        into the running kernel — no recompile, counts persist."""
        if (
            value := latest_sample_value(context.get(self._offset_stream))
        ) is not None:
            self._offset_ns = value

    def accumulate(self, data: Mapping[str, Any]) -> None:
        if (
            abs(self._offset_ns - self._built_offset_ns)
            >= self._params.offset_tolerance_ns
        ):
            self._hist.swap_table(self._build_table())
            self._built_offset_ns = self._offset_ns
        super().accumulate(data)

    def _spectrum(self, values: np.ndarray, name: str, unit="counts"):
        return DataArray(
            Variable(values, ("dspacing",), unit),
            coords={"dspacing": self._d_var},
            name=name,
        )

    def finalize(self) -> dict[str, DataArray]:
        win2d, cum2d, mon_win, mon_cum = self._take_publish()
        shape = (self._params.d_bins, self._n_bands)
        win2d = win2d.reshape(shape)
        cum2d = cum2d.reshape(shape)
        win = win2d.sum(axis=1)
        cum = cum2d.sum(axis=1)
        return {
            "dspacing_current": self._spectrum(win, "dspacing_current"),
            "dspacing_cumulative": self._spectrum(
                cum, "dspacing_cumulative"
            ),
            "dspacing_normalized": self._spectrum(
                cum / max(mon_cum, 1.0), "dspacing_normalized", unit=""
            ),
            "dspacing_two_theta": DataArray(
                Variable(cum2d, ("dspacing", "two_theta"), "counts"),
                coords={"dspacing": self._d_var, "two_theta": self._tt_var},
                name="dspacing_two_theta",
            ),
            "focussed_tof": DataArray(
                Variable(cum, ("tof",), "counts"),
                coords={"tof": self._tof_var},
                name="focussed_tof",
            ),
            "counts_current": DataArray(
                Variable(np.asarray(win.sum()), (), "counts"),
                name="counts_current",
            ),
            "monitor_counts_current": DataArray(
                Variable(np.asarray(mon_win), (), "counts"),
                name="monitor_counts_current",
            ),
        }


class PowderVanadiumWorkflow(PowderDiffractionWorkflow):
    """I(d) with vanadium normalization (reference:
    dream/specs.py:356 powder_reduction_with_vanadium).

    Divides the monitor-normalized spectrum per d bin by a vanadium
    response — by default the acceptance correction derived from the
    Bragg table (``vanadium_acceptance``), replaceable with a measured
    spectrum. The table-derived default recomputes automatically when a
    live emission-offset recalibration swaps the table.
    """

    _measured_vanadium: np.ndarray | None = None

    def _build_table(self):
        # Derive the acceptance as the table passes through — both the
        # initial build and live emission-offset swaps land here, so the
        # correction always matches the active table without retaining a
        # host copy of the (large) table anywhere.
        table = super()._build_table()
        if self._measured_vanadium is None:
            self._vanadium = vanadium_acceptance(
                table.table, self._params.d_bins, n_bands=self._n_bands
            )
        return table

    def set_vanadium(self, spectrum: np.ndarray) -> None:
        """Install a measured vanadium d-spectrum (same d binning)."""
        spectrum = np.asarray(spectrum, dtype=np.float64)
        if spectrum.shape != (self._params.d_bins,):
            raise ValueError(
                f"vanadium spectrum must have {self._params.d_bins} bins"
            )
        self._measured_vanadium = spectrum
        self._vanadium = spectrum

    def finalize(self) -> dict[str, DataArray]:
        results = super().finalize()
        norm = results["dspacing_normalized"].values
        with np.errstate(divide="ignore", invalid="ignore"):
            intensity = np.where(
                self._vanadium > 0, norm / self._vanadium, 0.0
            )
        results["intensity_dspacing"] = self._spectrum(
            intensity, "intensity_dspacing", unit=""
        )
        return results

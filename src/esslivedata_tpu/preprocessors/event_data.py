"""Event staging: ev44 chunks -> fixed-shape padded device batches.

TPU-native equivalent of the reference's ``to_nxevent_data.py`` +
``group_by_pixel.py``: the reference builds a scipp binned array (events
binned by pulse) and then groups by detector_number so workflows can
histogram; here the accumulator only *stages* raw event arrays into a
reusable padded host buffer (ops/event_batch.StagingBuffer) — the jitted
scatter kernel does projection+grouping+binning in one pass on device. The
zero-copy / release_buffers contract is the same as the reference's
(_buffers_in_use guard, to_nxevent_data.py:166-171).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, ClassVar

import numpy as np

from ..core.timestamp import Timestamp
from ..ops.event_batch import (
    EventBatch,
    bucket_size,
    make_staging_buffer,
    sanitize_pixel_id,
)

__all__ = [
    "DetectorEvents",
    "EventChunkRef",
    "MonitorEvents",
    "StagedEvents",
    "ToEventBatch",
]


@dataclass(frozen=True, slots=True)
class MonitorEvents:
    """Decoded ev44 monitor chunk: times of arrival only (the fast-path
    adapter skips pixel ids, reference message_adapter.py:360)."""

    time_of_arrival: np.ndarray  # ns within pulse

    @property
    def n_events(self) -> int:
        return int(self.time_of_arrival.shape[0])


@dataclass(frozen=True, slots=True)
class DetectorEvents:
    """Decoded ev44 detector chunk: pixel ids + times of arrival."""

    pixel_id: np.ndarray
    time_of_arrival: np.ndarray

    @property
    def n_events(self) -> int:
        return int(self.pixel_id.shape[0])


@dataclass(frozen=True, slots=True)
class EventChunkRef:
    """Lazy event chunk: a wire header view instead of decoded arrays.

    The batch decode plane's adapted-message payload (ADR 0125): wraps a
    ``kafka.wire.Ev44View`` (duck-typed — n_tof/n_pid counts, zero-copy
    ``time_of_flight``/``pixel_id`` properties, ``fill_into``) so the
    adapter allocates NO per-message ndarrays; the payload bytes are
    read exactly once, when the accumulator lands the whole window into
    a decode arena. ``monitor`` carries the adapter's routing decision:
    a monitor chunk zero-fills pixel ids whatever the wire holds (the
    reference's pixel-less monitor semantics).

    The ``pixel_id``/``time_of_arrival`` properties materialize arrays
    with the same dtypes the eager adapters produced — the compatibility
    surface for consumers outside the ref-mode accumulator.
    """

    view: Any  # kafka.wire.Ev44View (duck-typed; no kafka import here)
    monitor: bool = False

    @property
    def n_events(self) -> int:
        return int(self.view.n_tof)

    @property
    def pixel_id(self) -> np.ndarray:
        if self.monitor:
            return np.zeros(self.view.n_tof, dtype=np.int32)
        return self.view.pixel_id

    @property
    def time_of_arrival(self) -> np.ndarray:
        return self.view.time_of_flight.astype(np.float32)

    def fill_into(self, pid_dst: np.ndarray, toa_dst: np.ndarray) -> None:
        """Land the payload into arena slices of length ``n_events``
        (int32→float32 toa cast fused into the assignment)."""
        if self.monitor:
            toa_dst[:] = self.view.time_of_flight
            pid_dst[:] = 0
        else:
            self.view.fill_into(pid_dst, toa_dst)


@dataclass(frozen=True, slots=True)
class _ArrayChunk:
    """Eager arrays adopted into a ref-mode window (mixed producers):
    pays the per-message host sanitize the eager path always paid."""

    pixel_id: np.ndarray
    time_of_arrival: np.ndarray

    @property
    def n_events(self) -> int:
        return int(np.asarray(self.time_of_arrival).shape[0])

    def fill_into(self, pid_dst: np.ndarray, toa_dst: np.ndarray) -> None:
        pid_dst[:] = sanitize_pixel_id(self.pixel_id)
        toa_dst[:] = self.time_of_arrival


@dataclass(slots=True)
class StagedEvents:
    """One window's worth of staged events, ready for the device kernel."""

    batch: EventBatch
    first_timestamp: Timestamp | None
    last_timestamp: Timestamp | None
    n_chunks: int
    #: Window stream-cache slot (core/device_event_cache.StreamStageSlot),
    #: attached by the JobManager before fan-out: workflows thread it into
    #: their kernels so K jobs sharing this stream stage the batch once.
    #: None outside the managed path (tests, direct workflow use).
    cache: object | None = None

    @property
    def n_events(self) -> int:
        return self.batch.n_valid

    def detach(self) -> StagedEvents:
        """A copy owning its event arrays (see ``EventBatch.detach``) —
        the pipelined hand-off form; the cache slot is dropped (the
        pipeline's stage worker attaches the next window generation's)."""
        return StagedEvents(
            batch=self.batch.detach(),
            first_timestamp=self.first_timestamp,
            last_timestamp=self.last_timestamp,
            n_chunks=self.n_chunks,
        )


class ToEventBatch:
    """Accumulator staging event chunks into one padded device batch.

    Accepts DetectorEvents or MonitorEvents (monitor events get pixel_id 0,
    so a monitor is screen row 0 of a 1-row histogram), plus the batch
    decode plane's :class:`EventChunkRef` (ADR 0125). A window whose
    FIRST chunk is a ref runs in **ref mode**: instead of appending
    decoded arrays into the staging buffer per message, the accumulator
    records (chunk, offset) bookkeeping only, and ``get()`` leases a
    decode arena and lands every payload straight off the wire in one
    sequential fill — no per-message ndarray, one copy total
    (wire → arena; ``stage_raw`` then device-puts the arena views and
    runs the device decode prologue). Eager chunks arriving mid-window
    are adopted (:class:`_ArrayChunk`), refs arriving into an eager
    window materialize through their array properties — either mix is
    byte-identical to the all-eager path.
    """

    is_context: ClassVar[bool] = False

    def __init__(
        self, min_bucket: int | None = None, prefer_native: bool = True
    ) -> None:
        if min_bucket:
            self._buffer = make_staging_buffer(min_bucket, prefer_native)
        else:
            self._buffer = make_staging_buffer(prefer_native=prefer_native)
        self._min_bucket = min_bucket or 0
        self._first: Timestamp | None = None
        self._last: Timestamp | None = None
        self._n_chunks = 0
        #: Ref-mode window state: None = eager mode. The list holds
        #: fill_into-capable chunks in arrival order (message order is
        #: the arena order — part of the byte-identity contract).
        self._chunks: list | None = None
        self._ref_total = 0
        self._ref_taken = False

    def add(
        self,
        timestamp: Timestamp,
        data: DetectorEvents | MonitorEvents | EventChunkRef,
    ) -> None:
        if self._ref_taken:
            raise RuntimeError(
                "ToEventBatch.add called before release_buffers() of the "
                "last ref-mode batch"
            )
        lazy = hasattr(data, "fill_into")
        if lazy and self._chunks is None and self._n_chunks == 0:
            self._chunks = []  # first chunk is a ref: ref-mode window
        if self._chunks is not None:
            if lazy:
                view = getattr(data, "view", None)
                if (
                    view is not None
                    and not data.monitor
                    and view.n_pid
                    and view.n_pid != view.n_tof
                ):
                    # Same containment point as the eager path's
                    # broadcast failure: raise at add(), the message
                    # preprocessor skips this message.
                    raise ValueError(
                        f"ev44 pixel_id length {view.n_pid} != "
                        f"time_of_flight length {view.n_tof}"
                    )
                self._chunks.append(data)
            else:
                if isinstance(data, MonitorEvents) or not hasattr(
                    data, "pixel_id"
                ):
                    pixel_id = np.zeros(
                        np.asarray(data.time_of_arrival).shape[0],
                        dtype=np.int32,
                    )
                else:
                    pixel_id = data.pixel_id
                self._chunks.append(
                    _ArrayChunk(
                        pixel_id=pixel_id,
                        time_of_arrival=data.time_of_arrival,
                    )
                )
            self._ref_total += self._chunks[-1].n_events
        else:
            # Eager mode. The staging buffer's own add() sanitizes pixel
            # ids (no-op pass for wire int32) and casts on assignment —
            # no defensive asarray/astype copies on this hot path.
            toa = data.time_of_arrival
            if isinstance(data, MonitorEvents) or not hasattr(
                data, "pixel_id"
            ):
                pixel_id = np.zeros(
                    np.asarray(toa).shape[0], dtype=np.int32
                )
            else:
                pixel_id = data.pixel_id
            self._buffer.add(pixel_id, toa)
        if self._first is None or timestamp < self._first:
            self._first = timestamp
        if self._last is None or timestamp > self._last:
            self._last = timestamp
        self._n_chunks += 1

    def _take_ref_batch(self) -> EventBatch:
        """Land every recorded chunk into a leased decode arena: the
        window's single contiguous (pixel, toa) pair, padded to the
        bucket boundary, owned by the arena lease (``detach`` is free).
        ``prologue=True`` defers pixel-id validation to the device
        decode prologue fused into ``stage_raw``."""
        from ..core.device_event_cache import default_decode_pool

        n = self._ref_total
        b = (
            bucket_size(n, self._min_bucket)
            if self._min_bucket
            else bucket_size(n)
        )
        lease = default_decode_pool().lease(b)
        pid = lease.pixel[:b]
        toa = lease.toa[:b]
        pos = 0
        for chunk in self._chunks:
            k = chunk.n_events
            chunk.fill_into(pid[pos : pos + k], toa[pos : pos + k])
            pos += k
        pid[n:b] = -1
        toa[n:b] = 0.0
        self._ref_taken = True
        return EventBatch(
            pixel_id=pid,
            toa=toa,
            n_valid=n,
            owner=lease,
            owned=True,
            prologue=True,
        )

    def get(self) -> StagedEvents:
        batch = (
            self._take_ref_batch()
            if self._chunks is not None
            else self._buffer.take()
        )
        staged = StagedEvents(
            batch=batch,
            first_timestamp=self._first,
            last_timestamp=self._last,
            n_chunks=self._n_chunks,
        )
        return staged

    def clear(self) -> None:
        self._buffer.clear()
        self._chunks = None
        self._ref_total = 0
        self._ref_taken = False
        self._first = None
        self._last = None
        self._n_chunks = 0

    def release_buffers(self) -> None:
        self._buffer.release()
        self._chunks = None
        self._ref_total = 0
        self._ref_taken = False
        self._first = None
        self._last = None
        self._n_chunks = 0

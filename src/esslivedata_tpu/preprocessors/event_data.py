"""Event staging: ev44 chunks -> fixed-shape padded device batches.

TPU-native equivalent of the reference's ``to_nxevent_data.py`` +
``group_by_pixel.py``: the reference builds a scipp binned array (events
binned by pulse) and then groups by detector_number so workflows can
histogram; here the accumulator only *stages* raw event arrays into a
reusable padded host buffer (ops/event_batch.StagingBuffer) — the jitted
scatter kernel does projection+grouping+binning in one pass on device. The
zero-copy / release_buffers contract is the same as the reference's
(_buffers_in_use guard, to_nxevent_data.py:166-171).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import ClassVar

import numpy as np

from ..core.timestamp import Timestamp
from ..ops.event_batch import EventBatch, make_staging_buffer

__all__ = ["DetectorEvents", "MonitorEvents", "StagedEvents", "ToEventBatch"]


@dataclass(frozen=True, slots=True)
class MonitorEvents:
    """Decoded ev44 monitor chunk: times of arrival only (the fast-path
    adapter skips pixel ids, reference message_adapter.py:360)."""

    time_of_arrival: np.ndarray  # ns within pulse

    @property
    def n_events(self) -> int:
        return int(self.time_of_arrival.shape[0])


@dataclass(frozen=True, slots=True)
class DetectorEvents:
    """Decoded ev44 detector chunk: pixel ids + times of arrival."""

    pixel_id: np.ndarray
    time_of_arrival: np.ndarray

    @property
    def n_events(self) -> int:
        return int(self.pixel_id.shape[0])


@dataclass(slots=True)
class StagedEvents:
    """One window's worth of staged events, ready for the device kernel."""

    batch: EventBatch
    first_timestamp: Timestamp | None
    last_timestamp: Timestamp | None
    n_chunks: int
    #: Window stream-cache slot (core/device_event_cache.StreamStageSlot),
    #: attached by the JobManager before fan-out: workflows thread it into
    #: their kernels so K jobs sharing this stream stage the batch once.
    #: None outside the managed path (tests, direct workflow use).
    cache: object | None = None

    @property
    def n_events(self) -> int:
        return self.batch.n_valid

    def detach(self) -> StagedEvents:
        """A copy owning its event arrays (see ``EventBatch.detach``) —
        the pipelined hand-off form; the cache slot is dropped (the
        pipeline's stage worker attaches the next window generation's)."""
        return StagedEvents(
            batch=self.batch.detach(),
            first_timestamp=self.first_timestamp,
            last_timestamp=self.last_timestamp,
            n_chunks=self.n_chunks,
        )


class ToEventBatch:
    """Accumulator staging event chunks into one padded device batch.

    Accepts DetectorEvents or MonitorEvents (monitor events get pixel_id 0,
    so a monitor is screen row 0 of a 1-row histogram).
    """

    is_context: ClassVar[bool] = False

    def __init__(
        self, min_bucket: int | None = None, prefer_native: bool = True
    ) -> None:
        if min_bucket:
            self._buffer = make_staging_buffer(min_bucket, prefer_native)
        else:
            self._buffer = make_staging_buffer(prefer_native=prefer_native)
        self._first: Timestamp | None = None
        self._last: Timestamp | None = None
        self._n_chunks = 0

    def add(self, timestamp: Timestamp, data: DetectorEvents | MonitorEvents) -> None:
        toa = np.asarray(data.time_of_arrival)
        if isinstance(data, MonitorEvents) or not hasattr(data, "pixel_id"):
            pixel_id = np.zeros(toa.shape[0], dtype=np.int32)
        else:
            pixel_id = np.asarray(data.pixel_id)
        self._buffer.add(
            pixel_id.astype(np.int32, copy=False),
            toa.astype(np.float32, copy=False),
        )
        if self._first is None or timestamp < self._first:
            self._first = timestamp
        if self._last is None or timestamp > self._last:
            self._last = timestamp
        self._n_chunks += 1

    def get(self) -> StagedEvents:
        staged = StagedEvents(
            batch=self._buffer.take(),
            first_timestamp=self._first,
            last_timestamp=self._last,
            n_chunks=self._n_chunks,
        )
        return staged

    def clear(self) -> None:
        self._buffer.clear()
        self._first = None
        self._last = None
        self._n_chunks = 0

    def release_buffers(self) -> None:
        self._buffer.release()
        self._first = None
        self._last = None
        self._n_chunks = 0

"""Dense-data accumulators over labeled DataArrays.

Parity with reference ``preprocessors/accumulators.py``: ``Cumulative``
(+= with restart on structural mismatch, reference :238-261),
``LatestValueAccumulator`` (context, :57), ``NullAccumulator`` (:46).
The reference's NoCopyAccumulator and its paired window/cumulative
variant exist to avoid deepcopying a 500 MB histogram on every read
(:96-97). That problem does not arise here *by construction*: large
histograms are device-resident kernel state with fold semantics
(ops/histogram.py — window and cumulative share one scatter, reads are
device views), and host-side accumulators only ever hold the small dense
outputs. ``Cumulative`` therefore defaults to no-copy reads and there is
deliberately no pair API to keep aliasing-safe.
"""

from __future__ import annotations

from typing import ClassVar

from ..core.timestamp import Timestamp
from ..utils.labeled import DataArray

__all__ = ["Cumulative", "LatestValueAccumulator", "NullAccumulator"]


class NullAccumulator:
    """Swallows everything; for streams a service must consume but ignore."""

    is_context: ClassVar[bool] = False

    def add(self, timestamp: Timestamp, data: object) -> None:
        pass

    def get(self) -> None:
        return None

    def clear(self) -> None:
        pass

    def release_buffers(self) -> None:
        pass


class LatestValueAccumulator:
    """Keeps the most recent value — context streams (motor positions,
    chopper settings) that parameterize workflows. is_context=True gates
    job activation until a value exists (ADR 0002)."""

    is_context: ClassVar[bool] = True

    def __init__(self) -> None:
        self._value = None
        self._timestamp: Timestamp | None = None

    def add(self, timestamp: Timestamp, data: object) -> None:
        if self._timestamp is None or timestamp >= self._timestamp:
            self._value = data
            self._timestamp = timestamp

    @property
    def has_value(self) -> bool:
        return self._value is not None

    def get(self):
        if self._value is None:
            raise ValueError("LatestValueAccumulator is empty")
        return self._value

    def clear(self) -> None:
        self._value = None
        self._timestamp = None

    def release_buffers(self) -> None:
        pass


class Cumulative:
    """Running += of DataArrays, restarting when structure changes.

    A structural mismatch (different dims/shape/unit/coords — e.g. the
    upstream reconfigured its binning or an ad00 camera changed ROI) resets
    the accumulation to the new value instead of erroring, matching the
    reference's restart-on-mismatch behavior (accumulators.py:238-261).

    This subsumes the reference's ``reset_coord`` knob
    (NoCopyAccumulator:114-127): geometry is carried as coordinates
    (monitor position, detector transform), and ``same_structure`` compares
    coordinate *values* — so accumulation already restarts when the
    geometry moves, without naming the coord up front.

    ``clear_on_get`` gives window semantics (value since last read);
    otherwise since-start. Reads are no-copy by default: callers must not
    mutate the returned array (copy_on_get=True for defensive copies).
    """

    is_context: ClassVar[bool] = False

    def __init__(
        self, *, clear_on_get: bool = False, copy_on_get: bool = False
    ) -> None:
        self._clear_on_get = clear_on_get
        self._copy_on_get = copy_on_get
        self._value: DataArray | None = None

    def add(self, timestamp: Timestamp, data: DataArray) -> None:
        if self._value is not None and self._value.same_structure(data):
            self._value += data
        else:
            # restart: first value, or structure changed upstream (incl.
            # geometry coords — see class docstring)
            self._value = data.copy()

    @property
    def is_empty(self) -> bool:
        return self._value is None

    def get(self) -> DataArray:
        if self._value is None:
            raise ValueError("Cumulative accumulator is empty")
        value = self._value
        if self._copy_on_get:
            value = value.copy()
        if self._clear_on_get:
            self._value = None
        return value

    def clear(self) -> None:
        self._value = None

    def release_buffers(self) -> None:
        pass


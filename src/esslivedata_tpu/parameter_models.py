"""Shared pydantic parameter models (reference: parameter_models.py).

The UI schema vocabulary workflow params are built from: unit-tagged
ranges, bin-edge specs with linear/log scales, unit enums, and the
free-text numeric-list parser backing list inputs. ``get_*`` accessors
return plain floats in the declared unit (the reference returns scipp
scalars; our labeled-array layer keeps units on outputs, params stay
plain numbers converted by the consuming workflow).
"""

from __future__ import annotations

import json

import numpy as np
from pydantic import BaseModel, Field, field_validator, model_validator

from .utils.compat import StrEnum

__all__ = [
    "Angle",
    "AngleUnit",
    "DspacingUnit",
    "EdgesModel",
    "LengthUnit",
    "QUnit",
    "RangeModel",
    "Scale",
    "TimeUnit",
    "WavelengthUnit",
    "parse_number_list",
]


def parse_number_list(value: str) -> list[float]:
    """Parse a comma-separated numeric string; blank -> []; raises on
    non-numbers so it can back a pydantic field_validator for free-text
    list inputs (widgets have no native list input)."""
    value = value.strip()
    if not value:
        return []
    try:
        parsed = json.loads(f"[{value}]")
    except json.JSONDecodeError as err:
        raise ValueError(f"Invalid number list: {err}") from err
    if any(
        isinstance(x, bool) or not isinstance(x, (int, float)) for x in parsed
    ):
        raise ValueError("All entries must be numbers")
    return [float(x) for x in parsed]


class Scale(StrEnum):
    LINEAR = "linear"
    LOG = "log"


class TimeUnit(StrEnum):
    NS = "ns"
    US = "us"
    MS = "ms"
    S = "s"


class WavelengthUnit(StrEnum):
    ANGSTROM = "angstrom"
    NANOMETER = "nm"


class DspacingUnit(StrEnum):
    ANGSTROM = "angstrom"
    NANOMETER = "nm"


class LengthUnit(StrEnum):
    METER = "m"
    CENTIMETER = "cm"
    MILLIMETER = "mm"


class AngleUnit(StrEnum):
    DEGREE = "deg"
    RADIAN = "rad"


class QUnit(StrEnum):
    INVERSE_ANGSTROM = "1/angstrom"
    INVERSE_NANOMETER = "1/nm"


class RangeModel(BaseModel):
    """A (start, stop) range; subclasses add a ``unit`` field."""

    start: float = Field(default=0.0, description="Start of the range.")
    stop: float = Field(default=10.0, description="Stop of the range.")

    @field_validator("stop")
    @classmethod
    def _stop_after_start(cls, v, info):
        start = info.data.get("start")
        if start is not None and v <= start:
            raise ValueError("stop must be greater than start")
        return v


class EdgesModel(BaseModel):
    """Bin edges: range + count + scale; ``get_edges`` materializes them."""

    start: float = Field(default=1.0, description="Start of the edges.")
    stop: float = Field(default=10.0, description="Stop of the edges.")
    num_bins: int = Field(default=100, ge=1, le=10000)
    scale: Scale = Field(default=Scale.LINEAR)

    @field_validator("stop")
    @classmethod
    def _stop_after_start(cls, v, info):
        start = info.data.get("start")
        if start is not None and v <= start:
            raise ValueError("stop must be greater than start")
        return v

    @model_validator(mode="after")
    def _log_needs_positive_start(self):
        if self.scale == Scale.LOG and self.start <= 0:
            raise ValueError("start must be positive when scale is 'log'")
        return self

    def get_edges(self) -> np.ndarray:
        if self.scale == Scale.LOG:
            return np.geomspace(self.start, self.stop, self.num_bins + 1)
        return np.linspace(self.start, self.stop, self.num_bins + 1)


class Angle(BaseModel):
    value: float = Field(default=0.0)
    unit: AngleUnit = Field(default=AngleUnit.DEGREE)

    def get_degrees(self) -> float:
        if self.unit == AngleUnit.RADIAN:
            return float(np.rad2deg(self.value))
        return float(self.value)

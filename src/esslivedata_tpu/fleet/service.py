"""``livedata-relay``: the fan-out edge service (ADR 0121).

A relay container runs this entry point with an ``--upstream`` base URL
(the compute-tier service's ``--serve-port`` endpoint, or another
relay's — relays chain) and its own ``--serve-port``. It consumes every
upstream stream over SSE (fleet/sse_client.py: Last-Event-ID resume,
bounded jittered reconnect backoff), reconstructs frames with the delta
decoder, and re-fans them through its own BroadcastServer hub — the
``docker-compose.fleet.yml`` topology scales subscriber capacity by
adding relay replicas while the compute tier still encodes once per
tick.

Operational surface, same rules as every service runner:

- ``--metrics-port`` serves ``/metrics`` + ``/healthz``
  (telemetry/http.py) with the ``livedata_relay_*`` families
  (docs/fleet.md has the reading guide);
- ``--serve-port`` serves the standard fan-out endpoints
  (docs/serving.md) with ``hop`` = upstream hop + 1 on every
  ``/results`` row, federated so streams not yet relayed point at the
  upstream hop;
- SIGTERM/SIGINT drain and exit 0; a bind failure raises at startup
  (the loud-bind rule).
"""

from __future__ import annotations

import argparse
import logging
import os
import signal
import threading

__all__ = ["build_arg_parser", "main"]

logger = logging.getLogger(__name__)


def _env_default(name: str, fallback=None):
    value = os.environ.get(name)
    return value if value not in (None, "") else fallback


def build_arg_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        description="esslivedata-tpu relay: re-fan an upstream result "
        "stream (ADR 0121)"
    )
    parser.add_argument(
        "--upstream",
        default=_env_default("LIVEDATA_RELAY_UPSTREAM"),
        help="upstream base URL, e.g. http://detector-data:5011 "
        "(env: LIVEDATA_RELAY_UPSTREAM)",
    )
    parser.add_argument(
        "--serve-port",
        type=int,
        default=_env_default("LIVEDATA_SERVE_PORT"),
        help="port for this relay's /results + /streams endpoints "
        "(env: LIVEDATA_SERVE_PORT)",
    )
    parser.add_argument("--host", default="0.0.0.0")
    parser.add_argument(
        "--metrics-port",
        type=int,
        default=_env_default("LIVEDATA_METRICS_PORT"),
        help="/metrics + /healthz port (env: LIVEDATA_METRICS_PORT)",
    )
    parser.add_argument(
        "--queue-limit",
        type=int,
        default=32,
        help="per-subscriber bounded queue (overflow coalesces, "
        "ADR 0117)",
    )
    parser.add_argument(
        "--heartbeat-s",
        type=float,
        default=10.0,
        help="idle-stream SSE heartbeat interval on THIS relay's hub",
    )
    parser.add_argument(
        "--poll-interval",
        type=float,
        default=2.0,
        help="seconds between upstream /results discovery polls",
    )
    parser.add_argument(
        "--idle-timeout",
        type=float,
        default=30.0,
        help="seconds of upstream silence (no frames, no heartbeats) "
        "before a stream connection is declared dead and redialed",
    )
    parser.add_argument(
        "--name",
        default=_env_default("LIVEDATA_RELAY_NAME", "relay"),
        help="relay name on telemetry labels and /results rows",
    )
    parser.add_argument(
        "--check",
        action="store_true",
        help="validate configuration and exit (container smoke)",
    )
    return parser


def main(argv=None) -> int:
    logging.basicConfig(
        level=os.environ.get("LIVEDATA_LOG_LEVEL", "INFO"),
        format="%(asctime)s %(levelname)s %(name)s: %(message)s",
    )
    args = build_arg_parser().parse_args(argv)
    if not args.upstream:
        build_arg_parser().error(
            "--upstream (or LIVEDATA_RELAY_UPSTREAM) is required"
        )
    if args.serve_port is None:
        build_arg_parser().error(
            "--serve-port (or LIVEDATA_SERVE_PORT) is required"
        )
    if args.check:
        print(
            f"relay config OK: upstream={args.upstream} "
            f"serve_port={args.serve_port} metrics_port={args.metrics_port}"
        )
        return 0

    # Imports after --check so the container smoke stays dependency-light.
    from ..serving.plane import ServingPlane
    from ..telemetry.http import start_metrics_server
    from .relay import RelayPlane

    metrics = start_metrics_server(
        None if args.metrics_port is None else int(args.metrics_port)
    )
    plane = ServingPlane(
        port=int(args.serve_port),
        host=args.host,
        queue_limit=args.queue_limit,
        name=args.name,
        heartbeat_s=args.heartbeat_s,
    )
    relay = RelayPlane(
        args.upstream,
        plane.server,
        poll_interval_s=args.poll_interval,
        idle_timeout_s=args.idle_timeout,
        name=args.name,
    )
    logger.info(
        "relay %s up: upstream=%s serve=:%s metrics=%s",
        args.name,
        args.upstream,
        plane.port,
        "off" if metrics is None else f":{metrics.port}",
    )
    stop = threading.Event()

    def _on_signal(signum, frame):  # pragma: no cover - signal path
        logger.info("signal %d: draining relay", signum)
        stop.set()

    signal.signal(signal.SIGTERM, _on_signal)
    signal.signal(signal.SIGINT, _on_signal)
    try:
        while not stop.is_set():
            stop.wait(1.0)
    finally:
        relay.close()
        plane.close()
        if metrics is not None:
            metrics.close()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())

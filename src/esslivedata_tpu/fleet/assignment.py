"""Sticky (stream, fuse-key) -> replica partitioning (ADR 0121).

ADR 0115's :class:`~..parallel.mesh_tick.DevicePlacement` spreads tick
groups across the chips of ONE process; this module generalizes the
same key — the ``(stream, fuse-key)`` tick/fused group — to a fleet of
service replicas. Every replica computes the SAME deterministic
assignment from the SAME replica set, with no coordinator:

**Rendezvous (HRW) hashing**: for each group key, every replica id is
scored with ``blake2b(replica | key)`` and the highest score owns the
group. The property that matters operationally is *minimal movement*:
when a replica joins or leaves, only the groups whose argmax changes
move — exactly the joining/leaving replica's share (~1/N) — so a
rebalance re-keys a handful of groups instead of reshuffling the world
(pinned in tests/fleet/assignment_test.py). A moved group's state is a
**replay-the-gap** event, not a reset: the new owner restores from the
newest checkpoint and replays from the Kafka bookmark through the
normal ingest path (ADR 0118) — nothing about the group's accumulation
is lost, subscribers see one keyframe.

The replica set is **membership-driven**: a static ``--fleet-replicas``
list works for compose topologies, and the Kafka consumer-group
monitor (kafka/consumer.py ``GroupMembership``) supplies the rebalance
TRIGGER — its observer fires on every assignment, the caller
re-resolves the replica roster from its configured source and applies
it via :meth:`FleetAssignment.apply_membership` — so a crashed
replica's groups fail over at the group-protocol cadence.

The JobManager consults :meth:`owns` once per group per window
(``JobManager.set_fleet``): owned groups process, unowned groups'
fresh data is dropped on this replica (another replica is processing
it) while already-accumulated state still flushes. Consults count into
``livedata_fleet_group_checks{decision}`` so an operator can see the
partition working from any replica's scrape.
"""

from __future__ import annotations

import threading
from collections.abc import Callable, Iterable
from hashlib import blake2b

from ..telemetry.registry import REGISTRY, MetricFamily, Sample

__all__ = ["FleetAssignment", "rendezvous_owner"]

#: Ownership consults from the JobManager window path, by decision —
#: ``owned`` groups process here, ``skipped`` groups belong to a peer.
FLEET_GROUP_CHECKS = REGISTRY.counter(
    "livedata_fleet_group_checks",
    "Fleet-assignment ownership consults by the window path, by "
    "decision (owned = processed on this replica)",
    labelnames=("decision",),
)


def _score(replica: str, key: str) -> int:
    digest = blake2b(
        f"{replica}|{key}".encode(), digest_size=8
    ).digest()
    return int.from_bytes(digest, "big")


def rendezvous_owner(replicas: Iterable[str], key: str) -> str:
    """The HRW winner for ``key`` over ``replicas`` (must be
    non-empty). Pure and stateless — every replica computing this over
    the same set gets the same answer, which IS the protocol."""
    best = None
    best_score = -1
    for replica in replicas:
        score = _score(replica, key)
        if score > best_score or (
            score == best_score and (best is None or replica < best)
        ):
            best, best_score = replica, score
    if best is None:
        raise ValueError("empty replica set owns nothing")
    return best


class FleetAssignment:
    """Deterministic group->replica table for one fleet.

    ``self_id`` names THIS replica (required for :meth:`owns`; a
    router/observer-only assignment may omit it). ``set_replicas`` /
    ``apply_membership`` swap the replica set at runtime; observers
    (registered with :meth:`add_observer`) fire outside the lock with
    the new generation so the serving layer can trigger
    checkpoint-restore replay for newly-owned groups.
    """

    def __init__(
        self,
        replicas: Iterable[str],
        self_id: str | None = None,
        *,
        name: str = "fleet",
    ) -> None:
        replica_set = tuple(sorted(set(replicas)))
        if not replica_set:
            raise ValueError("a fleet needs at least one replica")
        if self_id is not None and self_id not in replica_set:
            raise ValueError(
                f"self_id {self_id!r} not in replica set {replica_set}"
            )
        self._lock = threading.Lock()
        self._replicas = replica_set
        self.self_id = self_id
        self._name = name
        self._generation = 0
        self._rebalances = 0
        self._observers: list[Callable[[int, tuple[str, ...]], None]] = []
        self._owned_child = FLEET_GROUP_CHECKS.labels(decision="owned")
        self._skipped_child = FLEET_GROUP_CHECKS.labels(
            decision="skipped"
        )
        self._collector_key = f"fleet:assignment:{name}"
        REGISTRY.register_collector(self._collector_key, self._telemetry)

    # -- assignment ---------------------------------------------------------
    @staticmethod
    def group_key(stream: str, fuse_tag=None) -> str:
        """The canonical hash key for a tick/fused group. ``fuse_tag``
        is the group's fuse key (``offer.key`` in the JobManager's
        grouping) — deterministic across replicas because it derives
        from layout digests and wire formats, not object ids; None
        keys ungrouped work by stream alone."""
        return stream if fuse_tag is None else f"{stream}|{fuse_tag!r}"

    def owner(self, stream: str, fuse_tag=None) -> str:
        with self._lock:
            replicas = self._replicas
        return rendezvous_owner(replicas, self.group_key(stream, fuse_tag))

    # graft: protocol=fleet (ADR 0124: the self_id compare below is the
    # modeled ownership guard; rendezvous_owner itself is imported by
    # the model, never reimplemented)
    def owns(self, stream: str, fuse_tag=None) -> bool:
        """True when THIS replica owns the group (requires
        ``self_id``). Counts the consult into the decision counter."""
        if self.self_id is None:
            raise ValueError("owns() needs a self_id; use owner()")
        owned = self.owner(stream, fuse_tag) == self.self_id
        (self._owned_child if owned else self._skipped_child).inc()
        return owned

    # -- membership ---------------------------------------------------------
    @property
    def replicas(self) -> tuple[str, ...]:
        with self._lock:
            return self._replicas

    @property
    def generation(self) -> int:
        with self._lock:
            return self._generation

    def set_replicas(
        self,
        replicas: Iterable[str],
        *,
        generation: int | None = None,
    ) -> bool:
        """Swap the replica set; returns True when it actually changed
        (observers fire only then, OUTSIDE the lock). ``generation``
        adopts the consumer-group generation when membership-driven;
        otherwise a local counter increments."""
        replica_set = tuple(sorted(set(replicas)))
        if not replica_set:
            raise ValueError("a fleet needs at least one replica")
        with self._lock:
            if replica_set == self._replicas:
                if generation is not None:
                    self._generation = max(self._generation, generation)
                return False
            if (
                self.self_id is not None
                and self.self_id not in replica_set
            ):
                raise ValueError(
                    f"self_id {self.self_id!r} left the replica set "
                    f"{replica_set}; a departing replica must stop, "
                    "not silently own nothing"
                )
            self._replicas = replica_set
            self._rebalances += 1
            self._generation = (
                generation
                if generation is not None
                else self._generation + 1
            )
            observers = list(self._observers)
            gen = self._generation
        for observer in observers:
            observer(gen, replica_set)
        return True

    def apply_membership(
        self, members: Iterable[str], generation: int
    ) -> bool:
        """Adopt a membership view: ``members`` are REPLICA IDS (from
        static config or a deployment registry), ``generation`` the
        rebalance generation that triggered the refresh. The Kafka
        ``GroupMembership`` observer (kafka/consumer.py) supplies the
        trigger and the generation — not the roster: a group member
        only sees its own partition assignment, so the caller
        re-resolves the replica set and passes it here."""
        return self.set_replicas(members, generation=generation)

    def add_observer(
        self, observer: Callable[[int, tuple[str, ...]], None]
    ) -> None:
        with self._lock:
            self._observers.append(observer)

    # -- introspection ------------------------------------------------------
    def moved_keys(
        self, keys: Iterable[str], old_replicas: Iterable[str]
    ) -> list[str]:
        """Which of ``keys`` changed owner between ``old_replicas`` and
        the current set — the operator's rebalance-impact probe (HRW
        guarantees this is ~the joining/leaving replica's share)."""
        with self._lock:
            current = self._replicas
        old = tuple(sorted(set(old_replicas)))
        return [
            key
            for key in keys
            if rendezvous_owner(old, key) != rendezvous_owner(current, key)
        ]

    def _telemetry(self) -> list[MetricFamily]:
        replicas_fam = MetricFamily(
            "livedata_fleet_replicas",
            "gauge",
            "Replicas in the fleet assignment's current view",
        )
        gen_fam = MetricFamily(
            "livedata_fleet_generation",
            "gauge",
            "Membership generation the assignment was computed from",
        )
        rebalance_fam = MetricFamily(
            "livedata_fleet_rebalances",
            "counter",
            "Replica-set changes applied to the assignment",
        )
        base = (("fleet", self._name),)
        with self._lock:
            replicas_fam.samples.append(
                Sample("", base, len(self._replicas))
            )
            gen_fam.samples.append(Sample("", base, self._generation))
            rebalance_fam.samples.append(
                Sample("_total", base, self._rebalances)
            )
        return [replicas_fam, gen_fam, rebalance_fam]

    def close(self) -> None:
        REGISTRY.unregister_collector(self._collector_key, self._telemetry)

"""SSE consumption for the relay tree (ADR 0121).

A relay subscribes to its upstream exactly like any browser: ``GET
/streams/<job>/<output>`` on the upstream :class:`~..serving.broadcast.
BroadcastServer` and reads the keyframe-then-delta event stream
(docs/serving.md). This module is the transport half of that — the
protocol parser plus a reconnecting client — and it is deliberately
telemetry-free: :mod:`.relay` owns the ``livedata_relay_*`` counters,
this layer just hands it frames.

Wire dialect (what the hub's SSE handler emits, serving/broadcast.py):

- ``id: <boot>:<epoch>:<seq>`` — the hub's incarnation id plus the
  delta-codec position of the event; the client retains the last one
  and echoes it as a ``Last-Event-ID`` header on reconnect, which lets
  an upstream whose boot + epoch still match resume with DELTAS from
  its recent-frame ring instead of a full keyframe. A boot change
  across a reconnect means the upstream RESTARTED — its epoch/seq
  numbering is no longer comparable, and the relay hard-resyncs.
- ``event: keyframe|delta`` + ``data: <base64 blob>`` — the delta-codec
  blob (serving/delta.py wire).
- ``: source_ts_ns=<int>`` — frame freshness metadata (ADR 0120),
  parsed so the relay can propagate the SOURCE timestamp downstream and
  the e2e histogram spans the whole tree.
- ``: keepalive`` — idle-stream heartbeat; carries no event but resets
  the client's idle clock, so a silent-but-alive upstream is never
  mistaken for a dead one.

Reconnect discipline: every reconnect waits a **bounded, jittered
exponential backoff** — base doubling per consecutive failure, capped
at ``backoff_cap_s``, multiplied by a seeded uniform jitter in
[0.5, 1.5) so a fleet of relays that lost the same upstream never
reconnects in lockstep (graftlint JGL026 polices exactly this shape in
client/relay modules). A successfully parsed frame resets the ladder.
The wait runs on the stop event, so ``stop()`` interrupts a sleeping
client immediately.
"""

from __future__ import annotations

# graftlint: disable-file=JGL012 - parser/client state is single-owner by
# contract: every SSEParser/SSEClient instance is created and driven by
# exactly ONE consume loop (a relay stream worker, or a test's main
# thread). The multi-role report is an aliasing artifact of analyzing
# the in-process HubRelay drivers together with the socket workers —
# no instance is ever shared across those roles.

import base64
import http.client
import logging
import threading
from collections.abc import Callable, Iterator
from dataclasses import dataclass
from random import Random
from urllib.parse import urlsplit

__all__ = ["SSEClient", "SSEFrame", "SSEParser"]

logger = logging.getLogger(__name__)


@dataclass(frozen=True, slots=True)
class SSEFrame:
    """One decoded SSE event from the upstream hub."""

    kind: str  #: ``keyframe`` | ``delta`` (the hub's event names)
    blob: bytes  #: the delta-codec blob (serving/delta.py wire)
    boot: str | None  #: hub incarnation from ``id: <boot>:<epoch>:<seq>``
    epoch: int | None
    seq: int | None
    source_ts_ns: int | None  #: from the ``: source_ts_ns=`` comment
    resumed: bool = False  #: first frame after a reconnect (relay.py
    #: uses it to classify hard-vs-soft resyncs)


class SSEParser:
    """Incremental line-fed SSE parser for the hub dialect.

    Feed raw lines (bytes, newline included or not); a completed event
    block (terminated by a blank line) with a ``data:`` field yields an
    :class:`SSEFrame`. Comment-only blocks (keepalives) yield None but
    count as liveness — the client resets its idle clock on EVERY line.
    """

    def __init__(self) -> None:
        self._reset_block()

    def _reset_block(self) -> None:
        self._kind: str | None = None
        self._data: bytes | None = None
        self._id: tuple[str | None, int, int] | None = None
        self._source_ts: int | None = None

    def feed(self, raw: bytes) -> SSEFrame | None:
        line = raw.rstrip(b"\r\n")
        if line == b"":
            frame = self._flush()
            self._reset_block()
            return frame
        if line.startswith(b":"):
            comment = line[1:].strip()
            if comment.startswith(b"source_ts_ns="):
                try:
                    self._source_ts = int(comment.partition(b"=")[2])
                except ValueError:
                    self._source_ts = None
            return None
        field, _, value = line.partition(b":")
        value = value.lstrip(b" ")
        if field == b"event":
            self._kind = value.decode("ascii", "replace")
        elif field == b"data":
            self._data = value
        elif field == b"id":
            parts = value.split(b":")
            try:
                if len(parts) == 3:
                    self._id = (
                        parts[0].decode("ascii"),
                        int(parts[1]),
                        int(parts[2]),
                    )
                elif len(parts) == 2:  # bootless dialect (tests, older)
                    self._id = (None, int(parts[0]), int(parts[1]))
            except (ValueError, UnicodeDecodeError):
                self._id = None
        # ``retry:`` and unknown fields: ignored (the client owns its
        # own backoff policy).
        return None

    def _flush(self) -> SSEFrame | None:
        if self._data is None:
            return None
        try:
            blob = base64.b64decode(self._data, validate=True)
        except Exception:
            logger.warning("undecodable SSE data field (%d bytes)",
                           len(self._data))
            return None
        boot, epoch, seq = (
            self._id if self._id is not None else (None, None, None)
        )
        return SSEFrame(
            kind=self._kind or "message",
            blob=blob,
            boot=boot,
            epoch=epoch,
            seq=seq,
            source_ts_ns=self._source_ts,
        )


class SSEClient:
    """Reconnecting SSE consumer of one upstream stream.

    ``url`` is the stream endpoint, or a zero-arg callable returning it
    — the provider form lets a restarted upstream come back on a new
    address (kill-and-restart tests; DNS does this in production).

    :meth:`frames` is the single public loop: it yields
    :class:`SSEFrame` objects forever, reconnecting through errors with
    the bounded jittered backoff described in the module docstring and
    carrying ``Last-Event-ID`` resume metadata across reconnects. The
    first frame after any reconnect is marked ``resumed=True``.

    ``request_resync()`` drops the held resume position and the current
    connection: the next attach is a clean keyframe subscribe — the
    relay calls it when its decoder hits an unrecoverable gap.
    """

    def __init__(
        self,
        url: str | Callable[[], str],
        *,
        idle_timeout_s: float = 30.0,
        backoff_base_s: float = 0.5,
        backoff_cap_s: float = 10.0,
        seed: int | None = None,
    ) -> None:
        self._url = url if callable(url) else (lambda u=url: u)
        self._idle_timeout_s = float(idle_timeout_s)
        self._backoff_base_s = float(backoff_base_s)
        self._backoff_cap_s = float(backoff_cap_s)
        self._rng = Random(seed)
        self._stop = threading.Event()
        self._conn: http.client.HTTPConnection | None = None
        self._last_event_id: tuple[str, int, int] | None = None
        self._lock = threading.Lock()
        #: Completed (re)connect attempts after the first successful
        #: one — the relay's reconnect counter reads this.
        self.reconnects = 0

    @property
    def last_event_id(self) -> tuple[str, int, int] | None:
        with self._lock:
            return self._last_event_id

    def stop(self) -> None:
        self._stop.set()
        self._close_conn()

    def request_resync(self) -> None:
        """Forget the resume position and force a reconnect — the next
        attach starts from a full keyframe."""
        with self._lock:
            self._last_event_id = None
        self._close_conn()

    def _close_conn(self) -> None:
        with self._lock:
            conn, self._conn = self._conn, None
        if conn is not None:
            try:
                conn.close()
            except OSError:  # pragma: no cover - close is best-effort
                pass

    def _connect(self) -> http.client.HTTPResponse:
        url = self._url()
        parts = urlsplit(url)
        if parts.scheme != "http":
            raise ValueError(f"SSEClient supports http:// only, got {url!r}")
        conn = http.client.HTTPConnection(
            parts.hostname,
            parts.port or 80,
            timeout=self._idle_timeout_s,
        )
        headers = {"Accept": "text/event-stream"}
        with self._lock:
            if self._last_event_id is not None:
                headers["Last-Event-ID"] = "%s:%d:%d" % self._last_event_id
            self._conn = conn
        path = parts.path or "/"
        if parts.query:
            path += "?" + parts.query
        conn.request("GET", path, headers=headers)
        response = conn.getresponse()
        if response.status != 200:
            body = response.read(200)
            conn.close()
            raise ConnectionError(
                f"upstream {url} answered {response.status}: {body!r}"
            )
        return response

    def _backoff(self, attempts: int) -> None:
        """Bounded exponential backoff with seeded jitter; waits on the
        stop event so ``stop()`` interrupts it immediately."""
        delay = min(
            self._backoff_cap_s,
            self._backoff_base_s * (2 ** min(attempts - 1, 16)),
        )
        delay *= 0.5 + self._rng.random()  # jitter: [0.5, 1.5) of base
        self._stop.wait(delay)

    def frames(self) -> Iterator[SSEFrame]:
        attempts = 0
        connected_before = False
        while not self._stop.is_set():
            try:
                response = self._connect()
            except (OSError, ValueError, http.client.HTTPException) as err:
                attempts += 1
                logger.debug("upstream connect failed (%s); backing off", err)
                self._backoff(attempts)
                continue
            resumed = connected_before
            if connected_before:
                self.reconnects += 1
            connected_before = True
            parser = SSEParser()
            try:
                while not self._stop.is_set():
                    line = response.readline()
                    if not line:
                        break  # upstream closed the stream
                    frame = parser.feed(line)
                    if frame is None:
                        continue
                    attempts = 0
                    if (
                        frame.boot is not None
                        and frame.epoch is not None
                        and frame.seq is not None
                    ):
                        with self._lock:
                            self._last_event_id = (
                                frame.boot,
                                frame.epoch,
                                frame.seq,
                            )
                    yield SSEFrame(
                        kind=frame.kind,
                        blob=frame.blob,
                        boot=frame.boot,
                        epoch=frame.epoch,
                        seq=frame.seq,
                        source_ts_ns=frame.source_ts_ns,
                        resumed=resumed,
                    )
                    resumed = False
            except (TimeoutError, OSError, http.client.HTTPException) as err:
                logger.debug("upstream stream dropped (%s)", err)
            finally:
                self._close_conn()
            if self._stop.is_set():
                return
            attempts += 1
            self._backoff(attempts)

"""Relay tree: re-fan an upstream broadcast stream CDN-style (ADR 0121).

One compute-tier process encodes each publish tick ONCE (ADR 0117);
its subscriber capacity is bounded by that one process's sockets and
fan-out loop. A **relay** breaks the wall: it subscribes upstream
exactly like any SSE client, reconstructs every frame with the delta
decoder, and republishes through its OWN embedded
:class:`~..serving.broadcast.BroadcastServer` hub — so subscriber
capacity scales with relay count while the compute tier's work stays
one encode per stream per tick (``bench.py --relay`` gates both ends).
Relays chain: a relay's hub is itself a valid upstream, and every
``/results`` row carries its ``hop`` distance from the compute tier.

Resync discipline (the gap-not-reset contract across a hop):

- A mid-stream delta gap (the relay itself was coalesced upstream, or
  bytes were lost) makes the decoder raise; the relay drops its resume
  position and re-subscribes for a keyframe (``reason="gap"``).
- A reconnect that resumes cleanly — the upstream honored
  ``Last-Event-ID`` and continued with deltas, or re-sent a keyframe in
  the SAME epoch at a seq >= the held one — is a **soft** rebase: the
  downstream token is unchanged, so downstream subscribers keep riding
  deltas (no keyframe at all, the ideal outcome).
- A reconnect keyframe whose epoch differs or whose seq REGRESSED means
  the upstream restarted (fresh hub, epoch numbering reset — durability
  restored the accumulation but not the serving counters): the relay
  bumps its downstream generation, so its hub emits exactly ONE
  epoch-bumped resync keyframe. Downstream sees a signaled rebase whose
  decoded counts CONTINUE (a gap, never a reset) — pinned in
  tests/fleet/relay_resume_test.py.

Frame freshness (ADR 0120): the upstream's ``source_ts_ns`` metadata is
re-attached on the downstream publish, so the e2e freshness histogram
spans the whole tree; ``relay_ingress``/``relay_published`` stages
decompose the hop's cost.

Three faces, one core: :class:`RelayChannel` is the per-stream
transport-independent state machine; :class:`HubRelay` drives it from
an in-process upstream hub (the bench and the SLO drill — synchronous,
deterministic, chaos-injectable via ``relay_upstream_drop``);
:class:`RelayPlane` drives it from a real HTTP upstream via
:class:`.sse_client.SSEClient` (the ``livedata-relay`` service).
"""

from __future__ import annotations

import logging
import threading
import time
import urllib.request
from collections.abc import Callable

from ..serving.broadcast import BroadcastServer, Subscription
from ..serving.delta import DeltaDecoder, DeltaError, decode_header
from ..telemetry.e2e import E2E_BUCKETS, observe_stage
from ..telemetry.registry import REGISTRY, MetricFamily, Sample
from .sse_client import SSEClient

__all__ = ["HubRelay", "RelayChannel", "RelayPlane"]

logger = logging.getLogger(__name__)

#: Frames the relay ingested from upstream, by blob kind.
RELAY_FRAMES = REGISTRY.counter(
    "livedata_relay_frames",
    "Frames a relay ingested from its upstream, by blob kind",
    labelnames=("kind",),
)
#: Resyncs by class: ``reconnect`` = hard (upstream restart, downstream
#: generation bump -> one keyframe), ``rebase`` = soft (same-epoch
#: keyframe after reconnect, downstream continuity preserved), ``gap`` =
#: mid-stream decoder gap (re-subscribe for a keyframe).
RELAY_RESYNCS = REGISTRY.counter(
    "livedata_relay_resyncs",
    "Relay resynchronizations against upstream, by class",
    labelnames=("reason",),
)
RELAY_RECONNECTS = REGISTRY.counter(
    "livedata_relay_reconnects",
    "Upstream connections the relay re-established after a drop",
)
#: Wall-clock age of upstream frames at relay ingress — how far behind
#: the compute tier this hop runs (the headline relay-health signal).
RELAY_UPSTREAM_LAG = REGISTRY.histogram(
    "livedata_relay_upstream_lag_seconds",
    "Freshness (wall minus source timestamp) of upstream frames at "
    "relay ingress (ADR 0121)",
    buckets=E2E_BUCKETS,
)


class RelayChannel:
    """Per-stream relay state: upstream decoder -> downstream publish.

    Transport-independent: callers hand it blobs (plus the frame's
    source timestamp and whether a reconnect preceded it) and it owns
    the resync classification described in the module docstring. The
    downstream epoch token is ``(generation, upstream epoch)``: an
    upstream IN-STREAM epoch bump (signaled reset/layout swap)
    propagates as-is, and a ``generation`` bump marks an upstream
    RESTART whose epoch numbering can no longer be compared.
    """

    __slots__ = (
        "stream",
        "hub",
        "_decoder",
        "_generation",
        "_last_boot",
        "_last_epoch",
        "_last_seq",
        "_observe_ingress",
        "frames_relayed",
    )

    def __init__(
        self,
        stream: str,
        hub: BroadcastServer,
        *,
        observe_ingress: bool = True,
    ) -> None:
        self.stream = stream
        self.hub = hub
        self._decoder = DeltaDecoder()
        self._generation = 0
        self._last_boot: str | None = None
        self._last_epoch: int | None = None
        self._last_seq: int | None = None
        #: False when the transport already observed the
        #: ``relay_ingress`` boundary (a HubRelay's upstream
        #: Subscription dequeues with that stage) — the channel must
        #: not fold the same crossing in twice.
        self._observe_ingress = observe_ingress
        self.frames_relayed = 0

    @property
    def generation(self) -> int:
        return self._generation

    # graft: protocol=relay (ADR 0124: the boot/epoch/seq classification
    # below is the modeled resync protocol over <boot>:<epoch>:<seq>)
    def on_blob(
        self,
        blob: bytes,
        source_ts_ns: int | None,
        *,
        after_reconnect: bool = False,
        boot: str | None = None,
    ) -> bool:
        """Ingest one upstream blob; republish the reconstructed frame
        downstream. Returns False when the channel hit an unrecoverable
        gap — the caller must re-subscribe for a keyframe (with the
        resume position dropped). ``boot`` is the upstream hub's
        incarnation id (SSE ``id:`` prefix) when the transport carries
        one: a changed boot across a reconnect IS an upstream restart,
        however plausible the epoch/seq numbers look."""
        header = decode_header(blob)
        if self._observe_ingress:
            observe_stage("relay_ingress", source_ts_ns)
        if source_ts_ns is not None:
            RELAY_UPSTREAM_LAG.observe(
                max(0.0, (time.time_ns() - source_ts_ns) / 1e9)
            )
        restarted = (
            boot is not None
            and self._last_boot is not None
            and boot != self._last_boot
        )
        if after_reconnect and header.keyframe and (
            restarted
            or (
                self._last_epoch is not None
                and (
                    header.epoch != self._last_epoch
                    or header.seq < (self._last_seq or 0)
                )
            )
        ):
            # Hard resync: the upstream restarted (boot changed, or its
            # epoch/seq numbering regressed). Its state may well
            # CONTINUE the old accumulation (durability restore), but
            # the wire cannot prove it — a fresh process could equally
            # have come back EMPTY with numbering that happens to look
            # contiguous — so downstream gets one signaled keyframe.
            # A channel is single-owner: one worker thread (RelayPlane)
            # or one driver (HubRelay) each.
            # graftlint: disable=JGL004 - single-owner channel instance
            self._generation += 1
            self._decoder = DeltaDecoder()
            RELAY_RESYNCS.labels(reason="reconnect").inc()
        elif after_reconnect and header.keyframe:
            RELAY_RESYNCS.labels(reason="rebase").inc()
        stale = (
            not header.keyframe
            and header.epoch == self._last_epoch
            and self._last_seq is not None
            and header.seq <= self._last_seq
        )
        try:
            frame = self._decoder.apply(blob)
        except DeltaError:
            if header.keyframe:
                # A keyframe always rebases cleanly on a fresh decoder.
                self._decoder = DeltaDecoder()
                frame = self._decoder.apply(blob)
                RELAY_RESYNCS.labels(reason="rebase").inc()
            else:
                RELAY_RESYNCS.labels(reason="gap").inc()
                return False
        if boot is not None:
            self._last_boot = boot
        self._last_epoch, self._last_seq = header.epoch, header.seq
        if stale:
            # Attach-race duplicate (already covered by a keyframe):
            # decoded to the held frame; republishing would burn a
            # downstream encode for an unchanged tick.
            return True
        RELAY_FRAMES.labels(
            kind="keyframe" if header.keyframe else "delta"
        ).inc()
        self.hub.publish_frame(
            self.stream,
            frame,
            token=("relay", self._generation, header.epoch),
            source_ts_ns=source_ts_ns,
        )
        observe_stage("relay_published", source_ts_ns)
        self.frames_relayed += 1
        return True


class HubRelay:
    """In-process relay hop over hub APIs (bench + SLO drill).

    Subscribes to the upstream hub through the same
    :meth:`BroadcastServer.subscribe` the SSE handler uses (with the
    ``relay_ingress`` e2e stage) and republishes through its own hub.
    Driven synchronously: callers :meth:`pump` after each upstream
    publish tick — determinism is the point (harness/load.py), and the
    socket transport has its own :class:`RelayPlane` + tests.

    Chaos: a fired ``relay_upstream_drop`` (harness/chaos.py) drops
    every upstream subscription; the next pump re-subscribes, which
    lands fresh attach keyframes and exercises the resync
    classification exactly as a socket drop would.
    """

    def __init__(
        self,
        upstream: BroadcastServer,
        *,
        name: str = "relay",
        queue_limit: int = 32,
        hub: BroadcastServer | None = None,
        chaos=None,
    ) -> None:
        self.upstream = upstream
        self.hub = (
            hub
            if hub is not None
            else BroadcastServer(
                port=None,
                name=name,
                queue_limit=queue_limit,
                hop=upstream.hop + 1,
            )
        )
        self._chaos = chaos
        self._subs: dict[str, Subscription] = {}
        self._channels: dict[str, RelayChannel] = {}
        self._pending_reconnect: set[str] = set()

    def set_chaos(self, chaos) -> None:
        """Install the fault schedule post-warm-up (the harness rule:
        explicit ``at`` ticks count steady consultations, and the warm
        phase pumps too)."""
        self._chaos = chaos

    def attach(self) -> int:
        """Subscribe to upstream streams not yet relayed; returns how
        many were added. Called from every pump, so streams that appear
        upstream mid-run (new jobs) are picked up."""
        added = 0
        for stream in self.upstream.cache.streams():
            if stream in self._subs:
                continue
            self._subs[stream] = self.upstream.subscribe(
                stream, stage="relay_ingress"
            )
            self._channels.setdefault(
                stream,
                # The Subscription's dequeue observes relay_ingress;
                # the channel must not double-count the boundary.
                RelayChannel(stream, self.hub, observe_ingress=False),
            )
            added += 1
        return added

    def _drop_upstream(self) -> None:
        """The ``relay_upstream_drop`` chaos fault: every upstream
        subscription dies; channels keep their decoder state (the relay
        process did not restart) and the next pump re-attaches."""
        for sub in self._subs.values():
            self.upstream.unsubscribe(sub)
        self._pending_reconnect.update(self._subs)
        self._subs.clear()
        RELAY_RECONNECTS.inc()

    def pump(self, timeout: float = 1.0) -> int:
        """Drain every upstream subscription into the downstream hub;
        returns frames relayed. Synchronous-driver contract: upstream
        publishes already happened, so ``depth`` is exact."""
        if self._chaos is not None and self._chaos.fires(
            "relay_upstream_drop"
        ):
            self._drop_upstream()
        self.attach()
        relayed = 0
        for stream, sub in list(self._subs.items()):
            channel = self._channels[stream]
            while sub.depth() > 0:
                blob, ts = sub.next_blob_meta(timeout=timeout)
                if blob is None:  # pragma: no cover - depth>0 guarantees
                    break
                ok = channel.on_blob(
                    blob,
                    ts,
                    after_reconnect=stream in self._pending_reconnect,
                    boot=self.upstream.boot,
                )
                self._pending_reconnect.discard(stream)
                if not ok:
                    # Unrecoverable gap: re-subscribe for a keyframe.
                    self.upstream.unsubscribe(sub)
                    self._subs[stream] = self.upstream.subscribe(
                        stream, stage="relay_ingress"
                    )
                    self._pending_reconnect.add(stream)
                    sub = self._subs[stream]
                    continue
                relayed += 1
        return relayed

    def close(self) -> None:
        for sub in self._subs.values():
            self.upstream.unsubscribe(sub)
        self._subs.clear()
        self.hub.close()


class RelayPlane:
    """The ``livedata-relay`` service core: HTTP upstream -> local hub.

    A discovery thread polls the upstream ``/results`` index; each
    discovered stream gets a worker thread running an
    :class:`.sse_client.SSEClient` loop into a :class:`RelayChannel`.
    The local hub's ``/results`` federates: streams not yet relayed are
    listed with a ``url`` pointing at the upstream hop
    (fleet/control.py), so a client landing here mid-warm-up is routed
    rather than 404ed.

    ``upstream`` is a base URL (``http://host:port``) or a zero-arg
    callable returning one (restart/failover tests).
    """

    def __init__(
        self,
        upstream: str | Callable[[], str],
        hub: BroadcastServer,
        *,
        poll_interval_s: float = 2.0,
        idle_timeout_s: float = 30.0,
        name: str = "relay",
        seed: int | None = None,
    ) -> None:
        self._upstream = (
            upstream if callable(upstream) else (lambda u=upstream: u)
        )
        self.hub = hub
        self._poll_interval_s = float(poll_interval_s)
        self._idle_timeout_s = float(idle_timeout_s)
        self._name = name
        self._seed = seed
        self._stop = threading.Event()
        self._lock = threading.Lock()
        self._clients: dict[str, SSEClientWorker] = {}
        self._upstream_rows: list[dict] = []
        self._collector_key = f"fleet:relay:{name}"
        REGISTRY.register_collector(self._collector_key, self._telemetry)
        self.hub.set_index_peers(self._peer_rows)
        self._discovery = threading.Thread(
            target=self._discover_loop,
            name=f"relay-discovery-{name}",
            daemon=True,
        )
        self._discovery.start()

    # -- discovery ----------------------------------------------------------
    def upstream_url(self) -> str:
        return self._upstream().rstrip("/")

    def _fetch_index(self) -> list[dict]:
        import json

        with urllib.request.urlopen(
            f"{self.upstream_url()}/results", timeout=5.0
        ) as response:
            return json.loads(response.read()).get("streams", [])

    def _discover_loop(self) -> None:  # graft: thread=relay-discovery
        while not self._stop.is_set():
            try:
                rows = self._fetch_index()
            except Exception as err:
                logger.debug("upstream index poll failed: %s", err)
                self._stop.wait(self._poll_interval_s)
                continue
            with self._lock:
                self._upstream_rows = rows
                known = set(self._clients)
            max_hop = max((row.get("hop", 0) for row in rows), default=0)
            self.hub.hop = max_hop + 1
            for row in rows:
                stream = row.get("stream")
                if not stream or stream in known:
                    continue
                self._start_worker(stream)
            self._stop.wait(self._poll_interval_s)

    def _start_worker(self, stream: str) -> None:
        worker = SSEClientWorker(
            stream,
            self,
            idle_timeout_s=self._idle_timeout_s,
            seed=self._seed,
        )
        with self._lock:
            if stream in self._clients:  # pragma: no cover - races only
                return
            self._clients[stream] = worker
        worker.start()

    # -- federation ---------------------------------------------------------
    def _peer_rows(self) -> list[dict]:
        """Upstream index rows for streams this relay has not cached
        yet — the federated ``/results`` points clients at the right
        hop instead of 404ing during warm-up."""
        base = self.upstream_url()
        with self._lock:
            rows = list(self._upstream_rows)
        out = []
        for row in rows:
            merged = dict(row)
            merged["url"] = base + merged.get("path", "")
            out.append(merged)
        return out

    # -- telemetry ----------------------------------------------------------
    def _telemetry(self) -> list[MetricFamily]:
        streams_fam = MetricFamily(
            "livedata_relay_streams",
            "gauge",
            "Streams this relay is actively relaying from upstream",
        )
        hop_fam = MetricFamily(
            "livedata_relay_hop",
            "gauge",
            "This relay's distance from the compute tier in hops",
        )
        base = (("relay", self._name),)
        with self._lock:
            n = len(self._clients)
        streams_fam.samples.append(Sample("", base, n))
        hop_fam.samples.append(Sample("", base, self.hub.hop))
        return [streams_fam, hop_fam]

    def close(self) -> None:
        self._stop.set()
        with self._lock:
            workers = list(self._clients.values())
            self._clients.clear()
        for worker in workers:
            worker.stop()
        self._discovery.join(timeout=5.0)
        for worker in workers:
            worker.join(timeout=5.0)
        REGISTRY.unregister_collector(self._collector_key, self._telemetry)

    @property
    def stopped(self) -> bool:
        return self._stop.is_set()


class SSEClientWorker(threading.Thread):
    """One stream's SSE consume loop (RelayPlane worker)."""

    def __init__(
        self,
        stream: str,
        plane: RelayPlane,
        *,
        idle_timeout_s: float,
        seed: int | None,
    ) -> None:
        super().__init__(name=f"relay-{stream}", daemon=True)
        self.stream = stream
        self._plane = plane
        self.channel = RelayChannel(stream, plane.hub)
        self.client = SSEClient(
            lambda: f"{plane.upstream_url()}/streams/{stream}",
            idle_timeout_s=idle_timeout_s,
            seed=seed,
        )

    def run(self) -> None:  # graft: thread=relay-stream
        reconnects_seen = 0
        for frame in self.client.frames():
            if self._plane.stopped:
                break
            if self.client.reconnects > reconnects_seen:
                RELAY_RECONNECTS.inc(
                    self.client.reconnects - reconnects_seen
                )
                reconnects_seen = self.client.reconnects
            ok = self.channel.on_blob(
                frame.blob,
                frame.source_ts_ns,
                after_reconnect=frame.resumed,
                boot=frame.boot,
            )
            if not ok:
                # Unrecoverable gap: clean keyframe re-subscribe.
                self.client.request_resync()

    def stop(self) -> None:
        self.client.stop()

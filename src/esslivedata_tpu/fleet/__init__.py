"""Fleet plane: horizontal scale-out past one process (ADR 0121).

Three coupled pieces, one goal — serve "millions of users" from a
topology of small processes instead of one big one:

- **Relay tree** (:mod:`.relay`, :mod:`.sse_client`): chainable
  fan-out hops. A relay consumes an upstream broadcast stream exactly
  like any SSE client (resumable keyframe-then-delta wire, ADR 0117)
  and re-fans through its own hub, so subscriber capacity scales with
  relay count while the compute tier encodes once per tick.
  ``livedata-relay`` (:mod:`.service`) is the container entry point.
- **Replica partitioning** (:mod:`.assignment`): deterministic
  rendezvous-hashed ``(stream, fuse-key) -> replica`` assignment —
  ADR 0115's sticky placement generalized from mesh slices to service
  replicas, membership-driven, with checkpoint/bookmark replay
  (ADR 0118) turning reassignment into a gap, not a reset.
- **Control plane** (:mod:`.control`): ``/results`` federation across
  replicas and relays, and job-commit -> owning-replica routing.
"""

from .assignment import FleetAssignment, rendezvous_owner
from .control import CommitRouter, fetch_index, peer_index
from .relay import HubRelay, RelayChannel, RelayPlane
from .sse_client import SSEClient, SSEFrame, SSEParser

__all__ = [
    "CommitRouter",
    "FleetAssignment",
    "HubRelay",
    "RelayChannel",
    "RelayPlane",
    "SSEClient",
    "SSEFrame",
    "SSEParser",
    "fetch_index",
    "peer_index",
    "rendezvous_owner",
]

"""Fleet control plane: federated results index + commit routing.

Two small, deliberately stateless pieces (ADR 0121):

**Federated ``/results``** — every node already serves its local index
(serving/broadcast.py); federation is a peer hook
(``BroadcastServer.set_index_peers``) returning EXTRA rows for streams
served elsewhere, each with a ``url`` pointing at the right hop:

- a **replica** lists its fleet peers' streams (jobs partitioned by the
  rendezvous assignment live on exactly one replica each), so a client
  asking any replica finds every stream and is pointed at its owner;
- a **relay** lists upstream streams it has not cached yet
  (:meth:`~.relay.RelayPlane._peer_rows`), so a client landing mid
  warm-up is routed upstream instead of 404ed.

Peer outages degrade the index to the reachable subset — federation
must never make a healthy node's own streams unlistable.

**Commit routing** — a job commit belongs on the replica that owns the
job's source stream. :class:`CommitRouter` answers ``owner``/
``owner_url`` from the same :class:`~.assignment.FleetAssignment` the
window path uses, so the control plane and the data plane can never
disagree about ownership. In the Kafka deployment the command topic is
broadcast and every replica sees every commit; each replica starts the
job (cheap: a scheduled job with no owned data never processes a
window) but only the owner accumulates — the router exists for
operators and HTTP surfaces that want to talk to the owner directly
(job status, checkpoint inspection, targeted drain).
"""

from __future__ import annotations

import json
import logging
import urllib.request
from collections.abc import Callable, Mapping

from .assignment import FleetAssignment

__all__ = ["CommitRouter", "fetch_index", "peer_index"]

logger = logging.getLogger(__name__)


def fetch_index(base_url: str, *, timeout: float = 5.0) -> list[dict]:
    """One node's ``/results`` rows (raises on unreachable/malformed —
    callers own the degrade policy)."""
    with urllib.request.urlopen(
        f"{base_url.rstrip('/')}/results", timeout=timeout
    ) as response:
        payload = json.loads(response.read())
    rows = payload.get("streams")
    if not isinstance(rows, list):
        raise ValueError(f"{base_url}/results carried no stream list")
    return rows


def peer_index(
    peers: Mapping[str, str], *, timeout: float = 5.0
) -> Callable[[], list[dict]]:
    """A ``BroadcastServer.set_index_peers`` hook federating the given
    ``{node name: base url}`` peers. Each returned row gains ``node``
    (who serves it) and ``url`` (the absolute SSE endpoint at that
    node). An unreachable peer contributes nothing this scrape — and a
    warning, once per outage transition, not per poll."""
    down: set[str] = set()

    def rows() -> list[dict]:
        out: list[dict] = []
        for name, base in peers.items():
            try:
                peer_rows = fetch_index(base, timeout=timeout)
            except Exception as err:
                if name not in down:
                    logger.warning(
                        "fleet peer %s (%s) unreachable: %s", name, base, err
                    )
                    down.add(name)
                continue
            down.discard(name)
            for row in peer_rows:
                merged = dict(row)
                merged.setdefault("node", name)
                merged["url"] = base.rstrip("/") + merged.get("path", "")
                out.append(merged)
        return out

    return rows


class CommitRouter:
    """Job-commit -> owning-replica lookup over the fleet assignment.

    ``replica_urls`` maps replica ids (the assignment's members) to
    their base URLs; ids without a URL still resolve by name (the
    Kafka-broadcast deployment needs no address to route correctness,
    only the data-plane filter).
    """

    def __init__(
        self,
        assignment: FleetAssignment,
        replica_urls: Mapping[str, str] | None = None,
    ) -> None:
        self.assignment = assignment
        self.replica_urls = dict(replica_urls or {})

    def owner(self, source_name: str, fuse_tag=None) -> str:
        """The replica that owns ``source_name``'s groups — where a
        commit for that source actually accumulates."""
        return self.assignment.owner(source_name, fuse_tag)

    def owner_url(self, source_name: str, fuse_tag=None) -> str | None:
        return self.replica_urls.get(self.owner(source_name, fuse_tag))

    def route(self, config) -> tuple[str, str | None]:
        """(owner replica, owner base url) for a WorkflowConfig-shaped
        commit (anything with ``job_id.source_name``)."""
        source = config.job_id.source_name
        owner = self.owner(source)
        return owner, self.replica_urls.get(owner)

"""Result fan-out tier: publish once per tick, serve N dashboards.

The subsystem that decouples viewers from the reduction stream
(ROADMAP open item 3, ADR 0117). Four pieces:

- :mod:`.result_cache` — host-side latest-frame + recent-ring cache per
  (job, output), fed at finalize time; subscriber attach/resync never
  touches the compute loop;
- :mod:`.delta` — exact byte-run delta codec (keyframe + sparse deltas,
  dense fallback, epoch-tagged) with byte-identical reconstruction of
  the da00 wire;
- :mod:`.broadcast` — SSE broadcast server with per-subscriber bounded
  queues and coalesce-on-overflow, plus the ``/results`` index and the
  ``livedata_serving_*`` telemetry families;
- :mod:`.plane` — the ``ServingPlane`` processor hook wiring the above
  into the service runners (``--serve-port``/``LIVEDATA_SERVE_PORT``).

See docs/serving.md for endpoints, the delta wire format and the
QoS/coalescing semantics.
"""

from .broadcast import BroadcastServer, Subscription, stream_key
from .delta import (
    DeltaDecoder,
    DeltaEncoder,
    DeltaError,
    decode_header,
    encode_delta,
    encode_keyframe,
)
from .plane import ServingPlane, get_or_create_plane
from .result_cache import CachedFrame, ResultCache

__all__ = [
    "BroadcastServer",
    "CachedFrame",
    "DeltaDecoder",
    "DeltaEncoder",
    "DeltaError",
    "ResultCache",
    "ServingPlane",
    "Subscription",
    "decode_header",
    "encode_delta",
    "encode_keyframe",
    "get_or_create_plane",
    "stream_key",
]

"""Host-side result cache: the broadcast tier's source of truth.

One entry per (job, output) stream holds the latest published da00
frame plus a bounded ring of recent ticks — the ADR 0113 static-output
host cache generalized from "layout-constant leaves, stored once per
digest" to "every output, stored once per publish tick". Subscribers
never touch the compute loop: an attach (or a slow consumer's resync)
is served a keyframe from here, so N dashboards cost the publish path
exactly zero extra device work (ROADMAP open item 3).

Epoch discipline: ``put`` takes an opaque ``token`` describing the
frame's generation — the serving plane builds it from the output's
structural layout (variable names/shapes/dtypes/axes) and the job's
``state_epoch`` (core/job.py: bumped on clear/reset and on a
``state_lost`` buffer-donation failure). A token change bumps the
stream's integer epoch, which forces the delta encoder onto a keyframe
and tells subscribers the accumulation restarted (a delta across
epochs would splice unrelated state generations).

Locking: ONE lock, ONE acquisition per operation — the discipline PR 9
gave ``LinkMonitor.stats()``. ``latest`` returns frame, epoch and seq
from the same critical section, so a scraping subscriber can never pair
a frame with the wrong epoch tag (pinned by the lock hammer in
tests/serving/result_cache_test.py); ``put`` is a dict store + deque
append under that lock — O(1), no encoding, nothing that could extend
the publish critical path.
"""

from __future__ import annotations

import threading
from collections import deque
from collections.abc import Hashable
from dataclasses import dataclass

__all__ = ["CachedFrame", "ResultCache"]


@dataclass(frozen=True, slots=True)
class CachedFrame:
    """One coherent (frame, epoch, seq) snapshot."""

    frame: bytes
    epoch: int
    seq: int


class _Entry:
    __slots__ = ("token", "epoch", "seq", "ring")

    def __init__(self, ring: int) -> None:
        self.token: Hashable = None
        self.epoch = -1
        self.seq = -1
        self.ring: deque[CachedFrame] = deque(maxlen=ring)


class ResultCache:
    """Latest frame + bounded recent ring per (job, output) stream."""

    def __init__(self, *, ring: int = 8) -> None:
        if ring < 1:
            raise ValueError("ring must hold at least the latest frame")
        self._ring = int(ring)
        self._lock = threading.Lock()
        self._entries: dict[str, _Entry] = {}

    def put(
        self, stream: str, frame: bytes, token: Hashable
    ) -> CachedFrame:
        """Record one published frame; returns its coherent
        (frame, epoch, seq) tag. A ``token`` differing from the
        previous put's bumps the epoch (and the ring resets — frames
        across a generation boundary must not look contiguous)."""
        with self._lock:
            entry = self._entries.get(stream)
            if entry is None:
                entry = self._entries[stream] = _Entry(self._ring)
            if entry.epoch < 0 or entry.token != token:
                entry.epoch += 1
                entry.token = token
                entry.ring.clear()
            entry.seq += 1
            cached = CachedFrame(frame, entry.epoch, entry.seq)
            entry.ring.append(cached)
            return cached

    def latest(self, stream: str) -> CachedFrame | None:
        """The newest frame with ITS epoch and seq — one acquisition,
        so the triple is always self-consistent."""
        with self._lock:
            entry = self._entries.get(stream)
            if entry is None or not entry.ring:
                return None
            return entry.ring[-1]

    def recent(self, stream: str) -> list[CachedFrame]:
        """The bounded ring, oldest first (current epoch only — the
        ring resets on epoch bumps)."""
        with self._lock:
            entry = self._entries.get(stream)
            return [] if entry is None else list(entry.ring)

    def streams(self) -> dict[str, CachedFrame]:
        """stream -> latest snapshot, for the /results index."""
        with self._lock:
            return {
                stream: entry.ring[-1]
                for stream, entry in self._entries.items()
                if entry.ring
            }

    def invalidate(self, stream: str | None = None) -> None:
        """Drop one stream's entry (or all) — a removed job's outputs
        must not serve stale keyframes forever."""
        with self._lock:
            if stream is None:
                self._entries.clear()
            else:
                self._entries.pop(stream, None)

"""Delta codec for the result fan-out tier (ADR 0117).

Rolling histograms change sparsely between publish ticks: a window's
events touch a few hundred bins of a multi-hundred-kB cumulative frame,
and the rest of the da00 wire (coords, axes, masks, the flatbuffer
scaffolding) is byte-identical from tick to tick. This module encodes
that sparsity: a **delta blob** carries only the byte runs that changed
against the previous frame, and a subscriber applying it to its copy of
the previous frame reconstructs the new da00 frame **byte-identically**
— the wire a Kafka consumer of the same publish would have seen
(pinned in tests/serving/delta_codec_test.py and the fan-out
integration suite).

Diffing at the byte level (not per-variable) is deliberate: it makes
exact round-trip a structural property instead of a per-schema promise
— timestamps, end_time coords and normalization denominators that
change every tick ride the same run encoding as the histogram bins, and
a frame whose da00 *layout* changed (projection swap, new output shape)
simply fails the equal-length precondition and degrades to a keyframe.

Blob wire format (version 1, little-endian; see docs/serving.md):

====== ====== ==========================================================
offset size   field
====== ====== ==========================================================
0      2      magic ``LD``
2      1      version (1)
3      1      flags — bit 0: keyframe
4      4      epoch (u32): bumped by the ResultCache on a layout-digest
              swap or a ``state_lost``/reset generation change; a delta
              never applies across epochs
8      4      seq (u32): per-stream publish tick counter
12     4      frame length (u32)
16     ...    keyframe: the full frame. delta: u32 run count, then per
              run u32 offset, u32 length, ``length`` raw bytes
====== ====== ==========================================================

**Dense fallback**: when the encoded runs would meet or exceed the full
frame size (first frames after a counts reset, a dense current-window
output, random noise), the encoder emits a keyframe instead — a delta
blob is never larger than the keyframe for the same tick.

Codec state is intentionally asymmetric:

- :class:`DeltaEncoder` is single-writer (the service's publish hook;
  one per stream) and encodes ONCE per tick no matter how many
  subscribers are attached — that is the fan-out saving.
- :class:`DeltaDecoder` is per-subscriber: keyframes (re)base it at any
  time, stale deltas (seq <= current, same epoch — a race between
  subscriber attach and an in-flight fan-out) are idempotent no-ops,
  and a gap or epoch mismatch raises :class:`DeltaError` so a consumer
  resyncs with a keyframe instead of silently diverging.
"""

from __future__ import annotations

import struct
from dataclasses import dataclass

import numpy as np

__all__ = [
    "DeltaDecoder",
    "DeltaEncoder",
    "DeltaError",
    "DeltaHeader",
    "FLAG_KEYFRAME",
    "HEADER_SIZE",
    "decode_header",
    "encode_delta",
    "encode_keyframe",
]

_MAGIC = b"LD"
_VERSION = 1
FLAG_KEYFRAME = 0x01

_HEADER = struct.Struct("<2sBBIII")
HEADER_SIZE = _HEADER.size  # 16

#: Two changed bytes closer than this are cheaper as one run than as
#: two (a run costs 8 bytes of offset+length framing).
_RUN_MERGE_GAP = 8


class DeltaError(ValueError):
    """Malformed blob, or a delta that cannot apply to the held base."""


@dataclass(frozen=True, slots=True)
class DeltaHeader:
    keyframe: bool
    epoch: int
    seq: int
    frame_len: int


def decode_header(blob: bytes) -> DeltaHeader:
    if len(blob) < HEADER_SIZE:
        raise DeltaError(f"blob too short for header: {len(blob)} bytes")
    magic, version, flags, epoch, seq, frame_len = _HEADER.unpack_from(blob)
    if magic != _MAGIC:
        raise DeltaError(f"bad magic {magic!r}")
    if version != _VERSION:
        raise DeltaError(f"unsupported delta version {version}")
    return DeltaHeader(
        keyframe=bool(flags & FLAG_KEYFRAME),
        epoch=epoch,
        seq=seq,
        frame_len=frame_len,
    )


def encode_keyframe(frame: bytes, *, epoch: int, seq: int) -> bytes:
    """The full frame, self-contained — what a fresh (or overflowed)
    subscriber receives to (re)base its decoder."""
    return (
        _HEADER.pack(_MAGIC, _VERSION, FLAG_KEYFRAME, epoch, seq, len(frame))
        + frame
    )


def _changed_runs(prev: bytes, cur: bytes) -> list[tuple[int, int]]:
    """(offset, length) byte runs where ``cur`` differs from ``prev``
    (equal lengths required), nearby runs merged so framing overhead
    never dominates genuinely sparse change."""
    a = np.frombuffer(prev, dtype=np.uint8)
    b = np.frombuffer(cur, dtype=np.uint8)
    idx = np.flatnonzero(a != b)
    if idx.size == 0:
        return []
    breaks = np.flatnonzero(np.diff(idx) > _RUN_MERGE_GAP)
    starts = idx[np.concatenate(([0], breaks + 1))]
    ends = idx[np.concatenate((breaks, [idx.size - 1]))] + 1
    return list(zip(starts.tolist(), (ends - starts).tolist()))


def encode_delta(
    prev: bytes, cur: bytes, *, epoch: int, seq: int
) -> bytes:
    """Delta blob of ``cur`` against ``prev`` — or a keyframe when the
    lengths differ or the runs would not undercut the full frame (dense
    fallback). The caller does not need to know which: the blob header
    says, and :class:`DeltaDecoder` handles both."""
    if len(prev) != len(cur):
        return encode_keyframe(cur, epoch=epoch, seq=seq)
    runs = _changed_runs(prev, cur)
    payload = sum(length for _, length in runs)
    if 4 + 8 * len(runs) + payload >= len(cur):
        return encode_keyframe(cur, epoch=epoch, seq=seq)
    parts = [
        _HEADER.pack(_MAGIC, _VERSION, 0, epoch, seq, len(cur)),
        struct.pack("<I", len(runs)),
    ]
    for offset, length in runs:
        parts.append(struct.pack("<II", offset, length))
        parts.append(cur[offset : offset + length])
    return b"".join(parts)


class DeltaEncoder:
    """Per-stream encoder: previous frame + epoch, keyframe-on-change.

    Single-writer by contract — the broadcast hub calls it from the one
    publish hook (the service's step worker); it holds no lock of its
    own. ``encode`` returns the blob every *attached* subscriber gets
    (one encode per tick, shared), ``keyframe`` re-emits the current
    state for a subscriber that attached late or overflowed.
    """

    __slots__ = ("_prev", "_epoch", "_seq")

    def __init__(self) -> None:
        self._prev: bytes | None = None
        self._epoch: int | None = None
        self._seq: int | None = None

    @property
    def seq(self) -> int | None:
        return self._seq

    # graft: protocol=epoch (ADR 0124: the epoch-change keyframe branch
    # below is the serving half of the modeled epoch discipline)
    def encode(self, frame: bytes, *, epoch: int, seq: int) -> bytes:
        """The blob for this tick: a delta against the previous frame,
        or a keyframe on the first frame, an epoch change (layout swap /
        ``state_lost`` — a delta across state generations would splice
        unrelated accumulations), or the dense fallback."""
        prev, prev_epoch = self._prev, self._epoch
        # Single-writer by contract (class docstring): each stream's
        # encoder is called by exactly one publish hook; relay workers
        # publish disjoint streams.
        # graftlint: disable=JGL012 - single-writer encoder contract
        self._prev, self._epoch, self._seq = frame, epoch, seq
        if prev is None or prev_epoch != epoch:
            return encode_keyframe(frame, epoch=epoch, seq=seq)
        return encode_delta(prev, frame, epoch=epoch, seq=seq)

    def keyframe(self) -> bytes | None:
        """A keyframe of the current state (same epoch/seq as the last
        ``encode``), or None before the first frame."""
        if self._prev is None:
            return None
        return encode_keyframe(
            self._prev, epoch=self._epoch, seq=self._seq
        )


class DeltaDecoder:
    """Per-subscriber reconstruction: keyframes rebase, deltas patch.

    ``apply`` returns the full reconstructed frame — byte-identical to
    the publisher's da00 wire for that tick. Stale deltas (seq <= the
    held seq in the same epoch) return the held frame unchanged: the
    attach flow enqueues a keyframe from the cache and an in-flight
    fan-out may race one already-covered delta behind it. Anything the
    decoder cannot prove applies (epoch mismatch, a seq gap, a length
    mismatch) raises :class:`DeltaError` — the consumer's cue to
    resubscribe for a keyframe, never to guess.
    """

    __slots__ = ("_frame", "_epoch", "_seq")

    def __init__(self) -> None:
        self._frame: bytearray | None = None
        self._epoch: int | None = None
        self._seq: int | None = None

    @property
    def epoch(self) -> int | None:
        return self._epoch

    @property
    def seq(self) -> int | None:
        return self._seq

    def frame(self) -> bytes | None:
        return None if self._frame is None else bytes(self._frame)

    def apply(self, blob: bytes) -> bytes:
        header = decode_header(blob)
        body = blob[HEADER_SIZE:]
        if header.keyframe:
            if len(body) != header.frame_len:
                raise DeltaError(
                    f"keyframe length {len(body)} != header "
                    f"{header.frame_len}"
                )
            self._frame = bytearray(body)
            self._epoch = header.epoch
            self._seq = header.seq
            return bytes(self._frame)
        if self._frame is None:
            raise DeltaError("delta before any keyframe")
        if header.epoch != self._epoch:
            raise DeltaError(
                f"delta epoch {header.epoch} != held epoch {self._epoch}"
            )
        if header.seq <= self._seq:
            # Attach race: the cache keyframe already covers this tick.
            return bytes(self._frame)
        if header.seq != self._seq + 1:
            raise DeltaError(
                f"delta seq {header.seq} after {self._seq}: gap "
                "(coalesced away?) — resync with a keyframe"
            )
        if header.frame_len != len(self._frame):
            raise DeltaError(
                f"delta frame length {header.frame_len} != held "
                f"{len(self._frame)}"
            )
        if len(body) < 4:
            raise DeltaError("delta body too short for run count")
        (n_runs,) = struct.unpack_from("<I", body, 0)
        pos = 4
        frame = self._frame
        for _ in range(n_runs):
            if pos + 8 > len(body):
                raise DeltaError("delta run header extends past blob")
            offset, length = struct.unpack_from("<II", body, pos)
            pos += 8
            if pos + length > len(body):
                raise DeltaError("delta run data extends past blob")
            if offset + length > len(frame):
                raise DeltaError(
                    f"delta run [{offset}:{offset + length}] outside "
                    f"frame of {len(frame)} bytes"
                )
            frame[offset : offset + length] = body[pos : pos + length]
            pos += length
        if pos != len(body):
            raise DeltaError(
                f"{len(body) - pos} trailing bytes after delta runs"
            )
        self._seq = header.seq
        return bytes(frame)

"""Broadcast plane: one publish in, N subscribers out (ADR 0117).

The hub decouples every viewer from the compute loop. The service's
publish hook calls :meth:`BroadcastServer.publish_frame` once per
(job, output) per publish tick; the hub stores the frame in the
:class:`~.result_cache.ResultCache`, delta-encodes it ONCE against the
previous tick (serving/delta.py), and enqueues the resulting blob onto
every attached subscriber's bounded queue. Per-subscriber cost is one
``put_nowait`` — no encoding, no device work, no serialization — so
publish-side work is flat in subscriber count (the bench ``--fanout``
acceptance).

Slow consumers are coalesced, never buffered unboundedly and never
waited on: when a subscriber's queue is full, its backlog is dropped,
a coalesce drop is counted, and a fresh keyframe of the CURRENT tick
takes its place — the consumer loses intermediate deltas (each tick's
frame supersedes the last; dashboards want now, not history) and
recovers exact state from the keyframe. The publish hook therefore
runs in O(subscribers) bounded, lock-cheap steps regardless of how
wedged any consumer is.

HTTP surface (stdlib ThreadingHTTPServer, the telemetry/http.py
pattern — daemon threads, loud bind failure at startup):

- ``GET /results`` — JSON index of every cached stream (job, output,
  epoch, seq, frame bytes, subscriber count);
- ``GET /streams/<job>/<output>`` — SSE: one ``keyframe`` event from
  the cache immediately, then live ``keyframe``/``delta`` events as
  ticks publish. ``data:`` is the base64 blob (serving/delta.py wire),
  ``id:`` the publish seq. ``<job>`` is ``source_name:job_number``.

``port=None`` runs the hub without HTTP — the bench's simulated
subscribers and the unit tests attach through :meth:`subscribe`, the
exact API the SSE handler uses.

Telemetry (ADR 0116): ``livedata_serving_frames``/``_bytes`` counters
(labeled keyframe|delta, counted per subscriber delivery — the fan-out
volume), ``livedata_serving_coalesce_drops``, and a keyed collector
exposing per-stream subscriber gauges and per-subscriber queue depths.
"""

from __future__ import annotations

import base64
import json
import logging
import os
import queue
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import unquote

from ..telemetry.e2e import observe_stage
from ..telemetry.registry import REGISTRY, MetricFamily, Sample
from .delta import (
    DeltaEncoder,
    decode_header,
    encode_delta,
    encode_keyframe,
)
from .result_cache import ResultCache

__all__ = ["BroadcastServer", "Subscription", "stream_key"]

logger = logging.getLogger(__name__)

#: Fan-out volume: frames/bytes enqueued per subscriber delivery, split
#: keyframe vs delta — delta bytes ≪ keyframe bytes is the tier's
#: bandwidth claim (bench --fanout records the ratio).
SERVING_FRAMES = REGISTRY.counter(
    "livedata_serving_frames",
    "Frames enqueued to subscribers by the broadcast plane",
    labelnames=("kind",),
)
SERVING_BYTES = REGISTRY.counter(
    "livedata_serving_bytes",
    "Bytes enqueued to subscribers by the broadcast plane",
    labelnames=("kind",),
)
SERVING_COALESCE_DROPS = REGISTRY.counter(
    "livedata_serving_coalesce_drops",
    "Slow-subscriber backlogs dropped and replaced by a keyframe",
)
#: Hub-side encodes per publish tick — ONE per (stream, tick) however
#: many subscribers or relays are attached (the fan-out saving; the
#: relay bench gates encodes/tick at the compute hub directly).
SERVING_ENCODES = REGISTRY.counter(
    "livedata_serving_encodes",
    "Delta/keyframe encodes performed by the broadcast hub (one per "
    "stream per publish tick, independent of subscriber count)",
    labelnames=("kind",),
)
#: Last-Event-ID resume outcomes (relay reconnects, browser refreshes):
#: ``delta`` = the gap was served from the recent-frame ring without a
#: full keyframe, ``current`` = the client was already at the head,
#: ``keyframe`` = epoch mismatch or ring miss forced a full rebase.
SERVING_RESUMES = REGISTRY.counter(
    "livedata_serving_resumes",
    "Subscriber attaches that carried Last-Event-ID resume metadata, "
    "by outcome",
    labelnames=("result",),
)


def stream_key(job: str, output: str) -> str:
    """The hub's stream id — mirrors the SSE path ``/streams/<job>/<output>``."""
    return f"{job}/{output}"


class Subscription:
    """One attached consumer: a bounded blob queue + resync flag.

    The queue is the ONLY hand-off between the publish hook and the
    consumer thread; it is bounded (coalesce-on-overflow, see module
    docstring) and drained with timeouts, so neither side can park
    forever (graftlint JGL010 discipline). Entries are
    ``(blob, source_ts_ns)`` pairs internally: the source timestamp
    rides along so dequeue can fold the ``subscriber_delivered`` e2e
    boundary in (ADR 0120) — the blob wire itself is untouched.
    """

    __slots__ = ("stream", "sub_id", "_queue", "delivered", "chaos", "stage")

    def __init__(
        self,
        stream: str,
        sub_id: int,
        limit: int,
        chaos=None,
        stage: str = "subscriber_delivered",
    ) -> None:
        self.stream = stream
        self.sub_id = sub_id
        self._queue: queue.Queue[tuple[bytes, int | None]] = queue.Queue(
            maxsize=limit
        )
        #: Blobs enqueued to this subscriber (hub-lock-guarded).
        self.delivered = 0
        #: Fault-injection schedule (harness/chaos.py): a fired
        #: ``subscriber_stall`` delays THIS consumer's dequeue — the
        #: slow-reader shape the coalesce path exists for.
        self.chaos = chaos
        #: The e2e boundary this consumer's dequeue observes (ADR
        #: 0120/0121): end viewers record ``subscriber_delivered``; a
        #: relay's upstream subscription records ``relay_ingress`` so
        #: the freshness histogram decomposes per hop instead of
        #: double-counting the headline stage.
        self.stage = stage

    def next_blob(self, timeout: float = 0.5) -> bytes | None:
        """The next blob, or None after ``timeout`` — callers loop and
        re-check their stop condition (never an untimeboxed park)."""
        blob, _ts = self.next_blob_meta(timeout=timeout)
        return blob

    def next_blob_meta(
        self, timeout: float = 0.5
    ) -> tuple[bytes | None, int | None]:
        """:meth:`next_blob` plus the blob's source timestamp (ns) —
        the SSE handler emits it as frame metadata. Dequeue is the
        ``subscriber_delivered`` boundary: the consumer owns the frame
        from here, whatever it does with it next."""
        if self.chaos is not None:
            self.chaos.maybe_delay("subscriber_stall")
        try:
            blob, ts = self._queue.get(timeout=timeout)
        except queue.Empty:
            return None, None
        observe_stage(self.stage, ts)
        return blob, ts

    def depth(self) -> int:
        return self._queue.qsize()

    # -- hub side (caller holds the hub lock) ------------------------------
    def _offer(self, blob: bytes, resync_keyframe, ts: int | None) -> bool:
        """Enqueue ``blob``; on overflow drop the backlog and enqueue a
        fresh keyframe instead (``resync_keyframe`` is a thunk so the
        keyframe encodes at most once per publish no matter how many
        subscribers overflowed). Returns False when coalesced."""
        try:
            self._queue.put_nowait((blob, ts))
            return True
        except queue.Full:
            while True:
                try:
                    self._queue.get_nowait()
                except queue.Empty:
                    break
            try:
                self._queue.put_nowait((resync_keyframe(), ts))
            except queue.Full:  # pragma: no cover - limit >= 1 by ctor
                pass
            return False


class BroadcastServer:
    """Subscriber hub + optional SSE/HTTP plane over a ResultCache."""

    def __init__(
        self,
        *,
        cache: ResultCache | None = None,
        port: int | None = None,
        host: str = "0.0.0.0",
        queue_limit: int = 32,
        name: str = "serving",
        heartbeat_s: float = 10.0,
        hop: int = 0,
        registry=REGISTRY,
    ) -> None:
        if queue_limit < 1:
            raise ValueError("queue_limit must be >= 1")
        if heartbeat_s <= 0:
            raise ValueError("heartbeat_s must be positive")
        self.cache = cache if cache is not None else ResultCache()
        self._queue_limit = int(queue_limit)
        self._name = name
        #: Seconds between SSE heartbeat comments on an idle stream —
        #: how fast a downstream relay/browser can tell a dead upstream
        #: from a quiet one (fleet/sse_client.py sizes its idle timeout
        #: from this).
        self.heartbeat_s = float(heartbeat_s)
        #: Distance from the compute tier in relay hops: 0 at the
        #: publishing service, upstream+1 at each relay. Rides every
        #: ``/results`` row so clients (and the metrics smoke) can see
        #: which tier they landed on.
        self.hop = int(hop)
        #: Hub incarnation id, leading every SSE event id
        #: (``<boot>:<epoch>:<seq>``). Epoch/seq numbering restarts
        #: with the process, so a ``Last-Event-ID`` from a PREVIOUS
        #: incarnation is not comparable — a boot mismatch forces the
        #: keyframe attach instead of silently treating the client as
        #: caught up (and lets a relay tell "upstream restarted" from
        #: "my connection blipped", ADR 0121).
        self.boot = os.urandom(4).hex()
        #: Optional callable returning extra ``/results`` rows for
        #: streams served by PEER nodes (fleet/control.py): each row
        #: carries a ``url`` pointing at the right hop. None = local
        #: index only.
        self._index_peers = None
        self._lock = threading.Lock()
        self._subscribers: dict[str, dict[int, Subscription]] = {}
        self._next_sub_id = 0
        #: Per-stream delta encoders — touched ONLY by the publish hook
        #: (single-writer contract, serving/delta.py); subscriber attach
        #: reads keyframes from the cache, never from here.
        self._encoders: dict[str, DeltaEncoder] = {}
        #: Last published source timestamp per stream (hub-lock-guarded):
        #: attach keyframes inherit it, and the scrape-time freshness
        #: collector reads it (ADR 0120).
        self._last_source_ts: dict[str, int] = {}
        #: Fault-injection schedule handed to new subscriptions
        #: (harness/chaos.py); None in production.
        self._chaos = None
        #: THIS hub's publish-tick encodes (hub-lock-guarded): the
        #: global ``livedata_serving_encodes`` counter sums every hub
        #: in the process, but the relay bench must prove the COMPUTE
        #: hub alone encodes once per stream per tick however many
        #: relays fan it out (ADR 0121).
        self.encodes = 0
        self._stopped = threading.Event()
        self._registry = registry
        self._collector_key = f"serving:{name}"
        registry.register_collector(self._collector_key, self._telemetry)
        self._frames_key = SERVING_FRAMES.labels(kind="keyframe")
        self._frames_delta = SERVING_FRAMES.labels(kind="delta")
        self._bytes_key = SERVING_BYTES.labels(kind="keyframe")
        self._bytes_delta = SERVING_BYTES.labels(kind="delta")
        self._server: ThreadingHTTPServer | None = None
        self._thread: threading.Thread | None = None
        if port is not None:
            handler = type(
                "_BoundHandler", (_Handler,), {"broadcast": self}
            )
            # A bind failure raises at startup — an operator who asked
            # for a serve port must not silently run dark (the
            # telemetry/http.py rule).
            self._server = ThreadingHTTPServer((host, int(port)), handler)
            self._server.daemon_threads = True
            self._thread = threading.Thread(
                target=self._server.serve_forever,
                name=f"serving-http-{self.port}",
                daemon=True,
            )
            self._thread.start()
            logger.info(
                "result fan-out endpoint on %s:%d (/results, /streams/...)",
                host,
                self.port,
            )

    @property
    def port(self) -> int | None:
        """The bound port (0 requests an ephemeral one); None = hub-only."""
        return None if self._server is None else self._server.server_address[1]

    @property
    def stopped(self) -> bool:
        return self._stopped.is_set()

    def set_chaos(self, chaos) -> None:
        """Install a fault-injection schedule (harness/chaos.py) handed
        to every LATER subscription — existing consumers keep running
        clean, which is exactly how a partial-outage drill looks."""
        self._chaos = chaos

    def set_index_peers(self, peers) -> None:
        """Install a callable returning extra ``/results`` rows for
        streams served by peer nodes (fleet/control.py federation):
        replicas list each other's partitions, a relay lists upstream
        streams it has not (yet) relayed — each row's ``url`` points
        the client at the right hop. None removes the hook."""
        self._index_peers = peers

    # -- hub ---------------------------------------------------------------
    def subscribe(
        self,
        stream: str,
        *,
        resume: tuple[int, int] | None = None,
        stage: str = "subscriber_delivered",
    ) -> Subscription:
        """Attach a consumer; a keyframe of the latest cached tick is
        enqueued immediately (registration and the cache read happen
        under the hub lock, so a concurrent publish either reaches this
        subscriber's queue or is already inside its keyframe — the
        stale-delta rule in DeltaDecoder absorbs the overlap).

        ``resume`` is Last-Event-ID-style metadata ``(epoch, seq)`` — a
        reconnecting client that still holds the frame it decoded at
        that tick. When the epoch still matches and the recent-frame
        ring covers the gap, the missed ticks are served as DELTAS
        against the client's held frame instead of a full keyframe (the
        relay reconnect path, ADR 0121); an epoch mismatch or a gap
        older than the ring falls back to today's keyframe attach, and
        a client already at the head gets nothing queued (live frames
        follow). Outcomes count into ``livedata_serving_resumes``.

        ``stage`` names the e2e boundary this consumer's dequeues
        observe (see :class:`Subscription`).
        """
        with self._lock:
            sub_id = self._next_sub_id
            self._next_sub_id += 1
            sub = Subscription(
                stream,
                sub_id,
                self._queue_limit,
                chaos=self._chaos,
                stage=stage,
            )
            self._subscribers.setdefault(stream, {})[sub_id] = sub
            cached = self.cache.latest(stream)
            if cached is not None:
                ts = self._last_source_ts.get(stream)
                blobs, outcome = self._attach_blobs(stream, cached, resume)
                resync: list[bytes] = []

                def resync_keyframe() -> bytes:
                    # Overflow during a multi-delta resume must coalesce
                    # to a REAL keyframe (enqueuing a later delta would
                    # hand the client an unsignaled seq gap); encoded at
                    # most once, and reused when the attach blob already
                    # is that keyframe.
                    if blobs and decode_header(blobs[-1]).keyframe:
                        return blobs[-1]
                    if not resync:
                        resync.append(
                            encode_keyframe(
                                cached.frame,
                                epoch=cached.epoch,
                                seq=cached.seq,
                            )
                        )
                    return resync[0]

                for blob in blobs:
                    header = decode_header(blob)
                    if sub._offer(blob, resync_keyframe, ts):
                        sub.delivered += 1
                        if header.keyframe:
                            self._frames_key.inc()
                            self._bytes_key.inc(len(blob))
                        else:
                            self._frames_delta.inc()
                            self._bytes_delta.inc(len(blob))
                    else:
                        sub.delivered += 1
                        SERVING_COALESCE_DROPS.inc()
                        self._frames_key.inc()
                        self._bytes_key.inc(len(resync_keyframe()))
                if resume is not None:
                    SERVING_RESUMES.labels(result=outcome).inc()
        return sub

    def _attach_blobs(
        self, stream: str, latest, resume: tuple[int, int] | None
    ) -> tuple[list[bytes], str]:
        """The blobs a fresh subscription starts with (caller holds the
        hub lock): a keyframe normally; under a matching ``resume``,
        the ring-served delta gap or nothing at all. The keyframe is
        only encoded on the branches that return it — a clean resume
        must not pay an O(frame) copy under the hub lock."""

        def keyframe() -> list[bytes]:
            return [
                encode_keyframe(
                    latest.frame, epoch=latest.epoch, seq=latest.seq
                )
            ]

        if resume is None:
            return keyframe(), "keyframe"
        epoch, seq = resume
        if epoch != latest.epoch:
            return keyframe(), "keyframe"
        if seq >= latest.seq:
            # Already at (or somehow past) the head: live deltas apply
            # directly to the client's held frame.
            return [], "current"
        ring = {
            frame.seq: frame.frame for frame in self.cache.recent(stream)
        }
        if any(s not in ring for s in range(seq, latest.seq + 1)):
            # The gap predates the ring (or spans an epoch reset that
            # cleared it): only a full rebase is sound.
            return keyframe(), "keyframe"
        deltas = [
            encode_delta(
                ring[s - 1], ring[s], epoch=latest.epoch, seq=s
            )
            for s in range(seq + 1, latest.seq + 1)
        ]
        return deltas, "delta"

    def unsubscribe(self, sub: Subscription) -> None:
        with self._lock:
            subs = self._subscribers.get(sub.stream)
            if subs is not None:
                subs.pop(sub.sub_id, None)
                if not subs:
                    del self._subscribers[sub.stream]

    def publish_frame(
        self, stream: str, frame: bytes, token, source_ts_ns: int | None = None
    ) -> None:
        """One publish tick for one stream: cache it, delta-encode it
        once, fan the blob out to every attached subscriber's bounded
        queue. Called from the service's publish hook (step worker) —
        everything here is host-side O(frame) + O(subscribers).
        ``source_ts_ns`` (ADR 0120) rides each queue entry so dequeue
        records delivery freshness, and feeds the per-stream freshness
        gauges the scrape collector exposes."""
        cached = self.cache.put(stream, frame, token)
        encoder = self._encoders.get(stream)
        if encoder is None:
            encoder = self._encoders[stream] = DeltaEncoder()
        blob = encoder.encode(frame, epoch=cached.epoch, seq=cached.seq)
        is_keyframe = bool(decode_header(blob).keyframe)
        SERVING_ENCODES.labels(
            kind="keyframe" if is_keyframe else "delta"
        ).inc()
        resync: list[bytes] = []

        def resync_keyframe() -> bytes:
            # At most one keyframe encode per publish, shared by every
            # overflowed subscriber; when the tick's own blob already IS
            # the keyframe, reuse it outright.
            if is_keyframe:
                return blob
            if not resync:
                resync.append(
                    encode_keyframe(
                        frame, epoch=cached.epoch, seq=cached.seq
                    )
                )
                SERVING_ENCODES.labels(kind="resync").inc()
            return resync[0]

        frames_child = self._frames_key if is_keyframe else self._frames_delta
        bytes_child = self._bytes_key if is_keyframe else self._bytes_delta
        with self._lock:
            self.encodes += 1
            if source_ts_ns is not None:
                self._last_source_ts[stream] = int(source_ts_ns)
            subs = self._subscribers.get(stream)
            if not subs:
                return
            for sub in subs.values():
                delivered = sub._offer(blob, resync_keyframe, source_ts_ns)
                sub.delivered += 1
                if delivered:
                    frames_child.inc()
                    bytes_child.inc(len(blob))
                else:
                    SERVING_COALESCE_DROPS.inc()
                    self._frames_key.inc()
                    self._bytes_key.inc(len(resync_keyframe()))

    def drop_stream(self, stream: str) -> None:
        """Forget a retired stream (job removed): cache entry, encoder
        state and freshness entry go; attached subscribers simply stop
        receiving. (Dropping the freshness entry matters: a dead
        stream's gauge would otherwise read ever-staler forever —
        and pin the label set, the JGL025 cardinality leak.)"""
        self.cache.invalidate(stream)
        self._encoders.pop(stream, None)
        with self._lock:
            self._last_source_ts.pop(stream, None)

    def drop_job(self, job: str) -> int:
        """Forget every stream of one retired job (the JobManager's
        remove command, via the retire observer): without this a
        long-running service under job churn would cache a ring of
        full frames per dead stream forever and keep listing it in
        ``/results`` as if live. Returns how many streams dropped."""
        prefix = f"{job}/"
        streams = [
            stream
            for stream in self.cache.streams()
            if stream.startswith(prefix)
        ]
        # Encoder keys are publish-hook-private, but a removed job
        # publishes nothing further — popping here is safe and frees
        # the prev-frame copy the encoder holds.
        for stream in streams:
            self.drop_stream(stream)
        return len(streams)

    # -- QoS ----------------------------------------------------------------
    def qos(self) -> dict[str, float | int]:
        """Subscriber count + worst send-queue pressure in [0, 1] — the
        LinkMonitor's fan-out axis reads this (back off publish
        coalescing when nobody is watching, hold cadence when someone
        is; core/link_monitor.py)."""
        with self._lock:
            n = sum(len(subs) for subs in self._subscribers.values())
            pressure = 0.0
            for subs in self._subscribers.values():
                for sub in subs.values():
                    pressure = max(
                        pressure, sub.depth() / self._queue_limit
                    )
            return {"subscribers": n, "queue_pressure": pressure}

    # -- telemetry ----------------------------------------------------------
    def _telemetry(self) -> list[MetricFamily]:
        subs_fam = MetricFamily(
            "livedata_serving_subscribers",
            "gauge",
            "Attached broadcast subscribers per stream",
        )
        depth_fam = MetricFamily(
            "livedata_serving_queue_depth",
            "gauge",
            "Per-subscriber send-queue depth (bounded at queue_limit; "
            "overflow coalesces to a keyframe instead of growing)",
        )
        fresh_fam = MetricFamily(
            "livedata_result_freshness_seconds",
            "gauge",
            "Wall-clock age of the newest published source timestamp "
            "per (job, output) stream (ADR 0120): how stale a viewer "
            "attaching NOW would be",
        )
        now_ns = time.time_ns()
        base = (("server", self._name),)
        with self._lock:
            total = 0
            for stream, subs in sorted(self._subscribers.items()):
                total += len(subs)
                subs_fam.samples.append(
                    Sample("", base + (("stream", stream),), len(subs))
                )
                for sub_id, sub in sorted(subs.items()):
                    depth_fam.samples.append(
                        Sample(
                            "",
                            base
                            + (
                                ("stream", stream),
                                ("subscriber", str(sub_id)),
                            ),
                            sub.depth(),
                        )
                    )
            for stream, ts in sorted(self._last_source_ts.items()):
                job, _, output = stream.partition("/")
                fresh_fam.samples.append(
                    Sample(
                        "",
                        base + (("job", job), ("output", output)),
                        max(0.0, (now_ns - ts) / 1e9),
                    )
                )
        subs_fam.samples.append(
            Sample("", base + (("stream", "all"),), total)
        )
        return [subs_fam, depth_fam, fresh_fam]

    def close(self) -> None:
        self._stopped.set()
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            if self._thread is not None:
                self._thread.join(timeout=5.0)
        # Owner-guarded: a successor server under the same name must
        # not lose its live collector to our late close (ADR 0116).
        self._registry.unregister_collector(
            self._collector_key, self._telemetry
        )


class _Handler(BaseHTTPRequestHandler):
    broadcast: BroadcastServer

    protocol_version = "HTTP/1.1"

    def do_GET(self) -> None:  # noqa: N802 - BaseHTTPRequestHandler API
        path = self.path.split("?", 1)[0]
        if path == "/results":
            self._serve_index()
        elif path.startswith("/streams/"):
            self._serve_stream(path)
        else:
            self._json_error(
                404, "unknown path (try /results or /streams/<job>/<output>)"
            )

    def _json_error(self, code: int, message: str) -> None:
        payload = json.dumps({"error": message}).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(payload)))
        self.end_headers()
        self.wfile.write(payload)

    def _serve_index(self) -> None:
        hub = self.broadcast
        streams = hub.cache.streams()
        with hub._lock:
            counts = {
                stream: len(subs)
                for stream, subs in hub._subscribers.items()
            }
            peers = hub._index_peers
        rows = []
        for stream, cached in sorted(streams.items()):
            job, _, output = stream.partition("/")
            rows.append(
                {
                    "job": job,
                    "output": output,
                    "stream": stream,
                    "epoch": cached.epoch,
                    "seq": cached.seq,
                    "frame_bytes": len(cached.frame),
                    "subscribers": counts.get(stream, 0),
                    "path": f"/streams/{stream}",
                    "node": hub._name,
                    "hop": hub.hop,
                }
            )
        if peers is not None:
            # Federation (ADR 0121): append peer rows for streams this
            # node does not serve locally — a peer outage degrades the
            # index to local-only instead of 500ing it.
            local = {row["stream"] for row in rows}
            try:
                rows.extend(
                    row
                    for row in peers()
                    if row.get("stream") not in local
                )
            except Exception:
                logger.exception("peer index federation failed")
        payload = json.dumps({"streams": rows}).encode()
        self.send_response(200)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(payload)))
        self.end_headers()
        self.wfile.write(payload)

    def _serve_stream(self, path: str) -> None:
        parts = path.split("/", 3)
        if len(parts) < 4 or not parts[2] or not parts[3]:
            self._json_error(404, "expected /streams/<job>/<output>")
            return
        stream = stream_key(unquote(parts[2]), unquote(parts[3]))
        hub = self.broadcast
        if hub.cache.latest(stream) is None:
            self._json_error(
                404,
                f"no published results for stream {stream!r} "
                "(see /results for the index)",
            )
            return
        # Last-Event-ID resume (ADR 0121): the SSE ``id:`` field is
        # ``<boot>:<epoch>:<seq>``; a reconnecting EventSource (or
        # relay) echoes it back and, boot + epoch permitting, resumes
        # on deltas instead of a full keyframe. An id minted by a
        # PREVIOUS hub incarnation (boot mismatch) or a malformed one
        # degrades to the plain keyframe attach.
        resume = None
        raw_id = self.headers.get("Last-Event-ID")
        if raw_id:
            parts = raw_id.strip().split(":")
            if len(parts) == 3 and parts[0] == hub.boot:
                try:
                    resume = (int(parts[1]), int(parts[2]))
                except ValueError:
                    resume = None
        sub = hub.subscribe(stream, resume=resume)
        try:
            self.send_response(200)
            self.send_header("Content-Type", "text/event-stream")
            self.send_header("Cache-Control", "no-cache")
            # SSE is an unbounded response: no Content-Length, and the
            # connection closes when either side goes away.
            self.send_header("Connection", "close")
            self.end_headers()
            self.wfile.write(b"retry: 3000\n\n")
            last_write = time.monotonic()
            heartbeat_s = hub.heartbeat_s
            while not hub.stopped:
                blob, source_ts = sub.next_blob_meta(
                    timeout=min(0.5, heartbeat_s / 2)
                )
                if blob is None:
                    if time.monotonic() - last_write >= heartbeat_s:
                        # Idle-stream heartbeat: lets a client (relay,
                        # EventSource wrapper) distinguish "no new
                        # ticks" from "dead upstream" without waiting
                        # out a TCP timeout (ADR 0121).
                        self.wfile.write(b": keepalive\n\n")
                        self.wfile.flush()
                        last_write = time.monotonic()
                    continue
                header = decode_header(blob)
                kind = b"keyframe" if header.keyframe else b"delta"
                # Frame metadata (ADR 0120): the source timestamp as an
                # SSE comment — EventSource clients ignore comments, so
                # the data wire is unchanged, but a latency-aware
                # client (the SLO harness, dashboards) reads its
                # freshness without decoding da00.
                meta = (
                    b""
                    if source_ts is None
                    else b": source_ts_ns=%d\n" % source_ts
                )
                self.wfile.write(
                    b"%sid: %s:%d:%d\nevent: %s\ndata: %s\n\n"
                    % (
                        meta,
                        hub.boot.encode(),
                        header.epoch,
                        header.seq,
                        kind,
                        base64.b64encode(blob),
                    )
                )
                self.wfile.flush()
                last_write = time.monotonic()
        except (BrokenPipeError, ConnectionResetError, OSError):
            # Consumer went away mid-stream: routine, not an error.
            logger.debug("SSE subscriber %d disconnected", sub.sub_id)
        finally:
            hub.unsubscribe(sub)

    def log_message(self, format: str, *args) -> None:  # noqa: A002
        logger.debug("serving http: " + format, *args)

"""Broadcast plane: one publish in, N subscribers out (ADR 0117).

The hub decouples every viewer from the compute loop. The service's
publish hook calls :meth:`BroadcastServer.publish_frame` once per
(job, output) per publish tick; the hub stores the frame in the
:class:`~.result_cache.ResultCache`, delta-encodes it ONCE against the
previous tick (serving/delta.py), and enqueues the resulting blob onto
every attached subscriber's bounded queue. Per-subscriber cost is one
``put_nowait`` — no encoding, no device work, no serialization — so
publish-side work is flat in subscriber count (the bench ``--fanout``
acceptance).

Slow consumers are coalesced, never buffered unboundedly and never
waited on: when a subscriber's queue is full, its backlog is dropped,
a coalesce drop is counted, and a fresh keyframe of the CURRENT tick
takes its place — the consumer loses intermediate deltas (each tick's
frame supersedes the last; dashboards want now, not history) and
recovers exact state from the keyframe. The publish hook therefore
runs in O(subscribers) bounded, lock-cheap steps regardless of how
wedged any consumer is.

HTTP surface (stdlib ThreadingHTTPServer, the telemetry/http.py
pattern — daemon threads, loud bind failure at startup):

- ``GET /results`` — JSON index of every cached stream (job, output,
  epoch, seq, frame bytes, subscriber count);
- ``GET /streams/<job>/<output>`` — SSE: one ``keyframe`` event from
  the cache immediately, then live ``keyframe``/``delta`` events as
  ticks publish. ``data:`` is the base64 blob (serving/delta.py wire),
  ``id:`` the publish seq. ``<job>`` is ``source_name:job_number``.

``port=None`` runs the hub without HTTP — the bench's simulated
subscribers and the unit tests attach through :meth:`subscribe`, the
exact API the SSE handler uses.

Telemetry (ADR 0116): ``livedata_serving_frames``/``_bytes`` counters
(labeled keyframe|delta, counted per subscriber delivery — the fan-out
volume), ``livedata_serving_coalesce_drops``, and a keyed collector
exposing per-stream subscriber gauges and per-subscriber queue depths.
"""

from __future__ import annotations

import base64
import json
import logging
import queue
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import unquote

from ..telemetry.e2e import observe_stage
from ..telemetry.registry import REGISTRY, MetricFamily, Sample
from .delta import DeltaEncoder, decode_header, encode_keyframe
from .result_cache import ResultCache

__all__ = ["BroadcastServer", "Subscription", "stream_key"]

logger = logging.getLogger(__name__)

#: Fan-out volume: frames/bytes enqueued per subscriber delivery, split
#: keyframe vs delta — delta bytes ≪ keyframe bytes is the tier's
#: bandwidth claim (bench --fanout records the ratio).
SERVING_FRAMES = REGISTRY.counter(
    "livedata_serving_frames",
    "Frames enqueued to subscribers by the broadcast plane",
    labelnames=("kind",),
)
SERVING_BYTES = REGISTRY.counter(
    "livedata_serving_bytes",
    "Bytes enqueued to subscribers by the broadcast plane",
    labelnames=("kind",),
)
SERVING_COALESCE_DROPS = REGISTRY.counter(
    "livedata_serving_coalesce_drops",
    "Slow-subscriber backlogs dropped and replaced by a keyframe",
)


def stream_key(job: str, output: str) -> str:
    """The hub's stream id — mirrors the SSE path ``/streams/<job>/<output>``."""
    return f"{job}/{output}"


class Subscription:
    """One attached consumer: a bounded blob queue + resync flag.

    The queue is the ONLY hand-off between the publish hook and the
    consumer thread; it is bounded (coalesce-on-overflow, see module
    docstring) and drained with timeouts, so neither side can park
    forever (graftlint JGL010 discipline). Entries are
    ``(blob, source_ts_ns)`` pairs internally: the source timestamp
    rides along so dequeue can fold the ``subscriber_delivered`` e2e
    boundary in (ADR 0120) — the blob wire itself is untouched.
    """

    __slots__ = ("stream", "sub_id", "_queue", "delivered", "chaos")

    def __init__(
        self, stream: str, sub_id: int, limit: int, chaos=None
    ) -> None:
        self.stream = stream
        self.sub_id = sub_id
        self._queue: queue.Queue[tuple[bytes, int | None]] = queue.Queue(
            maxsize=limit
        )
        #: Blobs enqueued to this subscriber (hub-lock-guarded).
        self.delivered = 0
        #: Fault-injection schedule (harness/chaos.py): a fired
        #: ``subscriber_stall`` delays THIS consumer's dequeue — the
        #: slow-reader shape the coalesce path exists for.
        self.chaos = chaos

    def next_blob(self, timeout: float = 0.5) -> bytes | None:
        """The next blob, or None after ``timeout`` — callers loop and
        re-check their stop condition (never an untimeboxed park)."""
        blob, _ts = self.next_blob_meta(timeout=timeout)
        return blob

    def next_blob_meta(
        self, timeout: float = 0.5
    ) -> tuple[bytes | None, int | None]:
        """:meth:`next_blob` plus the blob's source timestamp (ns) —
        the SSE handler emits it as frame metadata. Dequeue is the
        ``subscriber_delivered`` boundary: the consumer owns the frame
        from here, whatever it does with it next."""
        if self.chaos is not None:
            self.chaos.maybe_delay("subscriber_stall")
        try:
            blob, ts = self._queue.get(timeout=timeout)
        except queue.Empty:
            return None, None
        observe_stage("subscriber_delivered", ts)
        return blob, ts

    def depth(self) -> int:
        return self._queue.qsize()

    # -- hub side (caller holds the hub lock) ------------------------------
    def _offer(self, blob: bytes, resync_keyframe, ts: int | None) -> bool:
        """Enqueue ``blob``; on overflow drop the backlog and enqueue a
        fresh keyframe instead (``resync_keyframe`` is a thunk so the
        keyframe encodes at most once per publish no matter how many
        subscribers overflowed). Returns False when coalesced."""
        try:
            self._queue.put_nowait((blob, ts))
            return True
        except queue.Full:
            while True:
                try:
                    self._queue.get_nowait()
                except queue.Empty:
                    break
            try:
                self._queue.put_nowait((resync_keyframe(), ts))
            except queue.Full:  # pragma: no cover - limit >= 1 by ctor
                pass
            return False


class BroadcastServer:
    """Subscriber hub + optional SSE/HTTP plane over a ResultCache."""

    def __init__(
        self,
        *,
        cache: ResultCache | None = None,
        port: int | None = None,
        host: str = "0.0.0.0",
        queue_limit: int = 32,
        name: str = "serving",
        registry=REGISTRY,
    ) -> None:
        if queue_limit < 1:
            raise ValueError("queue_limit must be >= 1")
        self.cache = cache if cache is not None else ResultCache()
        self._queue_limit = int(queue_limit)
        self._name = name
        self._lock = threading.Lock()
        self._subscribers: dict[str, dict[int, Subscription]] = {}
        self._next_sub_id = 0
        #: Per-stream delta encoders — touched ONLY by the publish hook
        #: (single-writer contract, serving/delta.py); subscriber attach
        #: reads keyframes from the cache, never from here.
        self._encoders: dict[str, DeltaEncoder] = {}
        #: Last published source timestamp per stream (hub-lock-guarded):
        #: attach keyframes inherit it, and the scrape-time freshness
        #: collector reads it (ADR 0120).
        self._last_source_ts: dict[str, int] = {}
        #: Fault-injection schedule handed to new subscriptions
        #: (harness/chaos.py); None in production.
        self._chaos = None
        self._stopped = threading.Event()
        self._registry = registry
        self._collector_key = f"serving:{name}"
        registry.register_collector(self._collector_key, self._telemetry)
        self._frames_key = SERVING_FRAMES.labels(kind="keyframe")
        self._frames_delta = SERVING_FRAMES.labels(kind="delta")
        self._bytes_key = SERVING_BYTES.labels(kind="keyframe")
        self._bytes_delta = SERVING_BYTES.labels(kind="delta")
        self._server: ThreadingHTTPServer | None = None
        self._thread: threading.Thread | None = None
        if port is not None:
            handler = type(
                "_BoundHandler", (_Handler,), {"broadcast": self}
            )
            # A bind failure raises at startup — an operator who asked
            # for a serve port must not silently run dark (the
            # telemetry/http.py rule).
            self._server = ThreadingHTTPServer((host, int(port)), handler)
            self._server.daemon_threads = True
            self._thread = threading.Thread(
                target=self._server.serve_forever,
                name=f"serving-http-{self.port}",
                daemon=True,
            )
            self._thread.start()
            logger.info(
                "result fan-out endpoint on %s:%d (/results, /streams/...)",
                host,
                self.port,
            )

    @property
    def port(self) -> int | None:
        """The bound port (0 requests an ephemeral one); None = hub-only."""
        return None if self._server is None else self._server.server_address[1]

    @property
    def stopped(self) -> bool:
        return self._stopped.is_set()

    def set_chaos(self, chaos) -> None:
        """Install a fault-injection schedule (harness/chaos.py) handed
        to every LATER subscription — existing consumers keep running
        clean, which is exactly how a partial-outage drill looks."""
        self._chaos = chaos

    # -- hub ---------------------------------------------------------------
    def subscribe(self, stream: str) -> Subscription:
        """Attach a consumer; a keyframe of the latest cached tick is
        enqueued immediately (registration and the cache read happen
        under the hub lock, so a concurrent publish either reaches this
        subscriber's queue or is already inside its keyframe — the
        stale-delta rule in DeltaDecoder absorbs the overlap)."""
        with self._lock:
            sub_id = self._next_sub_id
            self._next_sub_id += 1
            sub = Subscription(
                stream, sub_id, self._queue_limit, chaos=self._chaos
            )
            self._subscribers.setdefault(stream, {})[sub_id] = sub
            cached = self.cache.latest(stream)
            if cached is not None:
                blob = encode_keyframe(
                    cached.frame, epoch=cached.epoch, seq=cached.seq
                )
                sub._offer(
                    blob, lambda: blob, self._last_source_ts.get(stream)
                )
                sub.delivered += 1
                self._frames_key.inc()
                self._bytes_key.inc(len(blob))
        return sub

    def unsubscribe(self, sub: Subscription) -> None:
        with self._lock:
            subs = self._subscribers.get(sub.stream)
            if subs is not None:
                subs.pop(sub.sub_id, None)
                if not subs:
                    del self._subscribers[sub.stream]

    def publish_frame(
        self, stream: str, frame: bytes, token, source_ts_ns: int | None = None
    ) -> None:
        """One publish tick for one stream: cache it, delta-encode it
        once, fan the blob out to every attached subscriber's bounded
        queue. Called from the service's publish hook (step worker) —
        everything here is host-side O(frame) + O(subscribers).
        ``source_ts_ns`` (ADR 0120) rides each queue entry so dequeue
        records delivery freshness, and feeds the per-stream freshness
        gauges the scrape collector exposes."""
        cached = self.cache.put(stream, frame, token)
        encoder = self._encoders.get(stream)
        if encoder is None:
            encoder = self._encoders[stream] = DeltaEncoder()
        blob = encoder.encode(frame, epoch=cached.epoch, seq=cached.seq)
        is_keyframe = bool(decode_header(blob).keyframe)
        resync: list[bytes] = []

        def resync_keyframe() -> bytes:
            # At most one keyframe encode per publish, shared by every
            # overflowed subscriber; when the tick's own blob already IS
            # the keyframe, reuse it outright.
            if is_keyframe:
                return blob
            if not resync:
                resync.append(
                    encode_keyframe(
                        frame, epoch=cached.epoch, seq=cached.seq
                    )
                )
            return resync[0]

        frames_child = self._frames_key if is_keyframe else self._frames_delta
        bytes_child = self._bytes_key if is_keyframe else self._bytes_delta
        with self._lock:
            if source_ts_ns is not None:
                self._last_source_ts[stream] = int(source_ts_ns)
            subs = self._subscribers.get(stream)
            if not subs:
                return
            for sub in subs.values():
                delivered = sub._offer(blob, resync_keyframe, source_ts_ns)
                sub.delivered += 1
                if delivered:
                    frames_child.inc()
                    bytes_child.inc(len(blob))
                else:
                    SERVING_COALESCE_DROPS.inc()
                    self._frames_key.inc()
                    self._bytes_key.inc(len(resync_keyframe()))

    def drop_stream(self, stream: str) -> None:
        """Forget a retired stream (job removed): cache entry, encoder
        state and freshness entry go; attached subscribers simply stop
        receiving. (Dropping the freshness entry matters: a dead
        stream's gauge would otherwise read ever-staler forever —
        and pin the label set, the JGL025 cardinality leak.)"""
        self.cache.invalidate(stream)
        self._encoders.pop(stream, None)
        with self._lock:
            self._last_source_ts.pop(stream, None)

    def drop_job(self, job: str) -> int:
        """Forget every stream of one retired job (the JobManager's
        remove command, via the retire observer): without this a
        long-running service under job churn would cache a ring of
        full frames per dead stream forever and keep listing it in
        ``/results`` as if live. Returns how many streams dropped."""
        prefix = f"{job}/"
        streams = [
            stream
            for stream in self.cache.streams()
            if stream.startswith(prefix)
        ]
        # Encoder keys are publish-hook-private, but a removed job
        # publishes nothing further — popping here is safe and frees
        # the prev-frame copy the encoder holds.
        for stream in streams:
            self.drop_stream(stream)
        return len(streams)

    # -- QoS ----------------------------------------------------------------
    def qos(self) -> dict[str, float | int]:
        """Subscriber count + worst send-queue pressure in [0, 1] — the
        LinkMonitor's fan-out axis reads this (back off publish
        coalescing when nobody is watching, hold cadence when someone
        is; core/link_monitor.py)."""
        with self._lock:
            n = sum(len(subs) for subs in self._subscribers.values())
            pressure = 0.0
            for subs in self._subscribers.values():
                for sub in subs.values():
                    pressure = max(
                        pressure, sub.depth() / self._queue_limit
                    )
            return {"subscribers": n, "queue_pressure": pressure}

    # -- telemetry ----------------------------------------------------------
    def _telemetry(self) -> list[MetricFamily]:
        subs_fam = MetricFamily(
            "livedata_serving_subscribers",
            "gauge",
            "Attached broadcast subscribers per stream",
        )
        depth_fam = MetricFamily(
            "livedata_serving_queue_depth",
            "gauge",
            "Per-subscriber send-queue depth (bounded at queue_limit; "
            "overflow coalesces to a keyframe instead of growing)",
        )
        fresh_fam = MetricFamily(
            "livedata_result_freshness_seconds",
            "gauge",
            "Wall-clock age of the newest published source timestamp "
            "per (job, output) stream (ADR 0120): how stale a viewer "
            "attaching NOW would be",
        )
        now_ns = time.time_ns()
        base = (("server", self._name),)
        with self._lock:
            total = 0
            for stream, subs in sorted(self._subscribers.items()):
                total += len(subs)
                subs_fam.samples.append(
                    Sample("", base + (("stream", stream),), len(subs))
                )
                for sub_id, sub in sorted(subs.items()):
                    depth_fam.samples.append(
                        Sample(
                            "",
                            base
                            + (
                                ("stream", stream),
                                ("subscriber", str(sub_id)),
                            ),
                            sub.depth(),
                        )
                    )
            for stream, ts in sorted(self._last_source_ts.items()):
                job, _, output = stream.partition("/")
                fresh_fam.samples.append(
                    Sample(
                        "",
                        base + (("job", job), ("output", output)),
                        max(0.0, (now_ns - ts) / 1e9),
                    )
                )
        subs_fam.samples.append(
            Sample("", base + (("stream", "all"),), total)
        )
        return [subs_fam, depth_fam, fresh_fam]

    def close(self) -> None:
        self._stopped.set()
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            if self._thread is not None:
                self._thread.join(timeout=5.0)
        # Owner-guarded: a successor server under the same name must
        # not lose its live collector to our late close (ADR 0116).
        self._registry.unregister_collector(
            self._collector_key, self._telemetry
        )


#: Seconds between SSE keepalive comments while a stream is idle.
_KEEPALIVE_S = 10.0


class _Handler(BaseHTTPRequestHandler):
    broadcast: BroadcastServer

    protocol_version = "HTTP/1.1"

    def do_GET(self) -> None:  # noqa: N802 - BaseHTTPRequestHandler API
        path = self.path.split("?", 1)[0]
        if path == "/results":
            self._serve_index()
        elif path.startswith("/streams/"):
            self._serve_stream(path)
        else:
            self._json_error(
                404, "unknown path (try /results or /streams/<job>/<output>)"
            )

    def _json_error(self, code: int, message: str) -> None:
        payload = json.dumps({"error": message}).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(payload)))
        self.end_headers()
        self.wfile.write(payload)

    def _serve_index(self) -> None:
        hub = self.broadcast
        streams = hub.cache.streams()
        with hub._lock:
            counts = {
                stream: len(subs)
                for stream, subs in hub._subscribers.items()
            }
        rows = []
        for stream, cached in sorted(streams.items()):
            job, _, output = stream.partition("/")
            rows.append(
                {
                    "job": job,
                    "output": output,
                    "stream": stream,
                    "epoch": cached.epoch,
                    "seq": cached.seq,
                    "frame_bytes": len(cached.frame),
                    "subscribers": counts.get(stream, 0),
                    "path": f"/streams/{stream}",
                }
            )
        payload = json.dumps({"streams": rows}).encode()
        self.send_response(200)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(payload)))
        self.end_headers()
        self.wfile.write(payload)

    def _serve_stream(self, path: str) -> None:
        parts = path.split("/", 3)
        if len(parts) < 4 or not parts[2] or not parts[3]:
            self._json_error(404, "expected /streams/<job>/<output>")
            return
        stream = stream_key(unquote(parts[2]), unquote(parts[3]))
        hub = self.broadcast
        if hub.cache.latest(stream) is None:
            self._json_error(
                404,
                f"no published results for stream {stream!r} "
                "(see /results for the index)",
            )
            return
        sub = hub.subscribe(stream)
        try:
            self.send_response(200)
            self.send_header("Content-Type", "text/event-stream")
            self.send_header("Cache-Control", "no-cache")
            # SSE is an unbounded response: no Content-Length, and the
            # connection closes when either side goes away.
            self.send_header("Connection", "close")
            self.end_headers()
            self.wfile.write(b"retry: 3000\n\n")
            last_write = time.monotonic()
            while not hub.stopped:
                blob, source_ts = sub.next_blob_meta(timeout=0.5)
                if blob is None:
                    if time.monotonic() - last_write >= _KEEPALIVE_S:
                        self.wfile.write(b": keepalive\n\n")
                        self.wfile.flush()
                        last_write = time.monotonic()
                    continue
                header = decode_header(blob)
                kind = b"keyframe" if header.keyframe else b"delta"
                # Frame metadata (ADR 0120): the source timestamp as an
                # SSE comment — EventSource clients ignore comments, so
                # the data wire is unchanged, but a latency-aware
                # client (the SLO harness, dashboards) reads its
                # freshness without decoding da00.
                meta = (
                    b""
                    if source_ts is None
                    else b": source_ts_ns=%d\n" % source_ts
                )
                self.wfile.write(
                    b"%sid: %d\nevent: %s\ndata: %s\n\n"
                    % (meta, header.seq, kind, base64.b64encode(blob))
                )
                self.wfile.flush()
                last_write = time.monotonic()
        except (BrokenPipeError, ConnectionResetError, OSError):
            # Consumer went away mid-stream: routine, not an error.
            logger.debug("SSE subscriber %d disconnected", sub.sub_id)
        finally:
            hub.unsubscribe(sub)

    def log_message(self, format: str, *args) -> None:  # noqa: A002
        logger.debug("serving http: " + format, *args)

"""ServingPlane: the processor-facing entry to the fan-out tier.

The OrchestratingProcessor's publish path calls
:meth:`ServingPlane.publish_results` (duck-typed ``result_fanout``
hook) with the same finalized :class:`~..core.job.JobResult` list it
hands the Kafka sink. Each output is encoded to the EXACT da00 wire
the sink serializer produces — same ``ResultKey`` source name, same
timestamp — so a subscriber's reconstructed frame is byte-identical to
what a Kafka consumer of that publish would read (the acceptance
contract, pinned in tests/serving/fanout_integration_test.py).

Epoch token per (job, output): the output's structural layout (variable
names, shapes, dtypes, axes — a projection/layout swap changes it) plus
the job's ``state_epoch`` (core/job.py — bumped on clear/reset and on a
``state_lost`` donation failure). Either changing forces the delta
codec onto a keyframe with a bumped epoch, so no delta ever splices
across state generations.

Containment: one output failing to encode loses only that output's
frame for that tick (logged), mirroring the sink's per-message
serialization containment — the fan-out hook must never take the step
worker down.

``get_or_create_plane`` keys planes by requested port so a process
that builds services repeatedly (tests driving ``main()``) reuses its
listener instead of failing the second bind — the core/service.py
``_metrics_servers`` rule.
"""

from __future__ import annotations

import logging
import threading

import numpy as np

from ..kafka.da00_compat import dataarray_to_da00
from ..kafka.wire import encode_da00
from ..telemetry.e2e import observe_stage
from .broadcast import BroadcastServer, stream_key
from .result_cache import ResultCache

__all__ = ["ServingPlane", "get_or_create_plane"]

logger = logging.getLogger(__name__)

#: getattr sentinel: "the result type has no source_ts_ns at all"
#: (bespoke/test doubles) — distinct from a real None (no data time).
_NO_SOURCE_TS = object()


class ServingPlane:
    """ResultCache + BroadcastServer behind the processor hook."""

    def __init__(
        self,
        *,
        port: int | None = None,
        host: str = "0.0.0.0",
        ring: int = 8,
        queue_limit: int = 32,
        name: str = "serving",
        heartbeat_s: float = 10.0,
        hop: int = 0,
    ) -> None:
        self.cache = ResultCache(ring=ring)
        self.server = BroadcastServer(
            cache=self.cache,
            port=port,
            host=host,
            queue_limit=queue_limit,
            name=name,
            heartbeat_s=heartbeat_s,
            hop=hop,
        )
        #: True after close(): the reuse table must not hand a plane
        #: with a dead listener to a later service build.
        self.closed = False

    @property
    def port(self) -> int | None:
        return self.server.port

    # -- processor hook ----------------------------------------------------
    def publish_results(self, results, timestamp) -> None:
        """Fan one publish tick's finalized results out. Runs on the
        service/step worker right after the sink publish; everything
        here is bounded host work (one da00 encode + one delta encode
        per output, one bounded enqueue per subscriber)."""
        ts = timestamp.ns
        window_source_ts: int | None = None
        for result in results:
            job = (
                f"{result.job_id.source_name}:{result.job_id.job_number}"
            )
            state_epoch = getattr(result, "state_epoch", 0)
            # The e2e anchor rides the result (ADR 0120). Distinguish
            # "bespoke result object without the attribute" (fall back
            # to the publish data timestamp) from a real JobResult
            # whose window carried NO data time (source_ts_ns is None):
            # the latter must stay None — an invented latency is worse
            # than a missing sample (telemetry/e2e.py), and the
            # freshness gauge must not report a dataless flush as
            # perfectly fresh.
            source_ts = getattr(result, "source_ts_ns", _NO_SOURCE_TS)
            if source_ts is _NO_SOURCE_TS:
                source_ts = ts
            if source_ts is not None and (
                window_source_ts is None or source_ts > window_source_ts
            ):
                window_source_ts = source_ts
            for key, da in zip(
                result.keys(), result.outputs.values(), strict=True
            ):
                try:
                    variables = dataarray_to_da00(da)
                    token = (
                        state_epoch,
                        tuple(
                            (
                                v.name,
                                tuple(np.asarray(v.data).shape),
                                str(np.asarray(v.data).dtype),
                                tuple(v.axes),
                            )
                            for v in variables
                        ),
                    )
                    frame = encode_da00(key.to_string(), ts, variables)
                    self.server.publish_frame(
                        stream_key(job, key.output_name),
                        frame,
                        token,
                        source_ts_ns=source_ts,
                    )
                except Exception:
                    logger.exception(
                        "fan-out encode failed for %s/%s",
                        job,
                        key.output_name,
                    )
        # One boundary observation per publish tick (ADR 0120): every
        # output of this window is now delta-encoded and enqueued.
        observe_stage("fanout_encoded", window_source_ts)

    def drop_job(self, job_id) -> int:
        """Drop a removed job's streams (wired to
        ``JobManager.set_retire_observer`` by the processor). Accepts a
        JobId or the already-formatted ``source:job_number`` string."""
        job = (
            job_id
            if isinstance(job_id, str)
            else f"{job_id.source_name}:{job_id.job_number}"
        )
        return self.server.drop_job(job)

    # -- QoS feedback ------------------------------------------------------
    def qos(self) -> dict[str, float | int]:
        """Subscriber count + worst queue pressure for the link
        monitor's fan-out axis (core/link_monitor.py)."""
        return self.server.qos()

    def close(self) -> None:
        self.closed = True
        self.server.close()


#: Planes by REQUESTED port (including 0): repeated service builds in
#: one process reuse their endpoint instead of leaking listeners.
#: Creation kwargs are remembered so a reuse with DIFFERENT settings
#: warns instead of silently dropping them.
_planes: dict[int, tuple[ServingPlane, dict]] = {}
_planes_lock = threading.Lock()


def get_or_create_plane(port: int, **kwargs) -> ServingPlane:
    with _planes_lock:
        entry = _planes.get(int(port))
        if entry is not None and entry[0].closed:
            # A closed plane's listener is dead: handing it out would
            # silently run the new service without the fan-out endpoint
            # — the exact dark-launch the loud-bind rule forbids.
            entry = None
        if entry is None:
            plane = ServingPlane(port=int(port), **kwargs)
            _planes[int(port)] = (plane, dict(kwargs))
            return plane
        plane, created_kwargs = entry
        if kwargs != created_kwargs:
            # Two services sharing one requested port share ONE plane
            # (their streams merge on one endpoint; job ids keep them
            # distinct) — but the second caller's settings do not
            # apply, which an operator should see, not guess.
            logger.warning(
                "serving plane on port %s reused with different "
                "settings %r (created with %r); the original settings "
                "stay in effect",
                port,
                kwargs,
                created_kwargs,
            )
        return plane

"""The /metrics plane: scrape + liveness over real HTTP, validated with
the in-tree promtext parser (what CI's metrics smoke runs against a
live service)."""

from __future__ import annotations

import json
import urllib.error
import urllib.request

import pytest

from esslivedata_tpu.telemetry import (
    MetricsRegistry,
    MetricsServer,
    parse_prometheus_text,
    start_metrics_server,
)


@pytest.fixture()
def server():
    registry = MetricsRegistry()
    c = registry.counter("livedata_test_ticks", "ticks", labelnames=("site",))
    c.inc(3, site="tick")
    srv = MetricsServer(0, host="127.0.0.1", registry=registry)
    try:
        yield srv
    finally:
        srv.close()


def fetch(server: MetricsServer, path: str):
    return urllib.request.urlopen(
        f"http://127.0.0.1:{server.port}{path}", timeout=5
    )


class TestMetricsPlane:
    def test_metrics_scrape_parses(self, server):
        response = fetch(server, "/metrics")
        assert response.status == 200
        assert response.headers["Content-Type"].startswith("text/plain")
        parsed = parse_prometheus_text(response.read().decode())
        family = parsed["livedata_test_ticks"]
        assert family.kind == "counter"
        assert family.samples == [
            ("livedata_test_ticks_total", {"site": "tick"}, 3.0)
        ]

    def test_healthz(self, server):
        response = fetch(server, "/healthz")
        assert response.status == 200
        assert json.loads(response.read()) == {"status": "ok"}

    def test_unknown_path_404(self, server):
        with pytest.raises(urllib.error.HTTPError) as err:
            fetch(server, "/nope")
        assert err.value.code == 404

    def test_start_metrics_server_none_port_is_noop(self):
        assert start_metrics_server(None) is None

    def test_concurrent_scrapes(self, server):
        import threading

        payloads = []
        lock = threading.Lock()

        def scrape():
            body = fetch(server, "/metrics").read().decode()
            with lock:
                payloads.append(body)

        threads = [threading.Thread(target=scrape) for _ in range(8)]
        for thread in threads:
            thread.start()
        for thread in threads:
            thread.join()
        assert len(payloads) == 8
        for body in payloads:
            parse_prometheus_text(body)


class TestServiceRunnerFlag:
    def test_setup_arg_parser_starts_endpoint_on_metrics_port(self):
        """--metrics-port 0 on the shared parser (every service runner's
        surface) must bring up a live /metrics + /healthz endpoint."""
        from esslivedata_tpu.core import service as service_mod

        parser = service_mod.setup_arg_parser("test")
        parser.parse_args(["--metrics-port", "0"])
        # The table keys by REQUESTED port (0 = ephemeral ask); the
        # bound port lives on the server. A second parse with the same
        # request must REUSE the listener, not leak another one.
        server = service_mod._metrics_servers.get(0)
        assert server is not None, "no metrics server started"
        parser.parse_args(["--metrics-port", "0"])
        assert service_mod._metrics_servers[0] is server
        port = server.port
        body = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=5
        ).read().decode()
        parse_prometheus_text(body)
        health = json.loads(
            urllib.request.urlopen(
                f"http://127.0.0.1:{port}/healthz", timeout=5
            ).read()
        )
        assert health == {"status": "ok"}
        service_mod._metrics_servers.pop(0)
        server.close()

    def test_trace_dump_flag_registers_exit_dump(self, tmp_path):
        from esslivedata_tpu.core import service as service_mod
        from esslivedata_tpu.telemetry import TRACER

        path = tmp_path / "trace.json"
        parser = service_mod.setup_arg_parser("test")
        parser.parse_args(["--trace-dump", str(path)])
        assert str(path) in service_mod._trace_dump_paths
        # The atexit hook is registered; dump directly to verify the
        # ring serializes (exit-time behavior minus the interpreter
        # teardown).
        TRACER.dump(str(path))
        doc = json.loads(path.read_text())
        assert "traceEvents" in doc
        service_mod._trace_dump_paths.discard(str(path))

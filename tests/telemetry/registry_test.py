"""Telemetry registry + exposition: instrument semantics, label
escaping, histogram bucket monotonicity — including under concurrent
writers — and the collector contract (keyed replacement, failure
containment). The in-tree promtext parser is both the test oracle here
and what CI's metrics smoke validates a live scrape with."""

from __future__ import annotations

import threading

import pytest

from esslivedata_tpu.telemetry import (
    MetricsRegistry,
    parse_prometheus_text,
    render_text,
)
from esslivedata_tpu.telemetry.registry import MetricFamily, Sample


class TestInstruments:
    def test_counter_labels_and_totals(self):
        reg = MetricsRegistry()
        c = reg.counter("ticks", "t", labelnames=("site",))
        c.inc(site="a")
        c.inc(2, site="b")
        child = c.labels(site="a")
        child.inc()
        assert c.value(site="a") == 2
        assert c.total() == 4

    def test_counter_rejects_negative_and_label_mismatch(self):
        reg = MetricsRegistry()
        c = reg.counter("ticks", "t", labelnames=("site",))
        with pytest.raises(ValueError):
            c.inc(-1, site="a")
        with pytest.raises(ValueError):
            c.inc(wrong="a")
        # The hot-path bound child enforces monotonicity too — a
        # negative delta must never silently decrease the series.
        with pytest.raises(ValueError):
            c.labels(site="a").inc(-1)

    def test_counter_named_total_does_not_double_suffix(self):
        """A counter whose NAME already carries the conventional
        ``_total`` (livedata_jit_compiles_total) must expose that exact
        series — a naive suffix append would publish ``..._total_total``
        and every documented query would return no data."""
        reg = MetricsRegistry()
        c = reg.counter("compiles_total", "compiles", labelnames=("site",))
        c.inc(site="tick")
        text = render_text(reg.collect())
        assert "compiles_total{" in text
        assert "compiles_total_total" not in text
        parsed = parse_prometheus_text(text)
        assert parsed["compiles_total"].samples == [
            ("compiles_total", {"site": "tick"}, 1.0)
        ]

    def test_get_or_create_is_idempotent_and_type_checked(self):
        reg = MetricsRegistry()
        a = reg.counter("ticks", "t", labelnames=("site",))
        assert reg.counter("ticks", "t", labelnames=("site",)) is a
        with pytest.raises(TypeError):
            reg.gauge("ticks", "t")
        with pytest.raises(TypeError):
            reg.counter("ticks", "t", labelnames=("other",))

    def test_histogram_buckets_fixed_and_validated(self):
        reg = MetricsRegistry()
        with pytest.raises(ValueError):
            reg.histogram("h", "h", buckets=(0.1, 0.1))
        with pytest.raises(ValueError):
            reg.histogram("h2", "h", buckets=(0.2, 0.1))
        # Bucket layout is part of the wire contract: a re-registration
        # asking for a DIFFERENT layout fails loudly instead of
        # silently observing into the first caller's buckets.
        reg.histogram("h4", "h", buckets=(0.01, 0.1))
        with pytest.raises(TypeError):
            reg.histogram("h4", "h", buckets=(1.0, 5.0))
        assert reg.histogram("h4", "h", buckets=(0.01, 0.1)) is not None
        h = reg.histogram("h3", "h", buckets=(0.01, 0.1, 1.0))
        h.observe(0.005)
        h.observe(0.05)
        h.observe(50.0)  # above every bound -> +Inf only
        assert h.count() == 3
        assert h.sum() == pytest.approx(50.055)

    def test_gauge_set_inc_dec(self):
        reg = MetricsRegistry()
        g = reg.gauge("depth", "d", labelnames=("stage",))
        g.set(3, stage="decode")
        g.inc(stage="decode")
        g.dec(2, stage="decode")
        assert g.value(stage="decode") == 2


class TestExposition:
    def test_render_parse_roundtrip_with_hostile_labels(self):
        reg = MetricsRegistry()
        c = reg.counter("msgs", "messages", labelnames=("src",))
        hostile = 'quote:" backslash:\\ newline:\nend'
        c.inc(3, src=hostile)
        text = render_text(reg.collect())
        parsed = parse_prometheus_text(text)
        samples = parsed["msgs"].samples
        assert any(
            labels.get("src") == hostile and value == 3
            for _name, labels, value in samples
        )

    def test_histogram_exposition_is_cumulative_and_closed(self):
        reg = MetricsRegistry()
        h = reg.histogram("lat", "latency", buckets=(0.01, 0.1))
        for v in (0.005, 0.005, 0.05, 5.0):
            h.observe(v)
        text = render_text(reg.collect())
        parsed = parse_prometheus_text(text)  # validates monotonicity
        rows = {
            labels["le"]: value
            for name, labels, value in parsed["lat"].samples
            if name.endswith("_bucket")
        }
        assert rows == {"0.01": 2, "0.1": 3, "+Inf": 4}
        counts = [
            value
            for name, _labels, value in parsed["lat"].samples
            if name.endswith("_count")
        ]
        assert counts == [4]

    def test_non_finite_values_render_as_spec_literals(self):
        """One inf/NaN sample must render ('+Inf'/'-Inf'/'NaN'), never
        raise — a crash here would 500 every later /metrics scrape."""
        reg = MetricsRegistry()
        g = reg.gauge("edges", "edge values", labelnames=("kind",))
        g.set(float("inf"), kind="pos")
        g.set(float("-inf"), kind="neg")
        g.set(float("nan"), kind="nan")
        text = render_text(reg.collect())
        assert 'edges{kind="pos"} +Inf' in text
        assert 'edges{kind="neg"} -Inf' in text
        assert 'edges{kind="nan"} NaN' in text
        parsed = parse_prometheus_text(text)
        values = {
            labels["kind"]: value
            for _n, labels, value in parsed["edges"].samples
        }
        assert values["pos"] == float("inf")
        assert values["nan"] != values["nan"]  # NaN round-trips

    def test_empty_family_still_exposes_header(self):
        reg = MetricsRegistry()
        reg.gauge("hbm_bytes", "per-device HBM")
        text = render_text(reg.collect())
        assert "# HELP hbm_bytes per-device HBM" in text
        assert "# TYPE hbm_bytes gauge" in text
        assert "hbm_bytes" in parse_prometheus_text(text)

    def test_same_named_families_merge_into_one_header(self):
        """Two keyed collectors legitimately emit ONE family split only
        by labels (two services' pipeline depths); the text format
        allows exactly one HELP/TYPE line per name — real scrapers
        reject a duplicate TYPE line, so render_text must merge."""
        reg = MetricsRegistry()
        for service in ("det", "mon"):
            reg.register_collector(
                f"svc:{service}",
                lambda service=service: [
                    MetricFamily(
                        "queue_depth",
                        "gauge",
                        "queued windows",
                        [Sample("", (("service", service),), 2.0)],
                    )
                ],
            )
        text = render_text(reg.collect())
        assert text.count("# TYPE queue_depth gauge") == 1
        parsed = parse_prometheus_text(text)
        services = {
            labels["service"]
            for _n, labels, _v in parsed["queue_depth"].samples
        }
        assert services == {"det", "mon"}

    def test_parser_rejects_non_monotone_buckets(self):
        bad = (
            "# TYPE lat histogram\n"
            'lat_bucket{le="0.01"} 5\n'
            'lat_bucket{le="0.1"} 3\n'
            'lat_bucket{le="+Inf"} 5\n'
            "lat_count 5\n"
        )
        with pytest.raises(ValueError, match="non-monotone"):
            parse_prometheus_text(bad)

    def test_parser_rejects_missing_inf_bucket(self):
        bad = (
            "# TYPE lat histogram\n"
            'lat_bucket{le="0.01"} 5\n'
        )
        with pytest.raises(ValueError, match=r"\+Inf"):
            parse_prometheus_text(bad)

    def test_exposition_correct_under_concurrent_writers(self):
        """The satellite pin: scrapes racing hot-path writers must
        always render a PARSEABLE, internally consistent payload —
        cumulative buckets monotone, +Inf == _count per labelset —
        never a torn histogram row."""
        reg = MetricsRegistry()
        h = reg.histogram(
            "lat", "latency", labelnames=("site",), buckets=(0.001, 0.01, 0.1)
        )
        c = reg.counter("ops", "ops", labelnames=("site",))
        stop = threading.Event()

        def writer(site: str) -> None:
            child_h = h.labels(site=site)
            child_c = c.labels(site=site)
            i = 0
            while not stop.is_set():
                child_h.observe((i % 7) * 0.003)
                child_c.inc()
                i += 1

        writers = [
            threading.Thread(target=writer, args=(s,))
            for s in ("tick", "publish", 'odd"site\n')
        ]
        for thread in writers:
            thread.start()
        failures = []
        try:
            for _ in range(200):
                text = render_text(reg.collect())
                try:
                    parse_prometheus_text(text)  # monotone + closed
                except ValueError as err:
                    failures.append(str(err))
                    break
        finally:
            stop.set()
            for thread in writers:
                thread.join()
        assert not failures, failures
        # Final state is quiescent: +Inf == count for every labelset.
        parsed = parse_prometheus_text(render_text(reg.collect()))
        for site in ("tick", "publish"):
            assert h.count(site=site) > 0
        assert parsed["ops"].kind == "counter"


class TestCollectors:
    def test_keyed_registration_replaces(self):
        reg = MetricsRegistry()
        reg.register_collector(
            "svc", lambda: [MetricFamily("a", "gauge", "a")]
        )
        reg.register_collector(
            "svc", lambda: [MetricFamily("b", "gauge", "b")]
        )
        names = [f.name for f in reg.collect()]
        assert "b" in names and "a" not in names
        reg.unregister_collector("svc")
        assert [f.name for f in reg.collect()] == []

    def test_owner_guarded_unregister_spares_the_successor(self):
        """A predecessor's late shutdown must not delete the collector
        that REPLACED its registration under the same key."""
        reg = MetricsRegistry()

        class Producer:
            def __init__(self, name):
                self.name = name

            def families(self):
                return [MetricFamily(self.name, "gauge", self.name)]

        a, b = Producer("a"), Producer("b")
        reg.register_collector("svc", a.families)
        reg.register_collector("svc", b.families)  # replacement
        reg.unregister_collector("svc", a.families)  # late A shutdown
        assert [f.name for f in reg.collect()] == ["b"]
        reg.unregister_collector("svc", b.families)
        assert reg.collect() == []

    def test_failing_collector_contained(self):
        reg = MetricsRegistry()

        def boom():
            raise RuntimeError("dead producer")

        reg.register_collector("bad", boom)
        reg.register_collector(
            "good",
            lambda: [
                MetricFamily(
                    "ok", "gauge", "ok", [Sample("", (), 1.0)]
                )
            ],
        )
        families = reg.collect()
        assert [f.name for f in families] == ["ok"]

    def test_snapshot_compact_drops_buckets(self):
        reg = MetricsRegistry()
        h = reg.histogram("lat", "l", buckets=(0.1,))
        h.observe(0.05)
        full = reg.snapshot()
        compact = reg.snapshot(compact=True)
        assert any(k.startswith("_bucket") for k in full["lat"])
        assert not any(k.startswith("_bucket") for k in compact["lat"])
        assert compact["lat"]["_count"] == 1

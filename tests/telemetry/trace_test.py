"""TickTracer: trace-id lifecycle, cross-thread span correlation,
Chrome trace_event export, ring bound, slow-tick watchdog latch, and
the compile-event recorder's trigger classification."""

from __future__ import annotations

import json
import threading

from esslivedata_tpu.telemetry import CompileEventRecorder, TickTracer


def make_tracer(**kwargs) -> TickTracer:
    kwargs.setdefault("enabled", True)
    kwargs.setdefault("slow_tick_s", 0.25)
    return TickTracer(**kwargs)


class TestSpans:
    def test_spans_share_the_window_trace_id_across_threads(self):
        """The correlation contract: decode on one worker, tick/fetch
        on another, all against the id allocated at decode."""
        tracer = make_tracer()
        trace_id = tracer.new_trace()
        with tracer.span("decode", trace_id):
            pass

        def step_worker() -> None:
            with tracer.bind(trace_id):
                with tracer.span("tick_execute"):
                    pass
                with tracer.span("fetch"):
                    pass

        thread = threading.Thread(target=step_worker)
        thread.start()
        thread.join()
        spans = tracer.spans(trace_id)
        assert [s.name for s in spans] == ["decode", "tick_execute", "fetch"]
        assert {s.trace_id for s in spans} == {trace_id}
        assert len({s.thread for s in spans}) == 2

    def test_bind_restores_previous_trace(self):
        tracer = make_tracer()
        outer, inner = tracer.new_trace(), tracer.new_trace()
        tracer.set_current(outer)
        with tracer.bind(inner):
            assert tracer.current() == inner
        assert tracer.current() == outer

    def test_disabled_tracer_records_nothing(self):
        tracer = TickTracer(enabled=False)
        trace_id = tracer.new_trace()
        with tracer.span("decode", trace_id):
            pass
        tracer.record("fetch", 0.0, 1.0, trace_id)
        assert tracer.spans() == []
        tracer.finish_tick(trace_id, 100.0)
        assert tracer.slow_ticks == 0

    def test_ring_is_bounded(self):
        tracer = make_tracer(capacity=8)
        trace_id = tracer.new_trace()
        for i in range(100):
            tracer.record(f"s{i}", 0.0, 0.001, trace_id)
        spans = tracer.spans()
        assert len(spans) == 8
        assert spans[-1].name == "s99"

    def test_untraced_span_skips_ring(self):
        tracer = make_tracer()
        tracer.set_current(None)
        with tracer.span("decode"):
            pass
        assert tracer.spans() == []


class TestChromeExport:
    def test_chrome_trace_loads_and_groups_by_trace_id(self, tmp_path):
        tracer = make_tracer()
        t1, t2 = tracer.new_trace(), tracer.new_trace()
        for trace_id in (t1, t2):
            for name in ("decode", "prestage", "tick_execute", "fetch"):
                tracer.record(name, 0.001, 0.002, trace_id)
        path = tmp_path / "trace.json"
        tracer.dump(str(path))
        doc = json.loads(path.read_text())
        events = doc["traceEvents"]
        assert len(events) == 8
        # Chrome trace_event contract: complete events with microsecond
        # timestamps, one pid per window so the viewer groups spans.
        for event in events:
            assert event["ph"] == "X"
            assert event["dur"] == 2000.0
            assert event["pid"] in (t1, t2)
        names_t1 = [e["name"] for e in events if e["pid"] == t1]
        assert names_t1 == ["decode", "prestage", "tick_execute", "fetch"]


class TestWatchdog:
    def test_slow_tick_logs_breakdown_and_latches(self, caplog):
        tracer = make_tracer(slow_tick_s=0.1)
        trace_id = tracer.new_trace()
        tracer.record("fetch", 0.0, 0.19, trace_id)
        with caplog.at_level("WARNING", logger="esslivedata_tpu.telemetry.trace"):
            tracer.finish_tick(trace_id, 0.2)
        assert tracer.slow_ticks == 1
        assert "slow tick" in caplog.text
        assert "fetch" in caplog.text
        # Latched onto the triggering duration: an equally slow tick
        # does NOT re-log; a slower one does.
        tracer.finish_tick(tracer.new_trace(), 0.2)
        assert tracer.slow_ticks == 1
        tracer.finish_tick(tracer.new_trace(), 0.5)
        assert tracer.slow_ticks == 2

    def test_breakdown_sums_repeated_span_names(self, caplog):
        """A window records one tick_execute/fetch pair PER tick group
        (and per mesh slice): the watchdog breakdown must aggregate
        them, not keep only the last — otherwise a tick dominated by
        four 50 ms fetches logs 'fetch: 50'."""
        tracer = make_tracer(slow_tick_s=0.1)
        trace_id = tracer.new_trace()
        for _ in range(4):
            tracer.record("fetch", 0.0, 0.05, trace_id)
        with caplog.at_level(
            "WARNING", logger="esslivedata_tpu.telemetry.trace"
        ):
            tracer.finish_tick(trace_id, 0.21)
        assert "200.0ms/4x" in caplog.text

    def test_latch_decays_back_toward_floor(self):
        tracer = make_tracer(slow_tick_s=0.1)
        tracer.finish_tick(tracer.new_trace(), 10.0)
        assert tracer.slow_ticks == 1
        # Healthy ticks decay the latch (0.95^n); after enough of them
        # a 0.2 s tick trips again even though 10 s once latched.
        for _ in range(200):
            tracer.finish_tick(tracer.new_trace(), 0.01)
        tracer.finish_tick(tracer.new_trace(), 0.2)
        assert tracer.slow_ticks == 2


class TestCompileClassification:
    def test_trigger_taxonomy(self):
        rec = CompileEventRecorder()
        group = ("hist", ("pub",))
        base = dict(layout_digest="d1", wire="wide", staged_sig="s1")
        assert rec.classify("tick", group, **base) == "new_group"
        assert (
            rec.classify("tick", group, **{**base, "layout_digest": "d2"})
            == "layout_swap"
        )
        assert (
            rec.classify(
                "tick",
                group,
                **{**base, "layout_digest": "d2", "wire": "compact"},
            )
            == "wire_flip"
        )
        assert (
            rec.classify(
                "tick",
                group,
                layout_digest="d2",
                wire="compact",
                staged_sig="s2",
            )
            == "batch_shape"
        )
        assert (
            rec.classify(
                "tick",
                group,
                layout_digest="d2",
                wire="compact",
                staged_sig="s2",
                residual="tag-b",
            )
            == "regroup"
        )
        # Byte-identical key missing anyway = LRU eviction recompile.
        assert (
            rec.classify(
                "tick",
                group,
                layout_digest="d2",
                wire="compact",
                staged_sig="s2",
                residual="tag-b",
            )
            == "evicted"
        )
        # Sites are independent: the same group is new at another site.
        assert rec.classify("publish", group, **base) == "new_group"

    def test_memory_is_bounded(self):
        rec = CompileEventRecorder()
        for i in range(rec._MEMORY_MAX + 10):
            rec.classify("tick", f"group-{i}")
        assert len(rec._memory) == rec._MEMORY_MAX
        # The evicted earliest group classifies as new again.
        assert rec.classify("tick", "group-0") == "new_group"


class TestExportConsistency:
    """The ring-export contract (ADR 0120 satellite): every exporter
    reads ONE snapshot under the lock, so concurrent writers trimming
    the ring can never make an export drop spans it promised."""

    def test_export_is_one_consistent_snapshot(self, tmp_path):
        tracer = make_tracer(capacity=100_000)
        stop = threading.Event()
        recorded = []

        def writer(worker: int) -> None:
            trace_id = tracer.new_trace()
            n = 0
            while not stop.is_set():
                tracer.record(f"w{worker}", 0.0, 1e-6, trace_id)
                n += 1
            recorded.append(n)

        def exporter() -> None:
            last = 0
            while not stop.is_set():
                snapshot = tracer.export()
                doc = tracer.chrome_trace(snapshot)
                # Payload and snapshot describe the SAME ring state.
                assert len(doc["traceEvents"]) == len(snapshot)
                # While the ring is not full, exports only grow: a
                # shrink means a snapshot raced a concurrent trim.
                assert len(snapshot) >= last
                last = len(snapshot)

        writers = [
            threading.Thread(target=writer, args=(i,)) for i in range(4)
        ]
        export_threads = [
            threading.Thread(target=exporter) for _ in range(2)
        ]
        for t in writers + export_threads:
            t.start()
        import time as _time

        _time.sleep(0.3)
        stop.set()
        for t in writers + export_threads:
            t.join()
        # Hammer postcondition: nothing below capacity was lost — the
        # final export holds every span every writer recorded.
        assert sum(recorded) <= 100_000, "raise capacity for this test"
        assert len(tracer.export()) == sum(recorded)

    def test_spans_recorded_before_export_always_appear(self):
        tracer = make_tracer(capacity=4096)
        trace_id = tracer.new_trace()
        tracer.record("landed", 0.0, 1e-6, trace_id)
        names = {s.name for s in tracer.export()}
        assert "landed" in names

    def test_dump_count_matches_payload(self, tmp_path, caplog):
        import logging

        tracer = make_tracer()
        trace_id = tracer.new_trace()
        for _ in range(5):
            tracer.record("phase", 0.0, 1e-6, trace_id)
        path = tmp_path / "trace.json"
        with caplog.at_level(logging.INFO):
            tracer.dump(str(path))
        doc = json.loads(path.read_text())
        assert len(doc["traceEvents"]) == 5
        assert "5 spans" in caplog.text


class TestWatchdogLatchSignal:
    def test_latched_between_breach_and_decay(self):
        tracer = make_tracer(slow_tick_s=0.1)
        assert not tracer.watchdog_latched
        trace_id = tracer.new_trace()
        tracer.finish_tick(trace_id, 0.5)  # breach: latch to 0.5
        assert tracer.watchdog_latched
        # Healthy ticks decay the latch back toward the floor
        # (0.95^n); latched stays True until the floor is reached.
        for _ in range(50):
            tracer.finish_tick(tracer.new_trace(), 0.01)
        assert not tracer.watchdog_latched

"""E2E source-timestamp propagation (ADR 0120): ONE ev44 reference
time, injected at the fake Kafka edge, must survive decode -> tick ->
sink publish -> SSE frame BYTE-EXACTLY — serial AND pipelined — and
the latency instrumentation along the way must never perturb the wire
(telemetry on vs off byte-identical, serving plane attached)."""

from __future__ import annotations

import json
import uuid

import numpy as np
import pytest

from esslivedata_tpu.config import JobId, WorkflowConfig
from esslivedata_tpu.config.instruments.dummy.specs import (
    DETECTOR_VIEW_HANDLE,
    INSTRUMENT,
)
from esslivedata_tpu.core.message_batcher import NaiveMessageBatcher
from esslivedata_tpu.kafka import wire
from esslivedata_tpu.kafka.sink import (
    FakeProducer,
    KafkaSink,
    make_default_serializer,
)
from esslivedata_tpu.kafka.source import FakeKafkaMessage
from esslivedata_tpu.serving import DeltaDecoder, ServingPlane
from esslivedata_tpu.services.detector_data import make_detector_service_builder
from esslivedata_tpu.services.fake_sources import PulsedRawSource
from esslivedata_tpu.telemetry import TRACER
from esslivedata_tpu.telemetry.e2e import E2E_LATENCY

BASE_NS = 1_700_000_000_000_000_000
PERIOD_NS = int(1e9 / 14)


def run_service(*, pipelined: bool, subscribe_at: int = 4):
    """Drive a real detector service over fakes with a hub-only
    ServingPlane attached; returns (sink data messages, plane,
    subscription, pulse reference times)."""
    builder = make_detector_service_builder(
        instrument="dummy", batcher=NaiveMessageBatcher(), job_threads=1
    )
    builder.pipelined = pipelined
    raw = PulsedRawSource([])
    producer = FakeProducer()
    sink = KafkaSink(
        producer,
        make_default_serializer(builder.stream_mapping.livedata, "e2e"),
    )
    service = builder.from_raw_source(raw, sink)
    plane = ServingPlane(port=None)
    # The processor hook the service factory wires for --serve-port;
    # hub-only here (no HTTP) — subscribe() IS the SSE handler's API.
    service.processor._result_fanout = plane
    config = WorkflowConfig(
        identifier=DETECTOR_VIEW_HANDLE.workflow_id,
        job_id=JobId(source_name="panel_0", job_number=uuid.UUID(int=9)),
        params={},
    )
    raw.inject(
        FakeKafkaMessage(
            json.dumps(
                {"kind": "start_job", "config": config.model_dump(mode="json")}
            ).encode(),
            "dummy_livedata_commands",
        )
    )
    service.step()
    det = INSTRUMENT.detectors["panel_0"]
    ids_space = det.detector_number.reshape(-1)
    rng = np.random.default_rng(23)
    sub = None
    pulse_times = []
    for pulse in range(10):
        t_pulse = BASE_NS + pulse * PERIOD_NS
        pulse_times.append(t_pulse)
        ids = rng.choice(ids_space, 256).astype(np.int32)
        toa = rng.uniform(0, 7.0e7, 256).astype(np.int32)
        payload = wire.encode_ev44(
            det.source_name,
            pulse,
            np.array([t_pulse]),
            np.array([0]),
            toa,
            pixel_id=ids,
        )
        raw.inject(FakeKafkaMessage(payload, "dummy_detector"))
        service.step()
        if pulse == subscribe_at:
            if pipelined:
                # The hub learns streams as publishes land on the step
                # worker; wait for the in-flight windows first.
                assert service.processor._pipeline.flush(timeout=60.0)
            streams = sorted(plane.cache.streams())
            target = next(
                s for s in streams if s.endswith("/image_cumulative")
            )
            sub = plane.server.subscribe(target)
    processor = service.processor
    if pipelined:
        assert processor._pipeline.flush(timeout=60.0)
    processor.finalize()
    data = [
        m
        for m in producer.messages
        if m.key is not None
        and (b"image" in m.key or b"spectrum" in m.key)
    ]
    return data, plane, sub, pulse_times


def reconstruct(sub) -> bytes:
    """Drain an SSE subscription's queue through the delta codec."""
    decoder = DeltaDecoder()
    frame = None
    while sub.depth() > 0:
        blob = sub.next_blob(timeout=1.0)
        assert blob is not None
        frame = decoder.apply(blob)
    assert frame is not None, "subscriber received nothing"
    return frame


@pytest.mark.parametrize("pipelined", [False, True])
class TestSourceTimestampSurvives:
    def test_reference_time_reaches_sse_frame_byte_exactly(
        self, pipelined
    ):
        stage_counts0 = {
            stage: E2E_LATENCY.count(stage=stage)
            for stage in (
                "decode",
                "staged",
                "published",
                "fanout_encoded",
                "subscriber_delivered",
            )
        }
        data, plane, sub, pulse_times = run_service(pipelined=pipelined)
        try:
            assert sub is not None
            frame = reconstruct(sub)
            decoded = wire.decode_da00(frame)
            # THE contract: the frame's timestamp is the window-end
            # DATA time — a pure function of the last injected ev44
            # reference time (batcher pulse quantization, no wall
            # clock anywhere on the way) — byte-exactly.
            from esslivedata_tpu.core.timestamp import Timestamp

            hi = Timestamp.from_ns(pulse_times[-1])
            end = hi.quantize_up()
            if end == hi:
                end = Timestamp.from_pulse_index(hi.pulse_index() + 1)
            assert decoded.timestamp_ns == end.ns
            # ...and it stays within one pulse of the reference time:
            # the source clock, not a republished wall clock.
            assert 0 <= decoded.timestamp_ns - pulse_times[-1] <= PERIOD_NS
            # And the SSE frame is the sink wire: the exact bytes a
            # Kafka consumer of the same publish read.
            sink_match = [
                m
                for m in data
                if m.value == frame and b"image_cumulative" in m.key
            ]
            assert sink_match, (
                "SSE reconstruction != any sink-published da00 message"
            )
            # Every boundary observed the window: the histogram counted
            # each stage (staged is pipelined-only by design).
            for stage in ("decode", "published", "fanout_encoded"):
                assert (
                    E2E_LATENCY.count(stage=stage) > stage_counts0[stage]
                ), stage
            assert (
                E2E_LATENCY.count(stage="subscriber_delivered")
                > stage_counts0["subscriber_delivered"]
            )
            staged_delta = (
                E2E_LATENCY.count(stage="staged")
                - stage_counts0["staged"]
            )
            assert (staged_delta > 0) == pipelined
        finally:
            plane.close()


class TestWireParityTelemetryOnOffWithPlane:
    @pytest.mark.parametrize("pipelined", [False, True])
    def test_wire_and_sse_frames_byte_identical(self, pipelined):
        """Telemetry on (tracer + e2e instrumentation recording) vs
        off: the sink wire AND the SSE reconstruction are byte-for-byte
        the same — the SLO plane observes the path, never perturbs it."""
        TRACER.enabled = True
        try:
            on, plane_on, sub_on, _ = run_service(pipelined=pipelined)
            frame_on = reconstruct(sub_on)
            plane_on.close()
            TRACER.enabled = False
            off, plane_off, sub_off, _ = run_service(pipelined=pipelined)
            frame_off = reconstruct(sub_off)
            plane_off.close()
        finally:
            TRACER.enabled = True
        assert len(on) == len(off) > 0
        assert [m.key for m in on] == [m.key for m in off]
        assert [m.value for m in on] == [m.value for m in off]
        assert frame_on == frame_off

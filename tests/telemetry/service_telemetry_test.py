"""Flight-recorder acceptance at the service level (ADR 0116):

- one scrape of a running service's registry exposes the publish
  dispatch counters (incl. the per-slice family), publish RTT
  histograms, pipeline queue depths, kafka/stream counters, HBM gauges
  and the jit compile-event histograms;
- the per-window trace correlates decode → prestage → tick_execute →
  fetch spans under shared trace ids and loads as Chrome trace_event;
- the da00 wire is byte-identical with telemetry on vs off (tracer
  enabled + scrapes racing the run vs tracer disabled) — the flight
  recorder observes the serving path, it must never perturb it.
"""

from __future__ import annotations

import json
import uuid

import numpy as np
import pytest

from esslivedata_tpu.config import JobId, WorkflowConfig
from esslivedata_tpu.config.instruments.dummy.specs import (
    DETECTOR_VIEW_HANDLE,
    INSTRUMENT,
)
from esslivedata_tpu.core.message_batcher import NaiveMessageBatcher
from esslivedata_tpu.kafka import wire
from esslivedata_tpu.kafka.sink import (
    FakeProducer,
    KafkaSink,
    make_default_serializer,
)
from esslivedata_tpu.kafka.source import FakeKafkaMessage
from esslivedata_tpu.services.detector_data import make_detector_service_builder
from esslivedata_tpu.services.fake_sources import PulsedRawSource
from esslivedata_tpu.telemetry import (
    REGISTRY,
    TRACER,
    parse_prometheus_text,
    render_text,
)


def run_service(*, pipelined: bool, scrape_every: int = 0):
    """Drive a real detector service over fakes; returns (data messages,
    scrapes collected mid-run)."""
    builder = make_detector_service_builder(
        instrument="dummy", batcher=NaiveMessageBatcher(), job_threads=1
    )
    builder.pipelined = pipelined
    raw = PulsedRawSource([])
    producer = FakeProducer()
    sink = KafkaSink(
        producer,
        make_default_serializer(builder.stream_mapping.livedata, "telem"),
    )
    service = builder.from_raw_source(raw, sink)
    config = WorkflowConfig(
        identifier=DETECTOR_VIEW_HANDLE.workflow_id,
        # Pinned job number: output keys carry it and the on/off runs
        # must be byte-comparable.
        job_id=JobId(source_name="panel_0", job_number=uuid.UUID(int=9)),
        params={},
    )
    raw.inject(
        FakeKafkaMessage(
            json.dumps(
                {"kind": "start_job", "config": config.model_dump(mode="json")}
            ).encode(),
            "dummy_livedata_commands",
        )
    )
    service.step()
    det = INSTRUMENT.detectors["panel_0"]
    ids_space = det.detector_number.reshape(-1)
    rng = np.random.default_rng(11)
    period_ns = int(1e9 / 14)
    scrapes = []
    for pulse in range(10):
        t_pulse = 1_700_000_000_000_000_000 + pulse * period_ns
        ids = rng.choice(ids_space, 256).astype(np.int32)
        toa = rng.uniform(0, 7.0e7, 256).astype(np.int32)
        payload = wire.encode_ev44(
            det.source_name,
            pulse,
            np.array([t_pulse]),
            np.array([0]),
            toa,
            pixel_id=ids,
        )
        raw.inject(FakeKafkaMessage(payload, "dummy_detector"))
        service.step()
        if scrape_every and pulse % scrape_every == 0:
            scrapes.append(render_text(REGISTRY.collect()))
    processor = service.processor
    if pipelined:
        assert processor._pipeline.flush(timeout=60.0)
    processor.finalize()
    data = [
        m
        for m in producer.messages
        if m.key is not None
        and (b"image" in m.key or b"spectrum" in m.key)
    ]
    return data, scrapes


class TestScrapeExposesTheStack:
    def test_one_scrape_carries_every_migrated_producer(self):
        TRACER.enabled = True
        try:
            _data, scrapes = run_service(pipelined=True, scrape_every=3)
        finally:
            TRACER.enabled = True
        assert scrapes
        parsed = parse_prometheus_text(scrapes[-1])
        # The acceptance list: dispatch counters (+ per-slice family),
        # RTT histograms, pipeline queue depths, kafka/stream counts,
        # HBM gauges, compile-event histograms, span decomposition.
        for family in (
            "livedata_publish_events",
            "livedata_publish_slice_events",
            "livedata_publish_rtt_seconds",
            "livedata_pipeline_queue_depth",
            "livedata_pipeline_stage_busy_seconds",
            "livedata_stream_messages",
            "livedata_kafka_sink_events",
            "livedata_hbm_bytes",
            "livedata_jit_compiles_total",
            "livedata_jit_compile_seconds",
            "livedata_tick_span_seconds",
            "livedata_link_rtt_ewma_seconds",
            "livedata_link_policy",
        ):
            assert family in parsed, f"scrape missing {family}"
        # The producers actually produced: compile events fired for the
        # tick program, spans decomposed the windows, the pipeline
        # reported its stages.
        assert parse_one_total(parsed, "livedata_jit_compiles_total") >= 1
        span_names = {
            labels.get("span")
            for _n, labels, _v in parsed["livedata_tick_span_seconds"].samples
        }
        assert {"decode", "prestage", "fetch"} <= span_names
        stages = {
            labels.get("stage")
            for _n, labels, _v in parsed[
                "livedata_pipeline_queue_depth"
            ].samples
        }
        assert {"decode", "stage", "step"} <= stages

    def test_trace_correlates_window_phases(self):
        TRACER.enabled = True
        TRACER.clear()
        run_service(pipelined=True)
        spans = TRACER.spans()
        by_trace: dict[int, list[str]] = {}
        for span in spans:
            by_trace.setdefault(span.trace_id, []).append(span.name)
        # At least one traced window shows the full decode -> prestage
        # -> device tick -> fetch chain under ONE id.
        full = [
            names
            for names in by_trace.values()
            if {"decode", "prestage", "tick_execute", "fetch"} <= set(names)
        ]
        assert full, f"no fully-correlated window: {by_trace}"
        # And the ring exports as Chrome trace_event JSON.
        doc = TRACER.chrome_trace()
        assert {e["name"] for e in doc["traceEvents"]} >= {
            "decode",
            "prestage",
            "tick_execute",
            "fetch",
        }


def parse_one_total(parsed, family: str) -> float:
    return sum(value for _n, _l, value in parsed[family].samples)


class TestWireParityTelemetryOnOff:
    @pytest.mark.parametrize("pipelined", [False, True])
    def test_da00_wire_byte_identical(self, pipelined):
        """Telemetry on (tracer recording + scrapes racing the run) vs
        off: same message keys, same bytes, same order."""
        TRACER.enabled = True
        try:
            on, _ = run_service(pipelined=pipelined, scrape_every=2)
            TRACER.enabled = False
            off, _ = run_service(pipelined=pipelined)
        finally:
            TRACER.enabled = True
        assert len(on) == len(off) > 0
        assert [m.key for m in on] == [m.key for m in off]
        assert [m.value for m in on] == [m.value for m in off]

"""Shared telemetry-suite fixtures."""

from __future__ import annotations

import pytest


@pytest.fixture(autouse=True)
def _clean_process_health_latches():
    """/healthz reads PROCESS state (ADR 0120): earlier service-driving
    suites can leave the slow-tick watchdog latched (a starved CI
    worker breaches it legitimately) or the state-lost window open.
    The telemetry suites assert the plumbing and the latch SEMANTICS —
    start every test from a clean latch, in ONE place (both latches'
    privates are poked here and nowhere else in tests)."""
    from esslivedata_tpu.telemetry import HEALTH, TRACER

    with TRACER._lock:
        TRACER._slow_latch_s = TRACER._slow_floor_s
        TRACER._slow_latched = False
    HEALTH._last_state_lost = None
    yield

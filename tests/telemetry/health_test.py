"""/healthz degraded semantics (ADR 0120): state-lost latch, slow-tick
watchdog latch, and the HTTP surface — always 200, never a restart
loop."""

from __future__ import annotations

import json
import urllib.request

from esslivedata_tpu.telemetry import HEALTH, STATE_LOST, TRACER, HealthState
from esslivedata_tpu.telemetry.http import MetricsServer


class FakeClock:
    def __init__(self) -> None:
        self.now = 100.0

    def __call__(self) -> float:
        return self.now


class TestHealthState:
    def test_ok_by_default(self):
        state = HealthState(clock=FakeClock())
        assert state.healthz() == {"status": "ok"}

    def test_state_lost_degrades_then_recovers(self):
        clock = FakeClock()
        state = HealthState(degraded_window_s=30.0, clock=clock)
        before = STATE_LOST.total()
        state.note_state_lost()
        assert STATE_LOST.total() == before + 1
        payload = state.healthz()
        assert payload["status"] == "degraded"
        assert "state_lost" in payload["reason"]
        # The latch clears once the interval passes — a loss 5 minutes
        # ago is history, not a current condition.
        clock.now += 31.0
        assert state.healthz() == {"status": "ok"}

    def test_watchdog_latch_degrades(self):
        state = HealthState(clock=FakeClock())
        TRACER.enabled = True
        trace_id = TRACER.new_trace()
        floor = TRACER._slow_floor_s
        try:
            TRACER.finish_tick(trace_id, floor * 100)
            payload = state.healthz()
            assert payload["status"] == "degraded"
            assert "watchdog" in payload["reason"]
        finally:
            # Decay the latch fully so later tests see a healthy tracer.
            for _ in range(300):
                TRACER.finish_tick(TRACER.new_trace(), 0.0)
        assert state.healthz() == {"status": "ok"}

    def test_job_note_state_lost_feeds_the_process_latch(self):
        """The single choke point: every JobManager containment site
        goes through Job.note_state_lost (JGL022), which must reach
        the process health latch."""
        from esslivedata_tpu.config.workflow_spec import JobId, WorkflowId

        from esslivedata_tpu.core.job import Job

        class _NullWorkflow:
            def accumulate(self, data):
                pass

            def finalize(self):
                return {}

            def clear(self):
                pass

        job = Job(
            job_id=JobId(source_name="det0"),
            workflow_id=WorkflowId(
                instrument="dummy", namespace="t", name="w", version=1
            ),
            workflow=_NullWorkflow(),
        )
        before = STATE_LOST.total()
        epoch = job.state_epoch
        job.note_state_lost()
        assert job.state_epoch == epoch + 1
        assert STATE_LOST.total() == before + 1
        assert HEALTH.healthz()["status"] == "degraded"
        # Reset the process-wide latch for neighboring tests.
        HEALTH._last_state_lost = None


class TestHealthzEndpoint:
    def test_degraded_is_still_http_200_with_reason(self):
        server = MetricsServer(0, host="127.0.0.1")
        try:
            url = f"http://127.0.0.1:{server.port}/healthz"
            with urllib.request.urlopen(url) as resp:
                assert resp.status == 200
                assert json.loads(resp.read()) == {"status": "ok"}
            HEALTH.note_state_lost()
            with urllib.request.urlopen(url) as resp:
                # STILL 200: degraded must not trip a supervisor's
                # restart probe (a restart loses MORE state).
                assert resp.status == 200
                payload = json.loads(resp.read())
            assert payload["status"] == "degraded"
            assert "state_lost" in payload["reason"]
        finally:
            HEALTH._last_state_lost = None
            server.close()

"""Shared parameter-model vocabulary (reference granularity:
tests/parameter_models_test.py): the free-text list parser, range/edge
validation, log-edge materialization, angle conversion.
"""

import numpy as np
import pytest
from pydantic import ValidationError

from esslivedata_tpu.parameter_models import (
    Angle,
    AngleUnit,
    EdgesModel,
    RangeModel,
    Scale,
    parse_number_list,
)


class TestParseNumberList:
    def test_plain_list(self):
        assert parse_number_list("1, 2.5, -3") == [1.0, 2.5, -3.0]

    def test_blank_is_empty(self):
        assert parse_number_list("") == []
        assert parse_number_list("   ") == []

    def test_scientific_notation(self):
        assert parse_number_list("1e3, 2.5e-2") == [1000.0, 0.025]

    @pytest.mark.parametrize(
        "bad", ["a, b", "1; 2", "1, , 2", "true, 1", '"x"', "[1], 2"]
    )
    def test_non_numbers_rejected(self, bad):
        with pytest.raises(ValueError):
            parse_number_list(bad)

    def test_backs_pydantic_validator(self):
        """The documented use: free-text list input on a model field."""
        from pydantic import BaseModel, field_validator

        class M(BaseModel):
            values: list[float] = []

            @field_validator("values", mode="before")
            @classmethod
            def _parse(cls, v):
                return parse_number_list(v) if isinstance(v, str) else v

        assert M(values="3, 4").values == [3.0, 4.0]
        with pytest.raises(ValidationError):
            M(values="3, x")


class TestRangeModel:
    def test_defaults_valid(self):
        r = RangeModel()
        assert r.stop > r.start

    def test_inverted_rejected(self):
        with pytest.raises(ValidationError, match="greater than start"):
            RangeModel(start=5.0, stop=5.0)
        with pytest.raises(ValidationError):
            RangeModel(start=5.0, stop=1.0)


class TestEdgesModel:
    def test_linear_edges(self):
        m = EdgesModel(start=0.0, stop=10.0, num_bins=5)
        np.testing.assert_allclose(
            m.get_edges(), np.linspace(0.0, 10.0, 6)
        )

    def test_log_edges_geometric(self):
        m = EdgesModel(start=1.0, stop=1000.0, num_bins=3, scale=Scale.LOG)
        np.testing.assert_allclose(m.get_edges(), [1.0, 10.0, 100.0, 1000.0])

    def test_log_requires_positive_start(self):
        with pytest.raises(ValidationError, match="positive"):
            EdgesModel(start=0.0, stop=10.0, scale=Scale.LOG)
        # The same start is fine on a linear scale.
        EdgesModel(start=0.0, stop=10.0, scale=Scale.LINEAR)

    def test_bin_count_bounds(self):
        with pytest.raises(ValidationError):
            EdgesModel(num_bins=0)
        with pytest.raises(ValidationError):
            EdgesModel(num_bins=10_001)
        assert EdgesModel(num_bins=10_000).get_edges().size == 10_001

    def test_inverted_rejected(self):
        with pytest.raises(ValidationError):
            EdgesModel(start=2.0, stop=2.0)


class TestAngle:
    def test_degrees_passthrough(self):
        assert Angle(value=45.0).get_degrees() == 45.0

    def test_radians_converted(self):
        a = Angle(value=np.pi / 2, unit=AngleUnit.RADIAN)
        assert a.get_degrees() == pytest.approx(90.0)

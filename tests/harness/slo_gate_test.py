"""SLO checker semantics (scripts/slo_gate.py, ADR 0120): rule
evaluation (quantiles, aggregates, allow_missing, absent-family
breach), scrape-delta algebra, and the load harness + gate round trip
with the containment-disabled control going red."""

from __future__ import annotations

import importlib.util
from pathlib import Path

import pytest

from esslivedata_tpu.telemetry.exposition import parse_prometheus_text

REPO = Path(__file__).resolve().parent.parent.parent


@pytest.fixture(scope="module")
def slo_gate():
    spec = importlib.util.spec_from_file_location(
        "slo_gate_under_test", REPO / "scripts" / "slo_gate.py"
    )
    module = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(module)
    return module


HIST = """\
# HELP lat_seconds latency
# TYPE lat_seconds histogram
lat_seconds_bucket{stage="deliver",le="0.1"} 90
lat_seconds_bucket{stage="deliver",le="0.5"} 99
lat_seconds_bucket{stage="deliver",le="+Inf"} 100
lat_seconds_sum{stage="deliver"} 12.5
lat_seconds_count{stage="deliver"} 100
"""

COUNTERS = """\
# HELP errors errs
# TYPE errors counter
errors_total{kind="a"} 3
errors_total{kind="b"} 5
# HELP quiet quiet counter
# TYPE quiet counter
# HELP depth depth
# TYPE depth gauge
depth{sub="1"} 4
depth{sub="2"} 9
"""


class TestEvaluation:
    def test_histogram_quantile_interpolates(self, slo_gate):
        fam = parse_prometheus_text(HIST)["lat_seconds"]
        p50 = slo_gate.histogram_quantile(fam, 0.5, {"stage": "deliver"})
        # 50th of 100 falls in the first bucket: 50/90 of [0, 0.1].
        assert p50 == pytest.approx(0.1 * 50 / 90)
        p99 = slo_gate.histogram_quantile(fam, 0.99, {"stage": "deliver"})
        assert 0.1 < p99 <= 0.5
        # The tail sample lands in +Inf: p100 reads as infinity.
        assert slo_gate.histogram_quantile(
            fam, 1.0, {"stage": "deliver"}
        ) == float("inf")

    def test_quantile_rule_breaches_on_budget(self, slo_gate):
        fams = parse_prometheus_text(HIST)
        rule = {
            "metric": "lat_seconds",
            "labels": {"stage": "deliver"},
            "agg": "p99",
            "op": "<=",
            "value": 0.05,
        }
        passed, observed, _ = slo_gate.evaluate_rule(rule, fams)
        assert not passed and observed > 0.05
        rule["value"] = 1.0
        assert slo_gate.evaluate_rule(rule, fams)[0]

    def test_sum_max_and_label_filter(self, slo_gate):
        fams = parse_prometheus_text(COUNTERS)
        assert slo_gate.evaluate_rule(
            {"metric": "errors", "agg": "sum", "op": "==", "value": 8},
            fams,
        )[0]
        assert slo_gate.evaluate_rule(
            {
                "metric": "errors",
                "labels": {"kind": "a"},
                "agg": "sum",
                "op": "==",
                "value": 3,
            },
            fams,
        )[0]
        assert slo_gate.evaluate_rule(
            {"metric": "depth", "agg": "max", "op": "<=", "value": 9},
            fams,
        )[0]

    def test_exposed_but_empty_counter_reads_zero(self, slo_gate):
        """A family with a HELP/TYPE header and no series is an
        instrument that never fired — 0, not a breach."""
        fams = parse_prometheus_text(COUNTERS)
        passed, observed, _ = slo_gate.evaluate_rule(
            {"metric": "quiet", "agg": "sum", "op": "==", "value": 0},
            fams,
        )
        assert passed and observed == 0.0

    def test_absent_family_breaches_unless_allowed(self, slo_gate):
        fams = parse_prometheus_text(COUNTERS)
        rule = {"metric": "nope", "agg": "sum", "op": "==", "value": 0}
        passed, observed, detail = slo_gate.evaluate_rule(rule, fams)
        assert not passed and observed is None and "absent" in detail
        rule["allow_missing"] = True
        assert slo_gate.evaluate_rule(rule, fams)[0]

    def test_subtract_deltas_counters_keeps_gauges(self, slo_gate):
        before = parse_prometheus_text(COUNTERS)
        after_text = COUNTERS.replace(
            'errors_total{kind="a"} 3', 'errors_total{kind="a"} 10'
        ).replace('depth{sub="1"} 4', 'depth{sub="1"} 2')
        delta = slo_gate.subtract(parse_prometheus_text(after_text), before)
        errors = {
            labels["kind"]: value
            for _n, labels, value in delta["errors"].samples
        }
        assert errors == {"a": 7.0, "b": 0.0}
        depth = {
            labels["sub"]: value
            for _n, labels, value in delta["depth"].samples
        }
        assert depth["1"] == 2.0  # gauge: level, not rate


def _tiny_config(**overrides):
    from esslivedata_tpu.harness import LoadConfig

    cfg = LoadConfig(
        streams=2,
        jobs_per_stream=1,
        subscribers=12,
        windows=10,
        warm_windows=2,
        events_per_window=256,
        pixels=1 << 10,
        queue_limit=4,
        wedge_every=5,
    )
    for key, value in overrides.items():
        setattr(cfg, key, value)
    return cfg


class TestHarnessRoundTrip:
    def test_clean_run_is_green(self, slo_gate):
        from esslivedata_tpu.harness import LoadHarness

        report = LoadHarness(_tiny_config()).run()
        assert report["parity_checks"] > 0
        assert report["parity_violations"] == 0
        assert report["gap_violations"] == 0
        assert report["coalesce_drops"] > 0  # wedged subs overflowed
        assert report["coalesce_recoveries"] > 0
        assert report["peak_queue_depth"] <= report["queue_limit"]

    def test_chaos_contained_and_control_goes_red(self, slo_gate):
        """One round trip at test scale: injected state loss is
        signaled (gate green on the invariants), and the SAME drill
        with the epoch signal disabled produces unsignaled resets the
        gate catches (exit-path semantics of scripts/slo_gate.py)."""
        from esslivedata_tpu.harness import ChaosSpec, LoadHarness

        chaos = ChaosSpec(
            seed=11, at={"tick_dispatch": frozenset({1, 7})}
        )
        report = LoadHarness(
            _tiny_config(chaos=chaos)
        ).run()
        assert report["chaos_injected"].get("tick_dispatch", 0) >= 1
        assert report["gap_violations"] == 0
        assert report["parity_violations"] == 0
        assert report["steady_compiles"] == 0
        assert report["healthz"]["status"] == "degraded"

        control = LoadHarness(
            _tiny_config(
                chaos=chaos, disable_containment="state_lost_signal"
            )
        ).run()
        assert control["gap_violations"] > 0
        # And the rule file translates that into a red gate.
        rules = slo_gate._load_rules(
            REPO / "scripts" / "slo_rules" / "smoke.json"
        )
        delta = slo_gate.subtract(
            parse_prometheus_text(control["scrape_after"]),
            parse_prometheus_text(control["scrape_before"]),
        )
        ok, results = slo_gate.evaluate(rules, delta)
        assert not ok
        breached = {r["name"] for r in results if not r["passed"]}
        assert "unsignaled_resets_zero" in breached

"""The SLO drill through a relay hop (harness/load.py + fleet/relay.py,
ADR 0121): parity and gap-discipline gated ACROSS the hop, and the
``relay_upstream_drop`` chaos site actually drilling the resync path."""

from __future__ import annotations

from esslivedata_tpu.harness import ChaosSpec, LoadConfig, LoadHarness
from esslivedata_tpu.harness.chaos import SITES


def _tiny(**overrides) -> LoadConfig:
    cfg = LoadConfig(
        streams=2,
        jobs_per_stream=1,
        subscribers=12,
        windows=12,
        warm_windows=2,
        events_per_window=512,
        pixels=1 << 10,
        queue_limit=4,
        seed=3,
    )
    for key, value in overrides.items():
        setattr(cfg, key, value)
    return cfg


def test_relay_upstream_drop_is_a_known_site():
    assert "relay_upstream_drop" in SITES


def test_drill_runs_through_one_relay_hop_with_parity():
    report = LoadHarness(_tiny()).run()
    assert report["relay_hops"] == 1
    assert report["relay_frames"] > 0
    assert report["parity_checks"] > 0
    assert report["parity_violations"] == 0
    assert report["gap_violations"] == 0


def test_relay_drop_chaos_resyncs_without_gap_violation():
    cfg = _tiny(
        chaos=ChaosSpec(
            seed=3,
            at={"relay_upstream_drop": frozenset({4})},
        )
    )
    report = LoadHarness(cfg).run()
    assert report["chaos_injected"] == {"relay_upstream_drop": 1}
    # The hop resynced (keyframe rebases at the relay's upstream
    # edge), and downstream discipline held: byte parity intact,
    # zero unsignaled resets across the hop.
    assert report["relay_resyncs"] >= 1
    assert report["parity_violations"] == 0
    assert report["gap_violations"] == 0


def test_direct_topology_still_available():
    report = LoadHarness(_tiny(relay_hops=0)).run()
    assert report["relay_hops"] == 0
    assert report["relay_frames"] == 0
    assert report["parity_violations"] == 0

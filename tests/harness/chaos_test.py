"""Chaos schedule semantics (ADR 0120): seeded determinism, explicit
fire ticks, the JobManager post-donation hook driving the REAL
note_state_lost containment, and the pipeline/broadcast stall hooks."""

from __future__ import annotations

import time

import numpy as np
import pytest

from esslivedata_tpu.harness.chaos import (
    CHAOS_INJECTIONS,
    ChaosError,
    ChaosSchedule,
    ChaosSpec,
)


class TestSchedule:
    def test_explicit_ticks_fire_exactly(self):
        sched = ChaosSchedule(ChaosSpec(at={"tick_dispatch": frozenset({1, 3})}))
        fires = [sched.fires("tick_dispatch") for _ in range(5)]
        assert fires == [False, True, False, True, False]
        assert sched.injected() == {"tick_dispatch": 2}
        assert sched.consultations() == {"tick_dispatch": 5}

    def test_sites_count_independently(self):
        sched = ChaosSchedule(
            ChaosSpec(at={"a": frozenset({0}), "b": frozenset({1})})
        )
        assert sched.fires("a") is True
        assert sched.fires("b") is False
        assert sched.fires("b") is True

    def test_rate_draws_are_seed_deterministic(self):
        def pattern(seed: int) -> list[bool]:
            sched = ChaosSchedule(
                ChaosSpec(seed=seed, rate={"slow_tick": 0.3})
            )
            return [sched.fires("slow_tick") for _ in range(64)]

        assert pattern(5) == pattern(5)
        assert pattern(5) != pattern(6)
        assert any(pattern(5)) and not all(pattern(5))

    def test_adding_a_site_never_shifts_another_sites_draws(self):
        one = ChaosSchedule(ChaosSpec(seed=9, rate={"a": 0.5}))
        two = ChaosSchedule(ChaosSpec(seed=9, rate={"a": 0.5, "b": 0.5}))
        assert [one.fires("a") for _ in range(32)] == [
            two.fires("a") for _ in range(32)
        ]

    def test_check_raises_chaos_error(self):
        sched = ChaosSchedule(ChaosSpec(at={"tick_dispatch": frozenset({0})}))
        with pytest.raises(ChaosError):
            sched.check("tick_dispatch")
        sched.check("tick_dispatch")  # consultation 1: quiet

    def test_fired_injections_count_into_the_registry(self):
        before = CHAOS_INJECTIONS.value(site="slow_tick")
        sched = ChaosSchedule(
            ChaosSpec(at={"slow_tick": frozenset({0})}, delay_s={"slow_tick": 0.0})
        )
        sched.maybe_delay("slow_tick")
        assert CHAOS_INJECTIONS.value(site="slow_tick") == before + 1

    def test_with_site_builds_on_a_spec(self):
        spec = ChaosSpec(seed=3).with_site("slow_tick", {2})
        assert spec.at["slow_tick"] == frozenset({2})


def _tiny_manager(k: int = 2):
    from esslivedata_tpu.config import JobId, WorkflowConfig, WorkflowSpec
    from esslivedata_tpu.core.job_manager import JobFactory, JobManager
    from esslivedata_tpu.workflows import WorkflowFactory
    from esslivedata_tpu.workflows.detector_view import (
        DetectorViewWorkflow,
        project_logical,
    )

    det = np.arange(16 * 16).reshape(16, 16)
    reg = WorkflowFactory()
    spec = WorkflowSpec(
        instrument="chaos", name="dv", source_names=["det0"]
    )
    reg.register_spec(spec).attach_factory(
        lambda *, source_name, params: DetectorViewWorkflow(
            projection=project_logical(det)
        )
    )
    mgr = JobManager(job_factory=JobFactory(reg), job_threads=1)
    for _ in range(k):
        mgr.schedule_job(
            WorkflowConfig(
                identifier=spec.identifier, job_id=JobId(source_name="det0")
            )
        )
    return mgr


def _staged(rng):
    from esslivedata_tpu.ops import EventBatch
    from esslivedata_tpu.preprocessors.event_data import StagedEvents

    pid = rng.integers(0, 256, 512).astype(np.int32)
    toa = rng.uniform(0, 7e7, 512).astype(np.float32)
    return StagedEvents(
        batch=EventBatch.from_arrays(pid, toa),
        first_timestamp=None,
        last_timestamp=None,
        n_chunks=1,
    )


class TestJobManagerHook:
    def test_tick_dispatch_fault_takes_the_state_lost_path(self):
        """The injected post-donation failure exercises the REAL
        containment: epoch bumps, jobs keep publishing (reset counts),
        next window recovers on the cached program."""
        from esslivedata_tpu.core.timestamp import Timestamp

        T = Timestamp.from_ns
        mgr = _tiny_manager()
        rng = np.random.default_rng(3)
        try:
            for w in range(2):  # both tick-program variants compile
                out = mgr.process_jobs(
                    {"det0": _staged(rng)}, start=T(0), end=T(w + 1)
                )
                assert len(out) == 2
            cum_before = float(out[0].outputs["counts_cumulative"].values)
            epoch_before = out[0].state_epoch
            # Steady consultation 0 fires: the dispatch runs (donating
            # the states), then "fails".
            mgr.set_chaos(
                ChaosSchedule(
                    ChaosSpec(at={"tick_dispatch": frozenset({0})})
                )
            )
            out = mgr.process_jobs(
                {"det0": _staged(rng)}, start=T(0), end=T(3)
            )
            assert len(out) == 2  # containment: every job published
            cur = float(out[0].outputs["counts_current"].values)
            cum = float(out[0].outputs["counts_cumulative"].values)
            assert cum == cur  # fresh state: the accumulation reset
            assert cum < cum_before
            assert out[0].state_epoch > epoch_before  # loss SIGNALED
            states = {str(s.state) for s in mgr.job_statuses()}
            assert "error" not in states
            # Recovery: the next window ticks again, accumulating.
            out = mgr.process_jobs(
                {"det0": _staged(rng)}, start=T(0), end=T(4)
            )
            assert (
                float(out[0].outputs["counts_cumulative"].values) > cum
            )
        finally:
            mgr.shutdown()


class TestBroadcastHook:
    def test_subscriptions_inherit_the_schedule_and_stall(self):
        from esslivedata_tpu.serving.broadcast import BroadcastServer

        hub = BroadcastServer(port=None)
        try:
            sched = ChaosSchedule(
                ChaosSpec(
                    at={"subscriber_stall": frozenset({0})},
                    delay_s={"subscriber_stall": 0.15},
                )
            )
            hub.set_chaos(sched)
            hub.publish_frame("j/out", b"frame-bytes", ("tok",))
            sub = hub.subscribe("j/out")
            t0 = time.perf_counter()
            blob = sub.next_blob(timeout=1.0)  # consultation 0: stalls
            stalled = time.perf_counter() - t0
            assert blob is not None
            assert stalled >= 0.15
            assert sched.injected() == {"subscriber_stall": 1}
        finally:
            hub.close()


class TestPipelineHook:
    def test_decode_stall_fires_and_windows_stay_ordered(self):
        """An injected decode-worker stall slows the pipeline but must
        never drop or reorder windows (the ADR 0111 ordering contract
        holds under chaos)."""
        from tests.core.ingest_pipeline_test import (
            make_manager,
            staged_window,
        )
        from esslivedata_tpu.core.ingest_pipeline import IngestPipeline
        from esslivedata_tpu.core.timestamp import Timestamp

        T = Timestamp.from_ns
        mgr = make_manager()
        published = []
        pipe = IngestPipeline(
            job_manager=mgr,
            decode=lambda payload: (payload, {}, None),
            publish=lambda results, end: published.append(end),
            depth=2,
        )
        sched = ChaosSchedule(
            ChaosSpec(
                at={"decode_stall": frozenset({1})},
                delay_s={"decode_stall": 0.2},
            )
        )
        pipe.set_chaos(sched)
        try:
            for i in range(4):
                pipe.submit(staged_window(i), start=T(0), end=T(i + 1))
            assert pipe.flush(timeout=30.0)
            assert sched.injected() == {"decode_stall": 1}
            assert published == [T(1), T(2), T(3), T(4)]
            assert pipe.failure is None
        finally:
            pipe.stop(drain=False)
            mgr.shutdown()

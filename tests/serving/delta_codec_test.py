"""Delta codec (serving/delta.py, ADR 0117): exact round-trip.

The codec's one promise: a subscriber applying keyframes and deltas in
order reconstructs every tick's frame BYTE-IDENTICALLY. These tests pin
the sparse/dense crossover, the epoch discipline (layout swap /
``state_lost`` → keyframe), the decoder's continuity rules (stale
deltas idempotent, gaps loud), and property-style round-trips over
randomized mutation patterns.
"""

from __future__ import annotations

import struct

import numpy as np
import pytest

from esslivedata_tpu.serving.delta import (
    HEADER_SIZE,
    DeltaDecoder,
    DeltaEncoder,
    DeltaError,
    decode_header,
    encode_delta,
    encode_keyframe,
)


def mutate(rng, frame: bytes, n_sites: int) -> bytes:
    out = bytearray(frame)
    for i in rng.integers(0, len(out), n_sites):
        out[i] = (out[i] + 1) % 256
    return bytes(out)


class TestBlobFormat:
    def test_keyframe_header_and_payload(self):
        blob = encode_keyframe(b"abcdef", epoch=3, seq=7)
        header = decode_header(blob)
        assert header.keyframe
        assert header.epoch == 3
        assert header.seq == 7
        assert header.frame_len == 6
        assert blob[HEADER_SIZE:] == b"abcdef"

    def test_bad_magic_and_truncation_raise(self):
        with pytest.raises(DeltaError):
            decode_header(b"XX" + b"\x00" * 20)
        with pytest.raises(DeltaError):
            decode_header(b"LD\x01")

    def test_unsupported_version_raises(self):
        blob = bytearray(encode_keyframe(b"x", epoch=0, seq=0))
        blob[2] = 99
        with pytest.raises(DeltaError):
            decode_header(bytes(blob))


class TestRoundTrip:
    def test_sparse_mutations_round_trip_byte_identical(self):
        rng = np.random.default_rng(1)
        frame = rng.integers(0, 256, 40_000).astype(np.uint8).tobytes()
        encoder, decoder = DeltaEncoder(), DeltaDecoder()
        assert decoder.apply(encoder.encode(frame, epoch=0, seq=0)) == frame
        for seq in range(1, 30):
            frame = mutate(rng, frame, int(rng.integers(1, 60)))
            blob = encoder.encode(frame, epoch=0, seq=seq)
            header = decode_header(blob)
            assert not header.keyframe
            assert len(blob) < len(frame)
            assert decoder.apply(blob) == frame

    def test_identical_frame_is_a_tiny_delta(self):
        frame = bytes(10_000)
        encoder, decoder = DeltaEncoder(), DeltaDecoder()
        decoder.apply(encoder.encode(frame, epoch=0, seq=0))
        blob = encoder.encode(frame, epoch=0, seq=1)
        assert not decode_header(blob).keyframe
        assert len(blob) == HEADER_SIZE + 4  # zero runs
        assert decoder.apply(blob) == frame

    def test_dense_fallback_emits_keyframe(self):
        rng = np.random.default_rng(2)
        a = rng.integers(0, 256, 5000).astype(np.uint8).tobytes()
        b = rng.integers(0, 256, 5000).astype(np.uint8).tobytes()
        blob = encode_delta(a, b, epoch=0, seq=1)
        assert decode_header(blob).keyframe
        # A delta blob is never larger than the keyframe for the tick.
        assert len(blob) == HEADER_SIZE + len(b)

    def test_length_change_forces_keyframe(self):
        blob = encode_delta(b"short", b"rather longer", epoch=0, seq=1)
        assert decode_header(blob).keyframe

    def test_crossover_scan_never_exceeds_keyframe_size(self):
        """Property: across the sparse→dense spectrum the emitted blob
        round-trips exactly and never beats the keyframe bound."""
        rng = np.random.default_rng(3)
        base = rng.integers(0, 256, 8192).astype(np.uint8).tobytes()
        for n_sites in (0, 1, 8, 64, 512, 4096, 8192):
            cur = mutate(rng, base, n_sites) if n_sites else base
            blob = encode_delta(base, cur, epoch=0, seq=1)
            assert len(blob) <= HEADER_SIZE + len(cur)
            decoder = DeltaDecoder()
            decoder.apply(encode_keyframe(base, epoch=0, seq=0))
            assert decoder.apply(blob) == cur

    def test_randomized_stream_round_trip(self):
        """Property-style: random walk of mutation densities, epoch
        bumps and frame-length changes — decoder output equals the
        published frame at every step."""
        rng = np.random.default_rng(4)
        encoder, decoder = DeltaEncoder(), DeltaDecoder()
        frame = rng.integers(0, 256, 2048).astype(np.uint8).tobytes()
        epoch = 0
        for seq in range(60):
            roll = rng.random()
            if roll < 0.1:
                epoch += 1  # generation change
            if roll < 0.05:
                frame = (
                    rng.integers(0, 256, int(rng.integers(512, 4096)))
                    .astype(np.uint8)
                    .tobytes()
                )
            else:
                frame = mutate(rng, frame, int(rng.integers(0, 300)))
            blob = encoder.encode(frame, epoch=epoch, seq=seq)
            assert decoder.apply(blob) == frame
            assert decoder.epoch == epoch


class TestEpochDiscipline:
    def test_epoch_bump_forces_keyframe(self):
        encoder = DeltaEncoder()
        frame = bytes(1000)
        encoder.encode(frame, epoch=0, seq=0)
        # Same bytes, new epoch (state_lost reset to zeros): keyframe.
        blob = encoder.encode(frame, epoch=1, seq=1)
        assert decode_header(blob).keyframe
        assert decode_header(blob).epoch == 1

    def test_delta_across_epochs_rejected_by_decoder(self):
        a, b = bytes(1000), b"\x01" + bytes(999)
        decoder = DeltaDecoder()
        decoder.apply(encode_keyframe(a, epoch=0, seq=0))
        blob = encode_delta(a, b, epoch=1, seq=1)
        assert not decode_header(blob).keyframe
        with pytest.raises(DeltaError, match="epoch"):
            decoder.apply(blob)

    def test_encoder_keyframe_reemits_current_state(self):
        encoder = DeltaEncoder()
        assert encoder.keyframe() is None
        rng = np.random.default_rng(5)
        frame = rng.integers(0, 256, 500).astype(np.uint8).tobytes()
        encoder.encode(frame, epoch=2, seq=9)
        blob = encoder.keyframe()
        header = decode_header(blob)
        assert header.keyframe and header.epoch == 2 and header.seq == 9
        decoder = DeltaDecoder()
        assert decoder.apply(blob) == frame


class TestDecoderContinuity:
    def _pair(self):
        rng = np.random.default_rng(6)
        a = rng.integers(0, 256, 2000).astype(np.uint8).tobytes()
        b = mutate(rng, a, 10)
        c = mutate(rng, b, 10)
        return a, b, c

    def test_delta_before_keyframe_raises(self):
        a, b, _c = self._pair()
        with pytest.raises(DeltaError, match="before any keyframe"):
            DeltaDecoder().apply(encode_delta(a, b, epoch=0, seq=1))

    def test_stale_delta_is_idempotent_noop(self):
        """The attach race: keyframe seq N from the cache, then the
        in-flight fan-out's delta seq N — held frame unchanged."""
        a, b, _c = self._pair()
        decoder = DeltaDecoder()
        decoder.apply(encode_keyframe(b, epoch=0, seq=1))
        out = decoder.apply(encode_delta(a, b, epoch=0, seq=1))
        assert out == b
        assert decoder.seq == 1

    def test_seq_gap_raises(self):
        a, b, c = self._pair()
        decoder = DeltaDecoder()
        decoder.apply(encode_keyframe(a, epoch=0, seq=0))
        with pytest.raises(DeltaError, match="gap"):
            decoder.apply(encode_delta(b, c, epoch=0, seq=2))

    def test_corrupt_run_bounds_raise(self):
        a, b, _c = self._pair()
        decoder = DeltaDecoder()
        decoder.apply(encode_keyframe(a, epoch=0, seq=0))
        blob = bytearray(encode_delta(a, b, epoch=0, seq=1))
        # Point the first run's offset past the frame end.
        struct.pack_into("<I", blob, HEADER_SIZE + 4, len(a) + 100)
        with pytest.raises(DeltaError):
            decoder.apply(bytes(blob))

"""Broadcast plane (serving/broadcast.py, ADR 0117).

Hub semantics (attach keyframes, shared-encode fan-out, slow-subscriber
coalescing with bounded memory and keyframe recovery), the SSE/HTTP
surface over real sockets, QoS and the ``livedata_serving_*``
telemetry families.
"""

from __future__ import annotations

import base64
import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from esslivedata_tpu.serving import (
    BroadcastServer,
    DeltaDecoder,
    decode_header,
)
from esslivedata_tpu.serving.broadcast import SERVING_COALESCE_DROPS
from esslivedata_tpu.telemetry import REGISTRY


def frames(n: int, size: int = 4000, seed: int = 0):
    rng = np.random.default_rng(seed)
    frame = rng.integers(0, 256, size).astype(np.uint8).tobytes()
    out = [frame]
    for _ in range(n - 1):
        arr = bytearray(out[-1])
        for i in rng.integers(0, size, 25):
            arr[i] = (arr[i] + 1) % 256
        out.append(bytes(arr))
    return out


class TestHub:
    def test_attach_gets_cached_keyframe_then_deltas(self):
        hub = BroadcastServer(port=None)
        try:
            series = frames(4)
            hub.publish_frame("s", series[0], token="t")
            sub = hub.subscribe("s")
            decoder = DeltaDecoder()
            blob = sub.next_blob(1.0)
            assert decode_header(blob).keyframe
            assert decoder.apply(blob) == series[0]
            for cur in series[1:]:
                hub.publish_frame("s", cur, token="t")
                blob = sub.next_blob(1.0)
                assert not decode_header(blob).keyframe
                assert decoder.apply(blob) == cur
        finally:
            hub.close()

    def test_attach_before_first_publish_waits_for_keyframe(self):
        hub = BroadcastServer(port=None)
        try:
            sub = hub.subscribe("s")
            assert sub.next_blob(0.05) is None
            hub.publish_frame("s", b"first", token="t")
            blob = sub.next_blob(1.0)
            assert decode_header(blob).keyframe
            assert DeltaDecoder().apply(blob) == b"first"
        finally:
            hub.close()

    def test_every_subscriber_gets_the_same_shared_blob(self):
        hub = BroadcastServer(port=None)
        try:
            series = frames(3)
            hub.publish_frame("s", series[0], token="t")
            subs = [hub.subscribe("s") for _ in range(5)]
            for sub in subs:
                sub.next_blob(1.0)  # attach keyframe
            hub.publish_frame("s", series[1], token="t")
            blobs = {sub.next_blob(1.0) for sub in subs}
            # One encode per tick, shared across subscribers.
            assert len(blobs) == 1
        finally:
            hub.close()

    def test_unsubscribe_stops_delivery(self):
        hub = BroadcastServer(port=None)
        try:
            hub.publish_frame("s", b"f0", token="t")
            sub = hub.subscribe("s")
            sub.next_blob(1.0)
            hub.unsubscribe(sub)
            hub.publish_frame("s", b"f1" * 100, token="t")
            assert sub.next_blob(0.05) is None
        finally:
            hub.close()

    def test_epoch_bump_reaches_subscriber_as_keyframe(self):
        hub = BroadcastServer(port=None)
        try:
            series = frames(3)
            hub.publish_frame("s", series[0], token="a")
            sub = hub.subscribe("s")
            decoder = DeltaDecoder()
            decoder.apply(sub.next_blob(1.0))
            hub.publish_frame("s", series[1], token="a")
            decoder.apply(sub.next_blob(1.0))
            # Token change (layout swap / state_lost): keyframe, epoch+1.
            hub.publish_frame("s", series[2], token="b")
            blob = sub.next_blob(1.0)
            header = decode_header(blob)
            assert header.keyframe and header.epoch == 1
            assert decoder.apply(blob) == series[2]
        finally:
            hub.close()

    def test_drop_stream_forgets_cache_and_encoder(self):
        hub = BroadcastServer(port=None)
        try:
            hub.publish_frame("s", b"f0", token="t")
            hub.drop_stream("s")
            assert hub.cache.latest("s") is None
            # Re-publish restarts at epoch 0/seq 0 with a keyframe.
            hub.publish_frame("s", b"f1", token="t")
            cached = hub.cache.latest("s")
            assert (cached.epoch, cached.seq) == (0, 0)
        finally:
            hub.close()

    def test_drop_job_forgets_every_stream_of_that_job_only(self):
        hub = BroadcastServer(port=None)
        try:
            hub.publish_frame("job1:u/current", b"a", token="t")
            hub.publish_frame("job1:u/cumulative", b"b", token="t")
            hub.publish_frame("job2:v/current", b"c", token="t")
            assert hub.drop_job("job1:u") == 2
            assert set(hub.cache.streams()) == {"job2:v/current"}
        finally:
            hub.close()


class TestSlowSubscriberCoalescing:
    def test_bounded_memory_and_keyframe_recovery(self):
        """The satellite acceptance: a consumer that never drains keeps
        a queue bounded at ``queue_limit``, loses intermediate deltas
        (counted as coalesce drops), and on its next drain recovers the
        EXACT latest frame from the resync keyframe."""
        limit = 4
        hub = BroadcastServer(port=None, queue_limit=limit)
        try:
            drops0 = SERVING_COALESCE_DROPS.total()
            series = frames(50, size=2000, seed=3)
            hub.publish_frame("s", series[0], token="t")
            sub = hub.subscribe("s")
            for cur in series[1:]:
                hub.publish_frame("s", cur, token="t")
            assert sub.depth() <= limit
            assert SERVING_COALESCE_DROPS.total() > drops0
            decoder = DeltaDecoder()
            out = None
            while (blob := sub.next_blob(0.05)) is not None:
                out = decoder.apply(blob)
            assert out == series[-1]
        finally:
            hub.close()

    def test_fast_subscriber_unaffected_by_slow_peer(self):
        hub = BroadcastServer(port=None, queue_limit=3)
        try:
            series = frames(30, size=2000, seed=4)
            hub.publish_frame("s", series[0], token="t")
            fast = hub.subscribe("s")
            slow = hub.subscribe("s")
            decoder = DeltaDecoder()
            decoder.apply(fast.next_blob(1.0))
            for cur in series[1:]:
                hub.publish_frame("s", cur, token="t")
                assert decoder.apply(fast.next_blob(1.0)) == cur
            assert slow.depth() <= 3
        finally:
            hub.close()

    def test_publish_never_blocks_on_wedged_consumer(self):
        """The publish hook must complete in bounded time no matter how
        wedged a consumer is — enqueue is put_nowait + coalesce, never
        a blocking put."""
        hub = BroadcastServer(port=None, queue_limit=2)
        try:
            hub.publish_frame("s", b"0" * 1000, token="t")
            hub.subscribe("s")  # never drained
            start = time.monotonic()
            for i in range(200):
                hub.publish_frame("s", bytes([i % 256]) * 1000, token="t")
            assert time.monotonic() - start < 5.0
        finally:
            hub.close()


class TestQos:
    def test_counts_and_pressure(self):
        hub = BroadcastServer(port=None, queue_limit=4)
        try:
            assert hub.qos() == {"subscribers": 0, "queue_pressure": 0.0}
            hub.publish_frame("s", b"f0", token="t")
            sub = hub.subscribe("s")
            hub.subscribe("other")
            qos = hub.qos()
            assert qos["subscribers"] == 2
            assert qos["queue_pressure"] == pytest.approx(0.25)  # keyframe
            sub.next_blob(1.0)
            assert hub.qos()["queue_pressure"] == 0.0
        finally:
            hub.close()


class TestTelemetry:
    def test_serving_families_present_and_labeled(self):
        hub = BroadcastServer(port=None, name="testsrv")
        try:
            hub.publish_frame("s", b"f0" * 50, token="t")
            sub = hub.subscribe("s")
            sub.next_blob(1.0)
            families = {f.name: f for f in REGISTRY.collect()}
            assert "livedata_serving_frames" in families
            assert "livedata_serving_bytes" in families
            assert "livedata_serving_coalesce_drops" in families
            subs_family = families["livedata_serving_subscribers"]
            rows = {
                dict(s.labels).get("stream"): s.value
                for s in subs_family.samples
                if dict(s.labels).get("server") == "testsrv"
            }
            assert rows.get("s") == 1
            assert rows.get("all") == 1
            depth_family = families["livedata_serving_queue_depth"]
            assert any(
                dict(s.labels).get("server") == "testsrv"
                for s in depth_family.samples
            )
        finally:
            hub.close()

    def test_collector_unregisters_on_close(self):
        hub = BroadcastServer(port=None, name="closing")
        hub.publish_frame("s", b"f0", token="t")
        hub.subscribe("s")
        hub.close()
        families = [
            s
            for f in REGISTRY.collect()
            if f.name == "livedata_serving_subscribers"
            for s in f.samples
            if dict(s.labels).get("server") == "closing"
        ]
        assert not families


class TestHttpSurface:
    @pytest.fixture()
    def hub(self):
        hub = BroadcastServer(port=0, host="127.0.0.1")
        yield hub
        hub.close()

    def _get(self, hub, path, timeout=5.0):
        return urllib.request.urlopen(
            f"http://127.0.0.1:{hub.port}{path}", timeout=timeout
        )

    def test_results_index(self, hub):
        hub.publish_frame("job1:u/current", b"x" * 100, token="t")
        with self._get(hub, "/results") as response:
            index = json.loads(response.read())
        (row,) = index["streams"]
        assert row["job"] == "job1:u"
        assert row["output"] == "current"
        assert row["frame_bytes"] == 100
        assert row["path"] == "/streams/job1:u/current"

    def test_sse_keyframe_then_delta(self, hub):
        series = frames(2, size=3000, seed=7)
        hub.publish_frame("j:u/out", series[0], token="t")
        response = self._get(hub, "/streams/j:u/out", timeout=10)

        def publish_later():
            time.sleep(0.2)
            hub.publish_frame("j:u/out", series[1], token="t")

        threading.Thread(target=publish_later, daemon=True).start()
        decoder = DeltaDecoder()
        events = []
        kind = None
        for raw in response:
            line = raw.decode().rstrip("\n")
            if line.startswith("event: "):
                kind = line[len("event: "):]
            elif line.startswith("data: "):
                blob = base64.b64decode(line[len("data: "):])
                events.append((kind, decoder.apply(blob)))
                if len(events) == 2:
                    break
        response.close()
        assert events[0] == ("keyframe", series[0])
        assert events[1] == ("delta", series[1])

    def test_unknown_stream_404s_with_hint(self, hub):
        with pytest.raises(urllib.error.HTTPError) as excinfo:
            self._get(hub, "/streams/none/such")
        assert excinfo.value.code == 404
        assert "results" in json.loads(excinfo.value.read())["error"]

    def test_unknown_path_404s(self, hub):
        with pytest.raises(urllib.error.HTTPError) as excinfo:
            self._get(hub, "/nope")
        assert excinfo.value.code == 404

    def test_subscriber_cleanup_after_disconnect(self, hub):
        hub.publish_frame("j:u/out", b"f" * 50, token="t")
        response = self._get(hub, "/streams/j:u/out", timeout=10)
        # Read the attach keyframe, then hang up.
        for raw in response:
            if raw.startswith(b"data: "):
                break
        response.close()
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            if hub.qos()["subscribers"] == 0:
                break
            # The handler notices the closed socket on its next write
            # attempt; publishes provoke one.
            hub.publish_frame("j:u/out", b"g" * 50, token="t")
            time.sleep(0.05)
        assert hub.qos()["subscribers"] == 0


class TestResumeAndHeartbeat:
    """Last-Event-ID resume + idle heartbeats (ADR 0121 satellite)."""

    def test_resume_same_epoch_serves_deltas_from_ring(self):
        hub = BroadcastServer(port=None)
        try:
            series = frames(4)
            for cur in series:
                hub.publish_frame("s", cur, token="t")
            # A client that decoded seq 0 reconnects: the ring covers
            # seqs 0..3, so the gap arrives as deltas — no keyframe.
            from esslivedata_tpu.serving.delta import encode_keyframe

            sub = hub.subscribe("s", resume=(0, 0))
            decoder = DeltaDecoder()
            decoder.apply(encode_keyframe(series[0], epoch=0, seq=0))
            got = []
            while sub.depth() > 0:
                blob = sub.next_blob(1.0)
                assert not decode_header(blob).keyframe, (
                    "resume within the ring must not replay a keyframe"
                )
                got.append(decoder.apply(blob))
            assert got[-1] == series[-1]
            assert decoder.seq == 3
        finally:
            hub.close()

    def test_resume_at_head_enqueues_nothing(self):
        hub = BroadcastServer(port=None)
        try:
            series = frames(2)
            hub.publish_frame("s", series[0], token="t")
            sub = hub.subscribe("s", resume=(0, 0))
            assert sub.depth() == 0
            # Live publishes then apply directly to the held frame.
            hub.publish_frame("s", series[1], token="t")
            blob = sub.next_blob(1.0)
            assert not decode_header(blob).keyframe
        finally:
            hub.close()

    def test_resume_epoch_mismatch_falls_back_to_keyframe(self):
        hub = BroadcastServer(port=None)
        try:
            series = frames(2)
            hub.publish_frame("s", series[0], token="t1")
            hub.publish_frame("s", series[1], token="t2")  # epoch bump
            sub = hub.subscribe("s", resume=(0, 0))
            blob = sub.next_blob(1.0)
            header = decode_header(blob)
            assert header.keyframe and header.epoch == 1
        finally:
            hub.close()

    def test_resume_older_than_ring_falls_back_to_keyframe(self):
        from esslivedata_tpu.serving import ResultCache

        hub = BroadcastServer(cache=ResultCache(ring=2), port=None)
        try:
            series = frames(6)
            for cur in series:
                hub.publish_frame("s", cur, token="t")
            sub = hub.subscribe("s", resume=(0, 0))  # ring holds 4, 5
            blob = sub.next_blob(1.0)
            assert decode_header(blob).keyframe
        finally:
            hub.close()

    def test_sse_id_carries_epoch_and_seq(self):
        hub = BroadcastServer(port=0, host="127.0.0.1")
        try:
            series = frames(1)
            hub.publish_frame("j:u/out", series[0], token="t")
            response = urllib.request.urlopen(
                f"http://127.0.0.1:{hub.port}/streams/j:u/out", timeout=10
            )
            for raw in response:
                line = raw.decode().rstrip("\n")
                if line.startswith("id: "):
                    boot, epoch_s, seq_s = line[len("id: "):].split(":")
                    assert boot == hub.boot
                    assert (int(epoch_s), int(seq_s)) == (0, 0)
                    break
            response.close()
        finally:
            hub.close()

    def test_socket_level_last_event_id_resume_without_keyframe(self):
        """The relay reconnect path over a REAL socket: a client that
        echoes the last SSE id back resumes on deltas when the epoch
        still matches — and detects liveness from heartbeats."""
        hub = BroadcastServer(port=0, host="127.0.0.1", heartbeat_s=0.2)
        try:
            series = frames(5)
            hub.publish_frame("j:u/out", series[0], token="t")
            # First connection: read the attach keyframe + its id.
            response = urllib.request.urlopen(
                f"http://127.0.0.1:{hub.port}/streams/j:u/out", timeout=10
            )
            decoder = DeltaDecoder()
            last_id = None
            for raw in response:
                line = raw.decode().rstrip("\n")
                if line.startswith("id: "):
                    last_id = line[len("id: "):]
                elif line.startswith("data: "):
                    decoder.apply(base64.b64decode(line[len("data: "):]))
                    break
            response.close()
            assert last_id == f"{hub.boot}:0:0"
            # Frames published while disconnected...
            for cur in series[1:3]:
                hub.publish_frame("j:u/out", cur, token="t")
            # ...resume with Last-Event-ID: deltas only, no keyframe.
            request = urllib.request.Request(
                f"http://127.0.0.1:{hub.port}/streams/j:u/out",
                headers={"Last-Event-ID": last_id},
            )
            response = urllib.request.urlopen(request, timeout=10)
            kinds, got = [], None
            saw_heartbeat = False
            for raw in response:
                line = raw.decode().rstrip("\n")
                if line.startswith("event: "):
                    kinds.append(line[len("event: "):])
                elif line.startswith(": keepalive"):
                    saw_heartbeat = True
                    break
                elif line.startswith("data: "):
                    got = decoder.apply(
                        base64.b64decode(line[len("data: "):])
                    )
            response.close()
            assert kinds == ["delta", "delta"]
            assert got == series[2]
            # Idle heartbeat arrived well under the client's patience.
            assert saw_heartbeat
        finally:
            hub.close()

    def test_resume_outcomes_count_into_registry(self):
        from esslivedata_tpu.serving.broadcast import SERVING_RESUMES

        hub = BroadcastServer(port=None)
        try:
            series = frames(3)
            for cur in series:
                hub.publish_frame("s", cur, token="t")
            delta0 = SERVING_RESUMES.value(result="delta")
            current0 = SERVING_RESUMES.value(result="current")
            key0 = SERVING_RESUMES.value(result="keyframe")
            hub.subscribe("s", resume=(0, 1))
            hub.subscribe("s", resume=(0, 2))
            hub.subscribe("s", resume=(9, 0))
            assert SERVING_RESUMES.value(result="delta") == delta0 + 1
            assert SERVING_RESUMES.value(result="current") == current0 + 1
            assert SERVING_RESUMES.value(result="keyframe") == key0 + 1
        finally:
            hub.close()

    def test_federated_index_appends_peer_rows(self):
        hub = BroadcastServer(port=0, host="127.0.0.1", name="local")
        try:
            hub.publish_frame("j:u/out", b"x" * 64, token="t")
            hub.set_index_peers(
                lambda: [
                    {
                        "stream": "peer:j/out",
                        "node": "peer-1",
                        "path": "/streams/peer:j/out",
                        "url": "http://peer:5012/streams/peer:j/out",
                        "hop": 1,
                    },
                    # A stream the local hub already serves must not be
                    # duplicated by federation.
                    {"stream": "j:u/out", "node": "peer-1"},
                ]
            )
            with urllib.request.urlopen(
                f"http://127.0.0.1:{hub.port}/results", timeout=5
            ) as response:
                rows = json.loads(response.read())["streams"]
            by_stream = {row["stream"]: row for row in rows}
            assert by_stream["j:u/out"]["node"] == "local"
            assert by_stream["j:u/out"]["hop"] == 0
            assert by_stream["peer:j/out"]["url"].startswith("http://peer")
            assert len(rows) == 2
        finally:
            hub.close()

    def test_peer_index_failure_degrades_to_local_rows(self):
        hub = BroadcastServer(port=0, host="127.0.0.1")
        try:
            hub.publish_frame("j:u/out", b"x" * 64, token="t")

            def broken():
                raise OSError("peer down")

            hub.set_index_peers(broken)
            with urllib.request.urlopen(
                f"http://127.0.0.1:{hub.port}/results", timeout=5
            ) as response:
                rows = json.loads(response.read())["streams"]
            assert [row["stream"] for row in rows] == ["j:u/out"]
        finally:
            hub.close()

    def test_resume_overflow_coalesces_to_a_real_keyframe(self):
        """A multi-delta resume into a tiny queue must coalesce to a
        KEYFRAME of the latest tick — enqueuing a later delta instead
        would hand the client an unsignaled seq gap."""
        hub = BroadcastServer(port=None, queue_limit=1)
        try:
            series = frames(5)
            for cur in series:
                hub.publish_frame("s", cur, token="t")
            # Gap of 4 deltas into a 1-slot queue: everything past the
            # first enqueue overflows and coalesces.
            sub = hub.subscribe("s", resume=(0, 0))
            assert sub.depth() == 1
            blob = sub.next_blob(1.0)
            header = decode_header(blob)
            assert header.keyframe and header.seq == 4
            assert DeltaDecoder().apply(blob) == series[-1]
        finally:
            hub.close()

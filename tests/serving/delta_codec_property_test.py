"""Property sweeps for the delta codec (ADR 0117/0124): arbitrary
frame streams round-trip byte-identically, an epoch change ALWAYS
produces a keyframe (the serving half of the JGL204 epoch discipline
the protocol pass model-checks — ``encoder.keyframes_on_epoch_change``
is the same guard the ``epoch`` model binds), and a sequence gap can
never splice: a non-keyframe blob the decoder cannot prove contiguous
raises, it never patches.

Hypothesis is optional tooling (not baked into every environment);
the module skips wholesale where it is absent — the deterministic
codec suite (``delta_codec_test.py``) still covers the fixed cases.
"""

from __future__ import annotations

import pytest

pytest.importorskip("hypothesis")

from hypothesis import given, settings, strategies as st  # noqa: E402

from esslivedata_tpu.serving.delta import (  # noqa: E402
    DeltaDecoder,
    DeltaEncoder,
    DeltaError,
    decode_header,
)

#: (frame bytes, bump-epoch-before-this-frame) stream steps. Frame
#: lengths vary freely: the encoder's dense/keyframe fallbacks are part
#: of the contract under test, not something to engineer around.
_STREAMS = st.lists(
    st.tuples(st.binary(min_size=0, max_size=96), st.booleans()),
    min_size=1,
    max_size=24,
)


def _drive(steps):
    """Run one encoder/decoder pair over the stream; yields
    (frame, epoch, bumped, blob, reconstructed)."""
    enc, dec = DeltaEncoder(), DeltaDecoder()
    epoch = 0
    for seq, (frame, bump) in enumerate(steps):
        if bump:
            epoch += 1
        blob = enc.encode(frame, epoch=epoch, seq=seq)
        yield frame, epoch, bump, blob, dec.apply(blob)


@settings(max_examples=200, deadline=None)
@given(_STREAMS)
def test_any_stream_round_trips_byte_identical(steps):
    for frame, _epoch, _bump, _blob, got in _drive(steps):
        assert got == frame


@settings(max_examples=200, deadline=None)
@given(_STREAMS)
def test_epoch_change_always_keyframes(steps):
    # The JGL204 discipline at the wire: a delta across an epoch bump
    # would bridge two unrelated accumulations. The encoder must never
    # emit one — the protocol model assumes exactly this guard.
    for _frame, _epoch, bump, blob, _got in _drive(steps):
        if bump:
            assert decode_header(blob).keyframe


@settings(max_examples=200, deadline=None)
@given(_STREAMS)
def test_decoder_tracks_encoder_epoch_and_seq(steps):
    enc, dec = DeltaEncoder(), DeltaDecoder()
    epoch = 0
    for seq, (frame, bump) in enumerate(steps):
        if bump:
            epoch += 1
        dec.apply(enc.encode(frame, epoch=epoch, seq=seq))
        assert dec.epoch == epoch
        assert dec.seq == seq


@settings(max_examples=200, deadline=None)
@given(
    st.binary(min_size=64, max_size=64),
    st.lists(
        st.tuples(st.integers(0, 63), st.integers(0, 255)),
        min_size=1,
        max_size=4,
    ),
)
def test_seq_gap_never_splices(base, edits):
    """Drop one tick from a delta stream: the decoder must either see
    a self-contained keyframe (dense fallback — fine, it rebases) or
    REFUSE the gapped delta. Silently patching a non-contiguous delta
    is the splice failure JGL203/JGL204 model at the protocol layer."""
    frames = [base]
    for offset, value in edits:
        prev = bytearray(frames[-1])
        prev[offset] = value
        frames.append(bytes(prev))
    enc, dec = DeltaEncoder(), DeltaDecoder()
    dec.apply(enc.encode(frames[0], epoch=0, seq=0))
    # Encode the middle of the stream but never deliver it...
    for seq, frame in enumerate(frames[1:-1], start=1):
        enc.encode(frame, epoch=0, seq=seq)
    # ...then deliver the final blob with the gap in front of it.
    blob = enc.encode(frames[-1], epoch=0, seq=len(frames) - 1)
    if len(frames) == 2 or decode_header(blob).keyframe:
        assert dec.apply(blob) == frames[-1]
    else:
        with pytest.raises(DeltaError):
            dec.apply(blob)


@settings(max_examples=100, deadline=None)
@given(st.binary(min_size=8, max_size=64), st.binary(min_size=8, max_size=64))
def test_stale_delta_returns_held_frame_unchanged(old, new):
    # The attach race: a keyframe from the cache may already cover an
    # in-flight delta; replaying it must be a no-op, never an error.
    enc, dec = DeltaEncoder(), DeltaDecoder()
    dec.apply(enc.encode(old, epoch=0, seq=0))
    stale = enc.encode(new, epoch=0, seq=1)
    held = dec.apply(enc.encode(new, epoch=0, seq=1))
    if not decode_header(stale).keyframe:
        assert dec.apply(stale) == held

"""Fan-out tier through the REAL JobManager path (ADR 0117).

The acceptance contract: a subscriber's reconstructed frames are
BYTE-IDENTICAL to the da00 wire the Kafka sink serializer produces for
the same publish — keyframe and delta paths both — for detector-view,
monitor and a Q-family (SANS I(Q)) workflow; epoch bumps fire on
reset/``state_lost`` generation changes; and the processor hook feeds
the plane exactly the results it feeds the sink.
"""

from __future__ import annotations

import numpy as np

from esslivedata_tpu.config import JobId, WorkflowConfig, WorkflowSpec
from esslivedata_tpu.core.job import Job, JobResult
from esslivedata_tpu.core.job_manager import (
    JobCommand,
    JobFactory,
    JobManager,
)
from esslivedata_tpu.core.timestamp import Timestamp
from esslivedata_tpu.kafka.da00_compat import dataarray_to_da00
from esslivedata_tpu.kafka.wire import decode_da00, encode_da00
from esslivedata_tpu.ops import EventBatch
from esslivedata_tpu.preprocessors import (
    DetectorEvents,
    MonitorEvents,
    ToEventBatch,
)
from esslivedata_tpu.preprocessors.event_data import StagedEvents
from esslivedata_tpu.serving import (
    DeltaDecoder,
    ServingPlane,
    decode_header,
    stream_key,
)
from esslivedata_tpu.workflows import WorkflowFactory
from esslivedata_tpu.workflows.detector_view import (
    DetectorViewWorkflow,
    project_logical,
)
from esslivedata_tpu.workflows.monitor_workflow import MonitorWorkflow
from esslivedata_tpu.workflows.sans import SansIQParams, SansIQWorkflow

T = Timestamp.from_ns


def staged(pid, toa) -> StagedEvents:
    return StagedEvents(
        batch=EventBatch.from_arrays(
            np.asarray(pid), np.asarray(toa, np.float32)
        ),
        first_timestamp=None,
        last_timestamp=None,
        n_chunks=1,
    )


def staged_monitor(n: int) -> StagedEvents:
    acc = ToEventBatch(min_bucket=16)
    acc.add(
        T(0),
        MonitorEvents(
            time_of_arrival=np.linspace(1e6, 6e7, n).astype(np.float32)
        ),
    )
    return acc.get()


def sink_wire(result, ts) -> dict[str, bytes]:
    """stream -> the EXACT bytes the Kafka sink serializer publishes."""
    job = f"{result.job_id.source_name}:{result.job_id.job_number}"
    return {
        stream_key(job, key.output_name): encode_da00(
            key.to_string(), ts.ns, dataarray_to_da00(da)
        )
        for key, da in zip(
            result.keys(), result.outputs.values(), strict=True
        )
    }


class _Checker:
    """One decoding subscriber per stream, asserting byte identity."""

    def __init__(self, plane: ServingPlane) -> None:
        self.plane = plane
        self.subs: dict[str, tuple] = {}
        self.saw_delta = False
        self.saw_keyframe = False

    def expect(self, references: dict[str, bytes], window: int) -> None:
        for stream, reference in references.items():
            entry = self.subs.get(stream)
            if entry is None:
                entry = self.subs[stream] = (
                    self.plane.server.subscribe(stream),
                    DeltaDecoder(),
                )
            sub, decoder = entry
            got = None
            while (blob := sub.next_blob(timeout=2.0)) is not None:
                header = decode_header(blob)
                if header.keyframe:
                    self.saw_keyframe = True
                else:
                    self.saw_delta = True
                got = decoder.apply(blob)
                if got == reference:
                    break
            assert got == reference, (
                f"window {window}: reconstruction != sink wire for "
                f"{stream}"
            )


class TestByteIdentityThroughJobManager:
    def _manager(self, makes, stream="det0", aux=None):
        created = []
        reg = WorkflowFactory()
        identifiers = []
        for i, make in enumerate(makes):
            spec = WorkflowSpec(
                instrument="test",
                name=f"fanout{i}",
                source_names=[stream],
                aux_source_names={
                    key: [value] for key, value in (aux or {}).items()
                },
            )

            def factory(*, source_name, params, _make=make):
                wf = _make()
                created.append(wf)
                return wf

            reg.register_spec(spec).attach_factory(factory)
            identifiers.append(spec.identifier)
        mgr = JobManager(job_factory=JobFactory(reg), job_threads=2)
        for identifier in identifiers:
            mgr.schedule_job(
                WorkflowConfig(
                    identifier=identifier,
                    job_id=JobId(source_name=stream),
                    aux_source_names=aux or {},
                )
            )
        return mgr, created

    def test_detector_view_and_monitor_keyframe_and_delta_paths(self):
        det = np.arange(144).reshape(12, 12)
        mgr, _ = self._manager(
            [
                lambda: DetectorViewWorkflow(
                    projection=project_logical(det)
                ),
                lambda: MonitorWorkflow(),
            ]
        )
        plane = ServingPlane(port=None)
        checker = _Checker(plane)
        rng = np.random.default_rng(11)
        try:
            for w in range(5):
                pid = rng.integers(-5, 150, 2500).astype(np.int64)
                toa = rng.uniform(-1e6, 8e7, 2500).astype(np.float32)
                results = mgr.process_jobs(
                    {"det0": staged(pid, toa)}, start=T(0), end=T(w + 1)
                )
                assert len(results) == 2
                ts = T(1000 + w)
                plane.publish_results(results, ts)
                for result in results:
                    checker.expect(sink_wire(result, ts), w)
            # Both wire paths exercised, both byte-identical.
            assert checker.saw_keyframe and checker.saw_delta
        finally:
            mgr.shutdown()
            plane.close()

    def test_q_family_workflow_byte_identical(self):
        ny = nx = 8
        xs = np.linspace(-0.5, 0.5, nx)
        gx, gy = np.meshgrid(xs, xs)
        positions = np.stack(
            [gx.reshape(-1), gy.reshape(-1), np.full(ny * nx, 5.0)],
            axis=1,
        )
        pixel_ids = np.arange(1, ny * nx + 1)
        mgr, _ = self._manager(
            [
                lambda: SansIQWorkflow(
                    positions=positions,
                    pixel_ids=pixel_ids,
                    params=SansIQParams(q_bins=20),
                    primary_stream="larmor_detector",
                    monitor_streams={"monitor_1"},
                )
            ],
            stream="larmor_detector",
            aux={"monitor": "monitor_1"},
        )
        plane = ServingPlane(port=None)
        checker = _Checker(plane)
        rng = np.random.default_rng(12)
        try:
            for w in range(4):
                pid = rng.integers(1, 65, 800).astype(np.int32)
                toa = rng.uniform(1e6, 7e7, 800).astype(np.float32)
                results = mgr.process_jobs(
                    {
                        "larmor_detector": staged(pid, toa),
                        "monitor_1": staged_monitor(400),
                    },
                    start=T(0),
                    end=T(w + 1),
                )
                assert len(results) == 1
                ts = T(2000 + w)
                plane.publish_results(results, ts)
                checker.expect(sink_wire(results[0], ts), w)
            assert checker.saw_delta
        finally:
            mgr.shutdown()
            plane.close()

    def test_remove_command_drops_the_jobs_streams(self):
        """Job churn must not pin dead streams: the JobManager's
        retire observer (wired by the processor; here directly) drops
        the removed job's cache entries so /results stops listing it
        and its frame ring frees."""
        det = np.arange(64).reshape(8, 8)
        mgr, _ = self._manager(
            [lambda: DetectorViewWorkflow(projection=project_logical(det))]
        )
        plane = ServingPlane(port=None)
        mgr.set_retire_observer(plane.drop_job)
        rng = np.random.default_rng(14)
        try:
            pid = rng.integers(0, 64, 500).astype(np.int64)
            toa = rng.uniform(0, 7e7, 500).astype(np.float32)
            results = mgr.process_jobs(
                {"det0": staged(pid, toa)}, start=T(0), end=T(1)
            )
            plane.publish_results(results, T(100))
            assert plane.cache.streams()
            assert mgr.handle_command(JobCommand(action="remove")) == 1
            assert plane.cache.streams() == {}
        finally:
            mgr.shutdown()
            plane.close()

    def test_reset_bumps_epoch_and_forces_keyframe(self):
        det = np.arange(64).reshape(8, 8)
        mgr, _ = self._manager(
            [lambda: DetectorViewWorkflow(projection=project_logical(det))]
        )
        plane = ServingPlane(port=None)
        checker = _Checker(plane)
        rng = np.random.default_rng(13)
        try:
            def window(w, ts_ns):
                pid = rng.integers(0, 64, 1000).astype(np.int64)
                toa = rng.uniform(0, 7e7, 1000).astype(np.float32)
                results = mgr.process_jobs(
                    {"det0": staged(pid, toa)}, start=T(0), end=T(w)
                )
                ts = T(ts_ns)
                plane.publish_results(results, ts)
                return results, ts

            for w in range(3):
                results, ts = window(w + 1, 3000 + w)
                checker.expect(sink_wire(results[0], ts), w)
            epochs_before = {
                stream: decoder.epoch
                for stream, (_, decoder) in checker.subs.items()
            }
            assert mgr.handle_command(JobCommand(action="reset")) == 1
            results, ts = window(10, 3100)
            references = sink_wire(results[0], ts)
            for stream, reference in references.items():
                sub, decoder = checker.subs[stream]
                blob = sub.next_blob(timeout=2.0)
                header = decode_header(blob)
                assert header.keyframe, (
                    f"{stream}: post-reset frame was not a keyframe"
                )
                assert header.epoch == epochs_before[stream] + 1
                assert decoder.apply(blob) == reference
        finally:
            mgr.shutdown()
            plane.close()


class TestStateEpochSignals:
    def test_job_clear_and_note_state_lost_bump(self):
        class _Workflow:
            def accumulate(self, data):
                pass

            def finalize(self):
                return {}

            def clear(self):
                pass

        from esslivedata_tpu.config.workflow_spec import WorkflowId

        job = Job(
            job_id=JobId(source_name="s"),
            workflow_id=WorkflowId(
                instrument="i", namespace="reduction", name="w", version=1
            ),
            workflow=_Workflow(),
        )
        assert job.state_epoch == 0
        job.clear()
        assert job.state_epoch == 1
        job.note_state_lost()
        assert job.state_epoch == 2

    def test_job_result_carries_state_epoch(self):
        class _Workflow:
            def accumulate(self, data):
                pass

            def finalize(self):
                return {}

            def clear(self):
                pass

        from esslivedata_tpu.config.workflow_spec import WorkflowId

        job = Job(
            job_id=JobId(source_name="s"),
            workflow_id=WorkflowId(
                instrument="i", namespace="reduction", name="w", version=1
            ),
            workflow=_Workflow(),
        )
        job.note_state_lost()
        assert job.get().state_epoch == 1

    def test_state_epoch_alone_forces_keyframe_through_plane(self):
        """Identical layout, identical bytes possible — the state_epoch
        component of the token must still force keyframe + epoch bump
        (the ``state_lost`` contract: a delta across a rebuilt
        accumulator would splice unrelated generations)."""
        from esslivedata_tpu.config.workflow_spec import ResultKey, WorkflowId
        from esslivedata_tpu.utils.labeled import DataArray, Variable

        wid = WorkflowId(
            instrument="i", namespace="reduction", name="w", version=1
        )
        job_id = JobId(source_name="s")
        da = DataArray(
            Variable(np.arange(8, dtype=np.float64), ("x",), None),
            name="out",
        )

        def result(epoch):
            return JobResult(
                job_id=job_id,
                workflow_id=wid,
                outputs={"out": da},
                start=None,
                end=None,
                state_epoch=epoch,
            )

        plane = ServingPlane(port=None)
        try:
            plane.publish_results([result(0)], T(1))
            stream = next(iter(plane.cache.streams()))
            sub = plane.server.subscribe(stream)
            decoder = DeltaDecoder()
            decoder.apply(sub.next_blob(2.0))
            epoch0 = decoder.epoch
            plane.publish_results([result(0)], T(2))
            assert not decode_header(
                blob := sub.next_blob(2.0)
            ).keyframe
            decoder.apply(blob)
            plane.publish_results([result(1)], T(3))
            blob = sub.next_blob(2.0)
            assert decode_header(blob).keyframe
            decoder.apply(blob)
            assert decoder.epoch == epoch0 + 1
        finally:
            plane.close()


class TestPlaneReuse:
    def test_closed_plane_is_not_reused(self):
        from esslivedata_tpu.serving import get_or_create_plane
        from esslivedata_tpu.serving.plane import _planes

        _planes.pop(0, None)
        first = get_or_create_plane(0, name="reuse-a")
        try:
            assert get_or_create_plane(0, name="reuse-a") is first
        finally:
            first.close()
        second = get_or_create_plane(0, name="reuse-a")
        try:
            # A closed plane's listener is dead: the table must build a
            # fresh one instead of silently running dark.
            assert second is not first
            assert second.port is not None
        finally:
            second.close()
            _planes.pop(0, None)

    def test_reuse_with_different_settings_warns(self, caplog):
        import logging

        from esslivedata_tpu.serving import get_or_create_plane
        from esslivedata_tpu.serving.plane import _planes

        _planes.pop(0, None)
        plane = get_or_create_plane(0, name="warn-a")
        try:
            with caplog.at_level(
                logging.WARNING, logger="esslivedata_tpu.serving.plane"
            ):
                assert get_or_create_plane(0, name="warn-b") is plane
            assert any(
                "different settings" in rec.message
                for rec in caplog.records
            )
        finally:
            plane.close()
            _planes.pop(0, None)


class TestProcessorHook:
    def test_publish_results_mirrors_sink_and_is_contained(self):
        """The OrchestratingProcessor hands the plane the same results
        it hands the sink — and a raising fan-out must not break the
        publish path."""
        from esslivedata_tpu.core.orchestrating_processor import (
            OrchestratingProcessor,
        )
        from esslivedata_tpu.core.fakes import (
            FakeMessageSink,
            FakeMessageSource,
        )
        from esslivedata_tpu.core.message_batcher import NaiveMessageBatcher
        from esslivedata_tpu.core.preprocessor import PreprocessorFactory

        class _Factory(PreprocessorFactory):
            def make_preprocessor(self, stream):
                return None

        class _RecordingFanout:
            def __init__(self, raise_on_publish=False):
                self.calls = []
                self.raise_on_publish = raise_on_publish

            def publish_results(self, results, timestamp):
                if self.raise_on_publish:
                    raise RuntimeError("fanout down")
                self.calls.append((list(results), timestamp))

            def qos(self):
                return {"subscribers": 0, "queue_pressure": 0.0}

        for raising in (False, True):
            fanout = _RecordingFanout(raise_on_publish=raising)
            sink = FakeMessageSink()
            processor = OrchestratingProcessor(
                source=FakeMessageSource(),
                sink=sink,
                preprocessor_factory=_Factory(),
                job_manager=JobManager(job_threads=1),
                batcher=NaiveMessageBatcher(),
                instrument="test",
                service_name=f"fanout-hook-{raising}",
                result_fanout=fanout,
            )
            from esslivedata_tpu.config.workflow_spec import WorkflowId
            from esslivedata_tpu.utils.labeled import DataArray, Variable

            result = JobResult(
                job_id=JobId(source_name="s"),
                workflow_id=WorkflowId(
                    instrument="i",
                    namespace="reduction",
                    name="w",
                    version=1,
                ),
                outputs={
                    "out": DataArray(
                        Variable(
                            np.arange(4, dtype=np.float64), ("x",), None
                        ),
                        name="out",
                    )
                },
                start=None,
                end=None,
            )
            processor._publish_results([result], T(5))
            assert sink.messages, "sink publish must happen either way"
            if not raising:
                assert len(fanout.calls) == 1
                results, ts = fanout.calls[0]
                assert results[0] is result
                assert ts.ns == 5
            processor.finalize()

"""ResultCache (serving/result_cache.py, ADR 0117): epoch/ring/locking.

The satellite fix this PR carries: the cache snapshot must follow the
ONE-acquisition discipline PR 9 gave ``LinkMonitor.stats()`` — a
scraping subscriber can never pair a frame with the wrong epoch tag.
The lock hammer at the bottom pins that under a real writer/reader
race.
"""

from __future__ import annotations

import struct
import threading

import pytest

from esslivedata_tpu.serving import ResultCache


class TestEpochSemantics:
    def test_same_token_keeps_epoch_and_advances_seq(self):
        cache = ResultCache()
        first = cache.put("s", b"f0", token=("layout", 0))
        second = cache.put("s", b"f1", token=("layout", 0))
        assert (first.epoch, first.seq) == (0, 0)
        assert (second.epoch, second.seq) == (0, 1)

    def test_token_change_bumps_epoch_and_resets_ring(self):
        cache = ResultCache(ring=4)
        cache.put("s", b"f0", token=("layout-a", 0))
        cache.put("s", b"f1", token=("layout-a", 0))
        bumped = cache.put("s", b"f2", token=("layout-b", 0))
        assert bumped.epoch == 1
        # Frames across a generation boundary must not look contiguous.
        assert [c.frame for c in cache.recent("s")] == [b"f2"]

    def test_state_epoch_component_bumps_too(self):
        cache = ResultCache()
        cache.put("s", b"f0", token=(0, "layout"))
        bumped = cache.put("s", b"f0", token=(1, "layout"))
        assert bumped.epoch == 1

    def test_streams_are_independent(self):
        cache = ResultCache()
        cache.put("a", b"x", token=1)
        cache.put("a", b"y", token=2)  # epoch 1
        first_b = cache.put("b", b"z", token=1)
        assert first_b.epoch == 0 and first_b.seq == 0


class TestRingAndIndex:
    def test_ring_is_bounded_oldest_dropped(self):
        cache = ResultCache(ring=3)
        for i in range(6):
            cache.put("s", bytes([i]), token="t")
        assert [c.frame for c in cache.recent("s")] == [
            b"\x03",
            b"\x04",
            b"\x05",
        ]
        assert cache.latest("s").frame == b"\x05"
        assert cache.latest("s").seq == 5

    def test_latest_none_for_unknown_stream(self):
        assert ResultCache().latest("nope") is None

    def test_streams_index_lists_latest(self):
        cache = ResultCache()
        cache.put("a", b"aa", token=1)
        cache.put("b", b"bb", token=1)
        index = cache.streams()
        assert set(index) == {"a", "b"}
        assert index["a"].frame == b"aa"

    def test_invalidate_drops_one_or_all(self):
        cache = ResultCache()
        cache.put("a", b"aa", token=1)
        cache.put("b", b"bb", token=1)
        cache.invalidate("a")
        assert cache.latest("a") is None
        assert cache.latest("b") is not None
        cache.invalidate()
        assert cache.streams() == {}

    def test_ring_must_hold_at_least_one(self):
        with pytest.raises(ValueError):
            ResultCache(ring=0)


class TestEpochFrameCoherence:
    def test_lock_hammer_frame_never_pairs_with_wrong_epoch(self):
        """A writer bumps the token (→ epoch) on every put, encoding
        the expected epoch INSIDE the frame; concurrent readers assert
        every snapshot's frame decodes to exactly its epoch tag. The
        pre-fix shape (latest() reading frame and epoch in separate
        acquisitions) fails this within a few thousand iterations."""
        cache = ResultCache(ring=2)
        stop = threading.Event()
        errors: list[str] = []

        def writer():
            i = 0
            while not stop.is_set():
                # token == i, changes every put → epoch == i.
                cache.put("s", struct.pack("<I", i), token=i)
                i += 1

        def reader():
            while not stop.is_set():
                cached = cache.latest("s")
                if cached is None:
                    continue
                (embedded,) = struct.unpack("<I", cached.frame)
                if embedded != cached.epoch:
                    errors.append(
                        f"frame says epoch {embedded}, tag says "
                        f"{cached.epoch}"
                    )
                    return

        threads = [threading.Thread(target=writer)] + [
            threading.Thread(target=reader) for _ in range(3)
        ]
        for t in threads:
            t.start()
        try:
            import time

            time.sleep(0.5)
        finally:
            stop.set()
            for t in threads:
                t.join(timeout=5.0)
        assert not errors, errors[0]

    def test_seq_epoch_pairing_under_mixed_tokens(self):
        """Same hammer, alternating token flips mid-stream: seq resets
        never tear against the epoch (each put's CachedFrame return and
        later latest() reads agree)."""
        cache = ResultCache(ring=4)
        stop = threading.Event()
        errors: list[str] = []

        def writer():
            i = 0
            while not stop.is_set():
                token = i // 7  # epoch bumps every 7 puts
                cached = cache.put(
                    "s", struct.pack("<II", token, i), token=token
                )
                if cached.epoch != token:
                    errors.append(
                        f"put returned epoch {cached.epoch} for token "
                        f"{token}"
                    )
                    return
                i += 1

        def reader():
            while not stop.is_set():
                cached = cache.latest("s")
                if cached is None:
                    continue
                token, _i = struct.unpack("<II", cached.frame)
                if token != cached.epoch:
                    errors.append(
                        f"frame token {token} != epoch {cached.epoch}"
                    )
                    return

        threads = [threading.Thread(target=writer)] + [
            threading.Thread(target=reader) for _ in range(2)
        ]
        for t in threads:
            t.start()
        try:
            import time

            time.sleep(0.3)
        finally:
            stop.set()
            for t in threads:
                t.join(timeout=5.0)
        assert not errors, errors[0]

"""Version display formatting (reference tests/format_version_test.py)."""

import pytest

from esslivedata_tpu import format_version


@pytest.mark.parametrize(
    ("raw", "expected"),
    [
        ("26.4.2", "26.4.2"),
        ("1.0.0", "1.0.0"),
        ("0.0.0", "0.0.0"),
        ("26.4.2.dev0+g68b165851.d20260410", "26.4.2-dev (68b16585)"),
        ("1.2.3.dev42+gabcdef012.d20250101", "1.2.3-dev (abcdef01)"),
        ("not-a-version", "not-a-version"),
    ],
)
def test_format_version(raw, expected):
    assert format_version(raw) == expected

"""Mutation guards for the protocol pass (ADR 0124 acceptance): gut
each modeled source guard in a SCRATCH copy of the real tree (via
``source_overrides`` — disk is never touched) and assert the checker
goes red with the exact JGL2xx code and a minimal counterexample.

This is the pass's reason to exist, tested end to end: the binding
probe must notice the gutted guard (fact -> False), the weakened model
must reach the failure the guard prevents, and the finding must anchor
at the weakened function with a humanly-short transition trace. A
mutation that stays green means the model never depended on that
guard — the checker is decorative for it.
"""

from __future__ import annotations

import numpy as np
import pytest

from tools.graftlint.protocol import run_protocol
from tools.graftlint.protocol.engine import _repo_root


def _mutated(path: str, old: str, new: str) -> dict[str, str]:
    source = (_repo_root() / path).read_text(encoding="utf-8")
    assert old in source, f"mutation target drifted: {old!r} not in {path}"
    return {path: source.replace(old, new)}


def _findings_for(overrides: dict[str, str]):
    report = run_protocol(codec=False, source_overrides=overrides)
    assert report.errors == []
    return report.findings


def _the_one_finding(overrides: dict[str, str], rule: str):
    findings = _findings_for(overrides)
    matching = [f for f in findings if f.rule == rule]
    assert matching, (
        f"mutation did not flip {rule}; findings: {findings}"
    )
    return matching[0]


# -- JGL202: delete the checkpoint file fsync -------------------------------


def test_deleting_checkpoint_fsync_is_jgl202():
    finding = _the_one_finding(
        _mutated(
            "src/esslivedata_tpu/durability/checkpoint.py",
            "os.fsync(fh.fileno())",
            "pass  # fsync deleted by mutation",
        ),
        "JGL202",
    )
    assert finding.path == "src/esslivedata_tpu/durability/checkpoint.py"
    assert "counterexample: init ->" in finding.message
    assert "crash" in finding.message
    # The finding names the gutted guard, not just the model.
    assert "guard not found in source" in finding.message


# -- JGL204: gut the state-loss epoch bump ----------------------------------


def test_gutting_state_lost_epoch_bump_is_jgl204():
    finding = _the_one_finding(
        _mutated(
            "src/esslivedata_tpu/core/job.py",
            "self.state_epoch += 1\n        HEALTH.note_state_lost()",
            "HEALTH.note_state_lost()",
        ),
        "JGL204",
    )
    assert finding.path == "src/esslivedata_tpu/core/job.py"
    assert "counterexample: init ->" in finding.message


# -- JGL201: short-circuit the fleet ownership compare ----------------------


def test_owns_without_self_compare_is_jgl201():
    finding = _the_one_finding(
        _mutated(
            "src/esslivedata_tpu/fleet/assignment.py",
            "owned = self.owner(stream, fuse_tag) == self.self_id",
            "owned = True",
        ),
        "JGL201",
    )
    assert finding.path == "src/esslivedata_tpu/fleet/assignment.py"
    # An unfiltered fleet violates single-ownership immediately: the
    # minimal witness is the initial state itself.
    assert "counterexample: init" in finding.message
    # Two replicas accumulating one group is the modeled failure.
    assert "processed by" in finding.message


# -- JGL203: drop the relay's boot-id check ---------------------------------


def test_dropping_relay_boot_check_is_jgl203():
    finding = _the_one_finding(
        _mutated(
            "src/esslivedata_tpu/fleet/relay.py",
            "and boot != self._last_boot",
            "and False",
        ),
        "JGL203",
    )
    assert finding.path == "src/esslivedata_tpu/fleet/relay.py"
    assert "counterexample: init ->" in finding.message


# -- JGL205: a codec that cannot round-trip ---------------------------------


class _LossyWorkflow:
    """dump_state drops the dtype, restore rebuilds float64: the
    re-assembled program's avals drift — exactly what JGL205 exists to
    catch before a restart streams the checkpoint."""

    def __init__(self) -> None:
        self.state = np.zeros(8, dtype=np.float32)

    def state_fingerprint(self) -> str:
        return "lossy"

    def dump_state(self) -> dict:
        return {"state": self.state.tolist()}

    def restore_state(self, arrays: dict) -> bool:
        self.state = np.asarray(arrays["state"], dtype=np.float64)
        return True


class _FakeSpec:
    family = "lossy_fixture"

    def source_location(self):
        return "tests/tools/protocol_mutation_test.py", 1

    @staticmethod
    def make_workflow(variant: str) -> _LossyWorkflow:
        return _LossyWorkflow()

    @staticmethod
    def assemble(wf: _LossyWorkflow):
        from esslivedata_tpu.harness.tick_contract import (
            TickProgram,
            TickProgramBuild,
        )

        program = TickProgram(
            label="publish",
            fn=lambda s: {"counts": s},
            args=(wf.state,),
            state_positions=(0,),
            staged_positions=(),
            outputs={"counts": wf.state},
        )
        return TickProgramBuild(
            programs=(program,), key_material=(str(wf.state.dtype),)
        )


def test_lossy_codec_spec_is_jgl205():
    report = run_protocol(codec_specs=[_FakeSpec()])
    findings = [f for f in report.findings if f.rule == "JGL205"]
    assert findings, report.findings
    assert any("round-trip" in f.message for f in findings)


def test_spec_without_factored_build_is_jgl205():
    class _Opaque:
        family = "opaque_fixture"
        make_workflow = None
        assemble = None

        def source_location(self):
            return "tests/tools/protocol_mutation_test.py", 1

    report = run_protocol(codec_specs=[_Opaque()])
    findings = [f for f in report.findings if f.rule == "JGL205"]
    assert findings
    assert "make_workflow" in findings[0].message


# -- control: the unmutated tree is clean -----------------------------------


def test_unmutated_tree_is_clean():
    report = run_protocol(codec=False)
    assert report.findings == []
    assert report.errors == []


# -- every modeled guard class has a mutation above -------------------------


def test_mutation_coverage_spans_all_model_rules():
    # JGL201..JGL205 each have a seeded mutation in this file (the
    # ISSUE's acceptance bar); this meta-assert keeps the set honest
    # if a rule is added without its mutation.
    import inspect
    import sys

    source = inspect.getsource(sys.modules[__name__])
    for rule in ("JGL201", "JGL202", "JGL203", "JGL204", "JGL205"):
        assert f'"{rule}"' in source
